"""BM25F keyword search over the searchable map buckets.

Reference: inverted/bm25_searcher.go:77 (BM25F over map buckets with term
frequencies, WAND-style term iteration :99), config defaults k1=1.2 b=0.75
(entities/models InvertedIndexConfig.BM25).

Scoring: classic BM25 with per-property weights (BM25F flavor): for query
term t and doc d with term frequency tf in property p of length L_p:

    idf(t)  = ln(1 + (N - df + 0.5) / (df + 0.5))
    s(t, d) = idf(t) * tf' * (k1 + 1) / (tf' + k1 * (1 - b + b * L/avgL))

with tf' summed over weighted properties.
"""

from __future__ import annotations

import heapq
import math
import struct
from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.entities.schema import DataType
from weaviate_tpu.inverted.analyzer import tokenize
from weaviate_tpu.inverted.index import InvertedIndex, length_bucket, searchable_bucket
from weaviate_tpu.index.interface import AllowList

DEFAULT_K1 = 1.2
DEFAULT_B = 0.75


class BM25Searcher:
    def __init__(self, inverted: InvertedIndex, class_def,
                 config: Optional[dict] = None, gen_fn=None):
        self.inverted = inverted
        self.class_def = class_def
        bm = (config or {}).get("bm25") or {}
        self.k1 = float(bm.get("k1", DEFAULT_K1))
        self.b = float(bm.get("b", DEFAULT_B))
        # per-prop document-length table cache, keyed by the shard's write
        # generation (gen_fn): rebuilding it costs a full map_get + sum over
        # EVERY doc, which used to dominate query time (~40 ms at 50k docs)
        self._gen_fn = gen_fn
        self._len_cache: dict[str, tuple] = {}
        self._count_cache: Optional[tuple] = None

    def _doc_count(self) -> int:
        """inverted.doc_count() materializes the full roaring doc set —
        ~1.6 ms at 50k docs; cache it per write generation like the length
        tables."""
        gen = self._gen_fn() if self._gen_fn is not None else None
        if gen is not None and self._count_cache is not None \
                and self._count_cache[0] == gen:
            return self._count_cache[1]
        c = self.inverted.doc_count()
        # cache only if no write started meanwhile: the writer bumps the
        # generation BEFORE mutating, so a count read mid-write must not be
        # pinned under the new generation
        if gen is not None and (self._gen_fn() == gen):
            self._count_cache = (gen, c)
        return c

    def _prop_lengths(self, prop_name: str, lb):
        """-> (sorted doc-id u64 array, f32 lengths aligned to it, avg).
        Cached per write generation when gen_fn is wired (the Shard path);
        standalone users pay the rebuild each call."""
        gen = self._gen_fn() if self._gen_fn is not None else None
        if gen is not None:
            hit = self._len_cache.get(prop_name)
            if hit is not None and hit[0] == gen:
                return hit[1], hit[2], hit[3]
        lengths = lb.map_get(b"len") if lb is not None else {}
        if lengths:
            docs = np.frombuffer(b"".join(lengths.keys()), dtype="<u8")
            vals = np.frombuffer(b"".join(lengths.values()),
                                 dtype="<u4").astype(np.float32)
            order = np.argsort(docs)
            docs, vals = docs[order], vals[order]
            avg = float(vals.mean())
        else:
            docs = np.empty(0, dtype=np.uint64)
            vals = np.empty(0, dtype=np.float32)
            avg = 1.0
        # same mid-write guard as _doc_count: never pin a table read while
        # a write (which bumps the generation first) is in flight
        if gen is not None and self._gen_fn() == gen:
            self._len_cache[prop_name] = (gen, docs, vals, avg)
        return docs, vals, avg

    def _searchable_props(self, properties: Optional[Sequence[str]]) -> list[tuple[str, float]]:
        """-> [(prop, weight)]; supports "prop^2" boost syntax."""
        out = []
        if properties:
            for p in properties:
                if "^" in p:
                    name, w = p.split("^", 1)
                    out.append((name, float(w)))
                else:
                    out.append((p, 1.0))
        else:
            for prop in self.class_def.properties:
                pt = prop.primitive_type()
                if (
                    pt is not None
                    and pt.base in (DataType.TEXT, DataType.STRING)
                    and prop.index_searchable
                ):
                    out.append((prop.name, 1.0))
        return out

    def search(
        self,
        query: str,
        limit: int,
        properties: Optional[Sequence[str]] = None,
        allow_list: Optional[AllowList] = None,
        additional_explanations: bool = False,
    ) -> list[tuple[int, float, Optional[dict]]]:
        """-> [(doc_id, score, explain|None)] sorted by score desc."""
        props = self._searchable_props(properties)
        n_docs = max(self._doc_count(), 1)
        scores: dict[int, float] = {}
        explains: dict[int, dict] = {}

        # collect per-term postings across properties
        terms: dict[str, float] = {}
        for prop_name, weight in props:
            prop = self.class_def.get_property(prop_name)
            tk = prop.tokenization if prop else "word"
            for t in tokenize(tk, query):
                terms.setdefault(t, 0.0)

        for prop_name, weight in props:
            sb = self.inverted.store.bucket(searchable_bucket(prop_name))
            lb = self.inverted.store.bucket(length_bucket(prop_name))
            if sb is None:
                continue
            len_docs, len_vals, avg_len = self._prop_lengths(prop_name, lb)
            for term in terms:
                postings = sb.map_get(term.encode("utf-8"))
                if not postings:
                    continue
                df = len(postings)
                idf = math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
                # vectorized posting scoring: the per-entry Python loop with
                # three struct.unpacks used to dominate high-df terms
                doc_ids = np.frombuffer(b"".join(postings.keys()), dtype="<u8")
                tf = np.frombuffer(b"".join(postings.values()),
                                   dtype="<f4").astype(np.float64)
                if allow_list is not None:
                    keep = allow_list.contains_array(doc_ids)
                    if not keep.any():
                        continue
                    doc_ids, tf = doc_ids[keep], tf[keep]
                if len_docs.size:
                    pos = np.searchsorted(len_docs, doc_ids)
                    pos_c = np.clip(pos, 0, len_docs.size - 1)
                    found = len_docs[pos_c] == doc_ids
                    length = np.where(found, len_vals[pos_c], avg_len)
                else:
                    length = np.full(doc_ids.shape, avg_len)
                denom = tf + self.k1 * (1 - self.b + self.b * (length / avg_len))
                s = weight * idf * tf * (self.k1 + 1) / denom
                get = scores.get
                for d, sv in zip(doc_ids.tolist(), s.tolist()):
                    scores[d] = get(d, 0.0) + sv
                if additional_explanations:
                    for d, tfv, lv in zip(doc_ids.tolist(), tf.tolist(),
                                          length.tolist()):
                        explains.setdefault(d, {})[f"BM25F_{term}_frequency"] = tfv
                        explains[d][f"BM25F_{term}_propLength"] = lv

        top = heapq.nlargest(limit, scores.items(), key=lambda kv: (kv[1], -kv[0]))
        return [(d, s, explains.get(d) if additional_explanations else None) for d, s in top]
