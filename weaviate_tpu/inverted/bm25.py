"""BM25F keyword search over the searchable map buckets.

Reference: inverted/bm25_searcher.go:77 (BM25F over map buckets with term
frequencies, WAND-style term iteration :99), config defaults k1=1.2 b=0.75
(entities/models InvertedIndexConfig.BM25).

Scoring: classic BM25 with per-property weights (BM25F flavor): for query
term t and doc d with term frequency tf in property p of length L_p:

    idf(t)  = ln(1 + (N - df + 0.5) / (df + 0.5))
    s(t, d) = idf(t) * tf' * (k1 + 1) / (tf' + k1 * (1 - b + b * L/avgL))

with tf' summed over weighted properties.

Engine design (round 5): the reference walks doc-at-a-time WAND iterators
(bm25_searcher.go:99) — a shape that is pure pointer-chasing and would run
at Python speed here. This implementation keeps WAND's *pruning math* but
vectorizes the traversal term-at-a-time (the MaxScore family):

1. postings decode straight to (doc_ids u64, tf f32) numpy arrays with no
   per-entry Python (storage/lsm.py map_get_arrays; ~13x the dict decode at
   df=4k), LRU-cached per (prop, term) under the shard write generation;
2. scoring units (one per prop x term) are processed in DESCENDING
   upper-bound order; a unit is fully scored (vectorized) only while an
   unseen doc could still reach the current top-k floor theta — i.e. while
   sum of remaining upper bounds >= theta; after that, units only LOOK UP
   their contributions to existing candidates via binary search
   (O(k log df) instead of O(df));
3. theta is the k-th best partial total so far, which only grows, and
   suffix upper-bound sums only shrink, so the switch is one-way and every
   candidate's final score is complete — the pruned top-k is float-exact
   identical to exhaustive scoring (tested in tests/test_bm25_wand.py).

The per-unit upper bound is the L->0, tf->tf_max envelope:
    ub = weight * idf * tf_max * (k1 + 1) / (tf_max + k1 * (1 - b))
which is monotone in tf and maximal at zero length — a valid (loose) bound
for every posting in the unit at the cost of one numpy max().
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.entities.schema import DataType
from weaviate_tpu.inverted.analyzer import tokenize
from weaviate_tpu.inverted.index import InvertedIndex, length_bucket, searchable_bucket
from weaviate_tpu.index.interface import AllowList

DEFAULT_K1 = 1.2
DEFAULT_B = 0.75

# decoded posting arrays kept per searcher: byte-budgeted LRU (an entry
# for a stopword-grade term on a 1M-doc shard is ~12 MB — counting entries
# instead of bytes could pin GBs)
_POST_CACHE_MAX_BYTES = 64 * 1024 * 1024


class _Unit:
    """One (property, term) scoring unit: doc-sorted postings + the length
    table of its property, scored lazily (fully or at given positions)."""

    __slots__ = ("ids", "tf", "idf", "weight", "len_docs", "len_vals",
                 "avg_len", "ub", "term", "k1", "b", "dense", "prop")

    def __init__(self, ids, tf, idf, weight, len_docs, len_vals, avg_len,
                 k1, b, term, prop=""):
        self.ids = ids
        self.tf = tf
        self.idf = idf
        self.weight = weight
        self.len_docs = len_docs
        self.len_vals = len_vals
        self.avg_len = avg_len
        self.k1 = k1
        self.b = b
        self.term = term
        self.prop = prop
        # doc ids 0..n-1 with no gaps (the common append-only shard): length
        # lookup is a direct index, no binary search
        self.dense = bool(len_docs.size) and len_docs[0] == 0 and \
            int(len_docs[-1]) == len_docs.size - 1
        tf_max = float(tf.max())
        self.ub = weight * idf * tf_max * (k1 + 1) / (tf_max + k1 * (1 - b))

    def _lengths(self, docs):
        # f64 throughout: f32 length math would drag the whole denominator
        # to f32 under numpy's weak-scalar promotion (L values are u32
        # counts, exact in either dtype)
        if self.dense:
            idx = docs.astype(np.int64)
            # max(), not idx[-1]: the explanations path passes score-ordered
            # (unsorted) doc ids
            if idx.size and int(idx.max()) < self.len_vals.size:
                return self.len_vals[idx].astype(np.float64)
            out = np.full(docs.shape, self.avg_len, dtype=np.float64)
            inb = idx < self.len_vals.size
            out[inb] = self.len_vals[idx[inb]]
            return out
        if self.len_docs.size:
            pos = np.clip(np.searchsorted(self.len_docs, docs), 0,
                          self.len_docs.size - 1)
            found = self.len_docs[pos] == docs
            return np.where(found, self.len_vals[pos],
                            self.avg_len).astype(np.float64)
        return np.full(docs.shape, self.avg_len, dtype=np.float64)

    def _score(self, docs, tf):
        tf = tf.astype(np.float64)
        length = self._lengths(docs)
        denom = tf + self.k1 * (1 - self.b + self.b * (length / self.avg_len))
        return self.weight * self.idf * tf * (self.k1 + 1) / denom

    def score_all(self, allow_list):
        """-> (doc_ids, scores) over the full posting list (allow-filtered)."""
        docs, tf = self.ids, self.tf
        if allow_list is not None:
            keep = allow_list.contains_array(docs)
            if not keep.any():
                return docs[:0], np.empty(0, dtype=np.float64)
            docs, tf = docs[keep], tf[keep]
        return docs, self._score(docs, tf)

    def lookup(self, cand_ids):
        """-> (mask over cand_ids, scores at mask, posting positions at
        mask) for candidates present in this unit's postings —
        O(|cand| log df), never touches the rest."""
        if not self.ids.size:
            return None
        pos = np.clip(np.searchsorted(self.ids, cand_ids), 0, self.ids.size - 1)
        found = self.ids[pos] == cand_ids
        if not found.any():
            return None
        sel = pos[found]
        return found, self._score(self.ids[sel], self.tf[sel]), sel


class BM25Searcher:
    def __init__(self, inverted: InvertedIndex, class_def,
                 config: Optional[dict] = None, gen_fn=None):
        self.inverted = inverted
        self.class_def = class_def
        bm = (config or {}).get("bm25") or {}
        self.k1 = float(bm.get("k1", DEFAULT_K1))
        self.b = float(bm.get("b", DEFAULT_B))
        # per-prop document-length table cache, keyed by the shard's write
        # generation (gen_fn): rebuilding it costs a full map_get + sum over
        # EVERY doc, which used to dominate query time (~40 ms at 50k docs)
        self._gen_fn = gen_fn
        self._len_cache: dict[str, tuple] = {}
        self._count_cache: Optional[tuple] = None
        # decoded (prop, term) posting arrays, LRU under the write generation
        self._post_cache: OrderedDict[tuple, tuple] = OrderedDict()
        self._post_cache_bytes = 0
        # guards the three generation caches: concurrent readers share one
        # searcher per shard (hit->move_to_end racing another thread's
        # evict/insert would KeyError, and unsynchronized byte accounting
        # drifts permanently)
        self._cache_lock = threading.RLock()
        # subkey byte order pinned by the store's marker (legacy LE stores
        # decode correctly, just without the pre-sorted fast decode)
        self._key_dtype = getattr(inverted, "subkey_dtype", ">u8")

    def _doc_count(self) -> int:
        """inverted.doc_count() materializes the full roaring doc set —
        ~1.6 ms at 50k docs; cache it per write generation like the length
        tables."""
        gen = self._gen_fn() if self._gen_fn is not None else None
        with self._cache_lock:
            if gen is not None and self._count_cache is not None \
                    and self._count_cache[0] == gen:
                return self._count_cache[1]
        c = self.inverted.doc_count()
        # cache only if no write started meanwhile: the writer bumps the
        # generation BEFORE mutating, so a count read mid-write must not be
        # pinned under the new generation
        if gen is not None and (self._gen_fn() == gen):
            with self._cache_lock:
                self._count_cache = (gen, c)
        return c

    def _prop_lengths(self, prop_name: str, lb):
        """-> (sorted doc-id u64 array, f32 lengths aligned to it, avg).
        Cached per write generation when gen_fn is wired (the Shard path);
        standalone users pay the rebuild each call."""
        gen = self._gen_fn() if self._gen_fn is not None else None
        if gen is not None:
            with self._cache_lock:
                hit = self._len_cache.get(prop_name)
                if hit is not None and hit[0] == gen:
                    return hit[1], hit[2], hit[3]
        r = lb.map_get_arrays(b"len", key_dtype=self._key_dtype, val_dtype="<u4") \
            if lb is not None else None
        if r is None and lb is not None:  # tombstones etc: generic decode
            lengths = lb.map_get(b"len")
            if lengths:
                docs = np.frombuffer(b"".join(lengths.keys()), dtype=self._key_dtype)
                docs = docs.astype(np.uint64)
                lvals = np.frombuffer(b"".join(lengths.values()), dtype="<u4")
                order = np.argsort(docs)
                r = docs[order], lvals[order]
        if r is not None and r[0].size:
            docs, vals = r[0], r[1].astype(np.float32)
            avg = float(vals.mean(dtype=np.float64))
        else:
            docs = np.empty(0, dtype=np.uint64)
            vals = np.empty(0, dtype=np.float32)
            avg = 1.0
        # same mid-write guard as _doc_count: never pin a table read while
        # a write (which bumps the generation first) is in flight
        if gen is not None and self._gen_fn() == gen:
            with self._cache_lock:
                self._len_cache[prop_name] = (gen, docs, vals, avg)
        return docs, vals, avg

    def _postings(self, sb, prop_name: str, term: str):
        """Decoded doc-sorted postings for one (prop, term): fast
        array decode (map_get_arrays) with a dict-path fallback, LRU-cached
        per write generation with the same mid-write guard as the other
        generation caches."""
        gen = self._gen_fn() if self._gen_fn is not None else None
        key = (prop_name, term)
        if gen is not None:
            with self._cache_lock:
                hit = self._post_cache.get(key)
                if hit is not None and hit[0] == gen:
                    self._post_cache.move_to_end(key)
                    return hit[1], hit[2]
        r = sb.map_get_arrays(term.encode("utf-8"), key_dtype=self._key_dtype)
        if r is None:  # odd-shaped or tombstoned postings: generic path
            postings = sb.map_get(term.encode("utf-8"))
            if postings:
                ids = np.frombuffer(
                    b"".join(postings.keys()), dtype=self._key_dtype).astype(np.uint64)
                tf = np.frombuffer(b"".join(postings.values()), dtype="<f4")
                order = np.argsort(ids, kind="stable")
                ids, tf = ids[order], tf[order]
            else:
                ids = np.empty(0, dtype=np.uint64)
                tf = np.empty(0, dtype=np.float32)
        else:
            ids, tf = r
        if gen is not None and self._gen_fn() == gen:
            with self._cache_lock:
                old = self._post_cache.pop(key, None)
                if old is not None:
                    self._post_cache_bytes -= old[1].nbytes + old[2].nbytes
                self._post_cache[key] = (gen, ids, tf)
                self._post_cache_bytes += ids.nbytes + tf.nbytes
                while self._post_cache_bytes > _POST_CACHE_MAX_BYTES \
                        and len(self._post_cache) > 1:
                    _, (_, e_ids, e_tf) = self._post_cache.popitem(last=False)
                    self._post_cache_bytes -= e_ids.nbytes + e_tf.nbytes
        return ids, tf

    def _searchable_props(self, properties: Optional[Sequence[str]]) -> list[tuple[str, float]]:
        """-> [(prop, weight)]; supports "prop^2" boost syntax."""
        out = []
        if properties:
            for p in properties:
                if "^" in p:
                    name, w = p.split("^", 1)
                    out.append((name, float(w)))
                else:
                    out.append((p, 1.0))
        else:
            for prop in self.class_def.properties:
                pt = prop.primitive_type()
                if (
                    pt is not None
                    and pt.base in (DataType.TEXT, DataType.STRING)
                    and prop.index_searchable
                ):
                    out.append((prop.name, 1.0))
        return out

    def _build_units(self, query, props, n_docs):
        """-> scoring units in prop-major, term-minor order (the original
        accumulation order — explanations preserve it)."""
        terms: dict[str, None] = {}
        for prop_name, _w in props:
            prop = self.class_def.get_property(prop_name)
            tk = prop.tokenization if prop else "word"
            for t in tokenize(tk, query):
                terms.setdefault(t)
        units = []
        for prop_name, weight in props:
            sb = self.inverted.store.bucket(searchable_bucket(prop_name))
            lb = self.inverted.store.bucket(length_bucket(prop_name))
            if sb is None:
                continue
            len_docs, len_vals, avg_len = self._prop_lengths(prop_name, lb)
            for term in terms:
                ids, tf = self._postings(sb, prop_name, term)
                if not ids.size:
                    continue
                df = ids.size
                idf = math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
                units.append(_Unit(ids, tf, idf, weight, len_docs, len_vals,
                                   avg_len, self.k1, self.b, term,
                                   prop=prop_name))
        return units

    @staticmethod
    def _rank(units, limit, allow_list, prune=True, stats=None):
        """MaxScore-pruned term-at-a-time ranking -> (top_ids, top_scores).
        prune=False runs the identical merge exhaustively (the equivalence
        oracle for tests). stats (a dict, optional) receives counts of
        fully-scored vs lookup-only units."""
        order = sorted(range(len(units)), key=lambda i: -units[i].ub)
        rem_after = [0.0] * (len(order) + 1)
        for j in range(len(order) - 1, -1, -1):
            rem_after[j] = rem_after[j + 1] + units[order[j]].ub
        cand_ids = np.empty(0, dtype=np.uint64)
        cand_scores = np.empty(0, dtype=np.float64)
        pending = []  # full-scored (ids, scores) not yet merged
        theta = -math.inf
        processed_ub = 0.0

        def merge():
            nonlocal cand_ids, cand_scores
            all_ids = np.concatenate([cand_ids] + [p[0] for p in pending])
            all_s = np.concatenate([cand_scores] + [p[1] for p in pending])
            pending.clear()
            cand_ids, inverse = np.unique(all_ids, return_inverse=True)
            # bincount folds left-to-right in array order, so per-doc
            # accumulation order stays "unit order" no matter how merges
            # batch — pruned and exhaustive results are float-identical
            cand_scores = np.bincount(
                inverse, weights=all_s, minlength=cand_ids.size)

        growth = 0.0  # sum of UBs folded in since theta was last computed
        for j, i in enumerate(order):
            u = units[i]
            if not prune or rem_after[j] >= theta:
                if stats is not None:
                    stats["full"] = stats.get("full", 0) + 1
                ids, s = u.score_all(allow_list)
                if ids.size:
                    pending.append((ids, s))
                processed_ub += u.ub
            else:
                if stats is not None:
                    stats["lookup"] = stats.get("lookup", 0) + 1
                if pending:
                    merge()
                if cand_ids.size:
                    hit = u.lookup(cand_ids)
                    if hit is not None:
                        found, add, _ = hit
                        cand_scores[found] += add
            growth += u.ub
            # theta (the k-th best partial) is only worth a merge+partition
            # when the NEXT unit could actually switch to lookup-only. Two
            # cheap upper bounds on what theta could have become: any
            # partial total <= processed_ub, and theta grows by at most the
            # UBs folded in since it was last computed. While rem_after is
            # above both, the comparison cannot prune — skip the refresh.
            theta_possible = processed_ub if theta == -math.inf \
                else min(processed_ub, theta + growth)
            if prune and rem_after[j + 1] < theta_possible:
                if pending:
                    merge()
                if cand_ids.size >= limit:
                    theta = float(np.partition(cand_scores, -limit)[-limit])
                    growth = 0.0
        if pending:
            merge()
        top = np.lexsort((cand_ids, -cand_scores))[:limit]
        return cand_ids[top], cand_scores[top]

    def search(
        self,
        query: str,
        limit: int,
        properties: Optional[Sequence[str]] = None,
        allow_list: Optional[AllowList] = None,
        additional_explanations: bool = False,
    ) -> list[tuple[int, float, Optional[dict]]]:
        """-> [(doc_id, score, explain|None)] sorted by score desc."""
        if limit <= 0:
            return []
        props = self._searchable_props(properties)
        n_docs = max(self._doc_count(), 1)
        units = self._build_units(query, props, n_docs)
        if not units:
            return []
        top_ids, top_scores = self._rank(units, limit, allow_list)

        explains: dict[int, dict] = {}
        if additional_explanations and top_ids.size:
            # per top doc, per unit (original prop-major order — later props
            # overwrite the same term's entries, as the exhaustive scorer did)
            for u in units:
                hit = u.lookup(top_ids)
                if hit is None:
                    continue
                found, _, sel = hit
                lens = u._lengths(u.ids[sel])
                for d, tfv, lv in zip(top_ids[found].tolist(),
                                      u.tf[sel].tolist(), lens.tolist()):
                    explains.setdefault(d, {})[f"BM25F_{u.term}_frequency"] = float(tfv)
                    explains[d][f"BM25F_{u.term}_propLength"] = float(lv)

        return [(int(d), float(s),
                 explains.get(int(d)) if additional_explanations else None)
                for d, s in zip(top_ids, top_scores)]
