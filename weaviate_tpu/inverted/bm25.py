"""BM25F keyword search over the searchable map buckets.

Reference: inverted/bm25_searcher.go:77 (BM25F over map buckets with term
frequencies, WAND-style term iteration :99), config defaults k1=1.2 b=0.75
(entities/models InvertedIndexConfig.BM25).

Scoring: classic BM25 with per-property weights (BM25F flavor): for query
term t and doc d with term frequency tf in property p of length L_p:

    idf(t)  = ln(1 + (N - df + 0.5) / (df + 0.5))
    s(t, d) = idf(t) * tf' * (k1 + 1) / (tf' + k1 * (1 - b + b * L/avgL))

with tf' summed over weighted properties.
"""

from __future__ import annotations

import heapq
import math
import struct
from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.entities.schema import DataType
from weaviate_tpu.inverted.analyzer import tokenize
from weaviate_tpu.inverted.index import InvertedIndex, length_bucket, searchable_bucket
from weaviate_tpu.index.interface import AllowList

DEFAULT_K1 = 1.2
DEFAULT_B = 0.75


class BM25Searcher:
    def __init__(self, inverted: InvertedIndex, class_def, config: Optional[dict] = None):
        self.inverted = inverted
        self.class_def = class_def
        bm = (config or {}).get("bm25") or {}
        self.k1 = float(bm.get("k1", DEFAULT_K1))
        self.b = float(bm.get("b", DEFAULT_B))

    def _searchable_props(self, properties: Optional[Sequence[str]]) -> list[tuple[str, float]]:
        """-> [(prop, weight)]; supports "prop^2" boost syntax."""
        out = []
        if properties:
            for p in properties:
                if "^" in p:
                    name, w = p.split("^", 1)
                    out.append((name, float(w)))
                else:
                    out.append((p, 1.0))
        else:
            for prop in self.class_def.properties:
                pt = prop.primitive_type()
                if (
                    pt is not None
                    and pt.base in (DataType.TEXT, DataType.STRING)
                    and prop.index_searchable
                ):
                    out.append((prop.name, 1.0))
        return out

    def search(
        self,
        query: str,
        limit: int,
        properties: Optional[Sequence[str]] = None,
        allow_list: Optional[AllowList] = None,
        additional_explanations: bool = False,
    ) -> list[tuple[int, float, Optional[dict]]]:
        """-> [(doc_id, score, explain|None)] sorted by score desc."""
        props = self._searchable_props(properties)
        n_docs = max(self.inverted.doc_count(), 1)
        scores: dict[int, float] = {}
        explains: dict[int, dict] = {}

        # collect per-term postings across properties
        terms: dict[str, float] = {}
        for prop_name, weight in props:
            prop = self.class_def.get_property(prop_name)
            tk = prop.tokenization if prop else "word"
            for t in tokenize(tk, query):
                terms.setdefault(t, 0.0)

        for prop_name, weight in props:
            sb = self.inverted.store.bucket(searchable_bucket(prop_name))
            lb = self.inverted.store.bucket(length_bucket(prop_name))
            if sb is None:
                continue
            lengths = lb.map_get(b"len") if lb is not None else {}
            if lengths:
                total = sum(struct.unpack("<I", v)[0] for v in lengths.values())
                avg_len = total / len(lengths)
            else:
                avg_len = 1.0
            for term in terms:
                postings = sb.map_get(term.encode("utf-8"))
                if not postings:
                    continue
                df = len(postings)
                idf = math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
                for did_b, tf_b in postings.items():
                    (doc_id,) = struct.unpack("<Q", did_b)
                    if allow_list is not None and not allow_list.contains(doc_id):
                        continue
                    (tf,) = struct.unpack("<f", tf_b)
                    L_b = lengths.get(did_b)
                    L = struct.unpack("<I", L_b)[0] if L_b else avg_len
                    denom = tf + self.k1 * (1 - self.b + self.b * (L / avg_len))
                    s = weight * idf * tf * (self.k1 + 1) / denom
                    scores[doc_id] = scores.get(doc_id, 0.0) + s
                    if additional_explanations:
                        explains.setdefault(doc_id, {})[f"BM25F_{term}_frequency"] = tf
                        explains[doc_id][f"BM25F_{term}_propLength"] = L

        top = heapq.nlargest(limit, scores.items(), key=lambda kv: (kv[1], -kv[0]))
        return [(d, s, explains.get(d) if additional_explanations else None) for d, s in top]
