"""Text analysis + sortable value encodings for the inverted index.

Reference: inverted/analyzer.go (tokenization + countable values);
tokenization modes from entities/models/property.go:88-98:
- word:       split on non-alphanumeric, lowercase
- lowercase:  split on whitespace, lowercase
- whitespace: split on whitespace, case-sensitive
- field:      trim, single token

Numeric/date/bool values are encoded as byte-sortable keys so range
operators become lexicographic key-range scans over the LSM bucket (the
reference uses the same trick with its own LexicographicallySortable*
helpers in entities/filters and inverted/).
"""

from __future__ import annotations

import re
import struct
from datetime import datetime, timezone
from typing import Any

from weaviate_tpu.entities.schema import DataType, Tokenization

_WORD_SPLIT = re.compile(r"[^0-9A-Za-z]+")
_WS_SPLIT = re.compile(r"\s+")


def tokenize(tokenization: str, value: str) -> list[str]:
    if tokenization == Tokenization.WORD:
        return [t.lower() for t in _WORD_SPLIT.split(value) if t]
    if tokenization == Tokenization.LOWERCASE:
        return [t.lower() for t in _WS_SPLIT.split(value) if t]
    if tokenization == Tokenization.WHITESPACE:
        return [t for t in _WS_SPLIT.split(value) if t]
    if tokenization == Tokenization.FIELD:
        v = value.strip()
        return [v] if v else []
    raise ValueError(f"unknown tokenization {tokenization!r}")


# -- byte-sortable encodings -------------------------------------------------


def encode_int(v: int) -> bytes:
    """Sign-flipped big-endian: lexicographic order == numeric order."""
    return struct.pack(">Q", (v + (1 << 63)) & 0xFFFFFFFFFFFFFFFF)


def encode_float(v: float) -> bytes:
    """IEEE-754 total-order trick: flip all bits for negatives, sign for
    positives."""
    (bits,) = struct.unpack(">Q", struct.pack(">d", float(v)))
    if bits & (1 << 63):
        bits = ~bits & 0xFFFFFFFFFFFFFFFF
    else:
        bits |= 1 << 63
    return struct.pack(">Q", bits)


def encode_bool(v: bool) -> bytes:
    return b"\x01" if v else b"\x00"


def parse_date(v: str | datetime) -> datetime:
    if isinstance(v, datetime):
        return v if v.tzinfo else v.replace(tzinfo=timezone.utc)
    s = v.strip()
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    dt = datetime.fromisoformat(s)
    return dt if dt.tzinfo else dt.replace(tzinfo=timezone.utc)


def encode_date(v: str | datetime) -> bytes:
    dt = parse_date(v)
    nanos = int(dt.timestamp() * 1e9)
    return encode_int(nanos)


def value_tokens(data_type: DataType, tokenization: str, value: Any) -> list[bytes]:
    """All index tokens for one property value (array types flatten)."""
    base = data_type.base
    raw_values = value if data_type.is_array and isinstance(value, list) else [value]
    out: list[bytes] = []
    for v in raw_values:
        if v is None:
            continue
        if base in (DataType.TEXT, DataType.STRING):
            out.extend(t.encode("utf-8") for t in tokenize(tokenization, str(v)))
        elif base is DataType.UUID:
            out.append(str(v).lower().encode("utf-8"))
        elif base is DataType.INT:
            out.append(encode_int(int(v)))
        elif base is DataType.NUMBER:
            out.append(encode_float(float(v)))
        elif base is DataType.BOOLEAN:
            out.append(encode_bool(bool(v)))
        elif base is DataType.DATE:
            out.append(encode_date(v))
        elif base is DataType.PHONE_NUMBER:
            if isinstance(v, dict):
                for kk in ("input", "internationalFormatted", "national", "nationalFormatted"):
                    s = v.get(kk)
                    if s:
                        out.append(re.sub(r"[^0-9]", "", str(s)).encode("utf-8"))
            else:
                out.append(re.sub(r"[^0-9]", "", str(v)).encode("utf-8"))
        # geoCoordinates and blob are not inverted-indexed (geo has its own
        # index, propertyspecific/; blob is unindexable)
    return out


def filter_value_token(data_type: DataType, tokenization: str, value: Any) -> bytes:
    """Single comparison token for a filter value (Equal/range operators)."""
    base = data_type.base
    if base in (DataType.TEXT, DataType.STRING):
        toks = tokenize(tokenization, str(value))
        return toks[0].encode("utf-8") if toks else b""
    if base is DataType.UUID:
        return str(value).lower().encode("utf-8")
    if base is DataType.INT:
        return encode_int(int(value))
    if base is DataType.NUMBER:
        return encode_float(float(value))
    if base is DataType.BOOLEAN:
        return encode_bool(bool(value))
    if base is DataType.DATE:
        return encode_date(value)
    raise ValueError(f"cannot build filter token for {data_type}")


class Analyzer:
    """Object -> per-property index tokens (analyzer.go Analyze)."""

    def __init__(self, class_def):
        self.class_def = class_def

    def analyze(self, properties: dict) -> dict[str, list[bytes]]:
        """-> {prop_name: [tokens]}; missing/None props are absent (used for
        the null index)."""
        out: dict[str, list[bytes]] = {}
        for prop in self.class_def.properties:
            pt = prop.primitive_type()
            if pt is None or pt.base in (DataType.GEO_COORDINATES, DataType.BLOB):
                continue
            v = properties.get(prop.name)
            if v is None:
                continue
            out[prop.name] = value_tokens(pt, prop.tokenization, v)
        return out
