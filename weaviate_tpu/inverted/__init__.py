"""Inverted index: analyzer, per-property buckets, filters -> AllowList, BM25.

Reference: adapters/repos/db/inverted/ — Searcher.DocIDs (searcher.go:157),
docBitmap merges (searcher_doc_bitmap.go:25-109), BM25F
(bm25_searcher.go:77), analyzer.go, prop-length tracker.
"""

from weaviate_tpu.inverted.analyzer import Analyzer, tokenize
from weaviate_tpu.inverted.index import InvertedIndex
from weaviate_tpu.inverted.searcher import FilterSearcher
from weaviate_tpu.inverted.bm25 import BM25Searcher

__all__ = ["Analyzer", "tokenize", "InvertedIndex", "FilterSearcher", "BM25Searcher"]
