"""Per-property inverted buckets + write path.

Reference bucket layout (shard_write_inverted*.go, inverted/):
- filterable  -> RoaringSet bucket  property_<name>_filterable: token -> docID bitmap
- searchable  -> Map bucket         property_<name>_searchable: token -> {docID: tf}
- null        -> RoaringSet bucket  property_<name>__null: {0x00/0x01 -> docIDs}
- lengths     -> Map bucket         property_<name>__length (BM25 prop-length
                 tracker, proplengthtracker role)
- __all_docs  -> RoaringSet         live docID universe (for Not/complement)
"""

from __future__ import annotations

import os
import struct
from collections import Counter as PyCounter

from weaviate_tpu.entities.schema import ClassDef, DataType
from weaviate_tpu.inverted.analyzer import Analyzer
from weaviate_tpu.storage.bitmap import Bitmap
from weaviate_tpu.storage.lsm import (
    STRATEGY_MAP,
    STRATEGY_ROARINGSET,
    Store,
)

ALL_DOCS_KEY = b"__all__"
NULL_TRUE = b"\x01"
NULL_FALSE = b"\x00"


def filterable_bucket(prop: str) -> str:
    return f"property_{prop}_filterable"


def searchable_bucket(prop: str) -> str:
    return f"property_{prop}_searchable"


def null_bucket(prop: str) -> str:
    return f"property_{prop}__null"


def length_bucket(prop: str) -> str:
    return f"property_{prop}__length"


# Persisted marker for the searchable/length subkey byte order. Round 5
# switched new stores to big-endian doc-id subkeys (segment byte-lex order
# == numeric order -> the postings fast path skips its argsort); stores
# written before the marker existed keep little-endian and are pinned to it
# on first reopen, so old segments never get decoded with the wrong order
# or mixed with new-format writes.
SUBKEY_MARKER = ".searchable_subkeys"


class InvertedIndex:
    def __init__(self, store: Store, class_def: ClassDef):
        self.store = store
        self.class_def = class_def
        self.analyzer = Analyzer(class_def)
        self._all = store.create_or_load_bucket("_all_docs", STRATEGY_ROARINGSET)
        self._ensure_buckets()
        self.subkey_fmt = self._init_subkey_format()
        self.subkey_dtype = ">u8" if self.subkey_fmt == ">Q" else "<u8"

    def _init_subkey_format(self) -> str:
        """-> ">Q" (new stores) or "<Q" (legacy data without a marker)."""
        path = os.path.join(self.store.root, SUBKEY_MARKER)
        try:
            with open(path) as f:
                return ">Q" if f.read().strip() == "be" else "<Q"
        except FileNotFoundError:
            pass
        has_data = False
        for prop in self.class_def.properties:
            for bn in (searchable_bucket(prop.name), length_bucket(prop.name)):
                b = self.store.bucket(bn)
                if b is not None and (b.segment_count() or len(b._mem)):
                    has_data = True
                    break
            if has_data:
                break
        fmt = "<Q" if has_data else ">Q"
        # crash-atomic + durable: the marker decides how every fsynced
        # subkey byte on disk is decoded, so it must never be weaker than
        # the data it describes (temp file -> fsync -> rename -> dir fsync)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("le" if fmt == "<Q" else "be")
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
        dfd = os.open(self.store.root, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        return fmt

    def _ensure_buckets(self) -> None:
        for prop in self.class_def.properties:
            pt = prop.primitive_type()
            if pt is None or pt.base in (DataType.GEO_COORDINATES, DataType.BLOB):
                continue
            if prop.index_filterable:
                self.store.create_or_load_bucket(filterable_bucket(prop.name), STRATEGY_ROARINGSET)
                self.store.create_or_load_bucket(null_bucket(prop.name), STRATEGY_ROARINGSET)
            if prop.index_searchable and pt.base in (DataType.TEXT, DataType.STRING):
                self.store.create_or_load_bucket(searchable_bucket(prop.name), STRATEGY_MAP)
                self.store.create_or_load_bucket(length_bucket(prop.name), STRATEGY_MAP)

    def update_schema(self, class_def: ClassDef) -> None:
        """Pick up added properties (migrator AddProperty path)."""
        self.class_def = class_def
        self.analyzer = Analyzer(class_def)
        self._ensure_buckets()

    # -- write path ----------------------------------------------------------

    def add_object(self, doc_id: int, properties: dict) -> None:
        # single-object form of the batch writer — ONE posting code path
        errs = self.add_objects_batch([(doc_id, properties)])
        if doc_id in errs:
            raise errs[doc_id]

    def add_objects_batch(self, items) -> dict[int, Exception]:
        """Batch twin of add_object (shard_write_batch_objects.go analog):
        postings are grouped per term across the WHOLE batch, so each unique
        token costs one WAL record + one memtable update instead of one per
        containing object. items = [(doc_id, properties)];
        -> {doc_id: error} for objects whose analysis failed (they get no
        postings; callers keep per-object batch error isolation)."""
        analyzed: list[tuple[int, dict]] = []
        errs: dict[int, Exception] = {}
        for doc_id, props in items:
            try:
                analyzed.append((doc_id, self.analyzer.analyze(props)))
            except Exception as e:  # noqa: BLE001 — per-object isolation
                errs[doc_id] = e
        if not analyzed:
            return errs
        self._all.roaring_add_many(ALL_DOCS_KEY, [d for d, _ in analyzed])
        for prop in self.class_def.properties:
            pt = prop.primitive_type()
            if pt is None or pt.base in (DataType.GEO_COORDINATES, DataType.BLOB):
                continue
            name = prop.name
            if prop.index_filterable:
                nulls_t: list[int] = []
                nulls_f: list[int] = []
                by_tok: dict[bytes, list[int]] = {}
                for doc_id, tokens in analyzed:
                    toks = tokens.get(name)
                    (nulls_t if toks is None else nulls_f).append(doc_id)
                    if toks:
                        for t in set(toks):
                            by_tok.setdefault(t, []).append(doc_id)
                null_recs = []
                if nulls_t:
                    null_recs.append((NULL_TRUE, nulls_t))
                if nulls_f:
                    null_recs.append((NULL_FALSE, nulls_f))
                if null_recs:
                    self.store.bucket(null_bucket(name)).roaring_add_many_keys(null_recs)
                if by_tok:
                    self.store.bucket(filterable_bucket(name)).roaring_add_many_keys(
                        by_tok.items())
            if prop.index_searchable and pt.base in (DataType.TEXT, DataType.STRING):
                sput: list[tuple[bytes, bytes, bytes]] = []
                lput: list[tuple[bytes, bytes, bytes]] = []
                for doc_id, tokens in analyzed:
                    toks = tokens.get(name)
                    if not toks:
                        continue
                    # subkey byte order per the store's persisted marker
                    # (big-endian on new stores: segment byte-lex order ==
                    # numeric order, so the BM25 postings fast path decodes
                    # pre-sorted arrays — see lsm.map_get_arrays key_dtype)
                    did = struct.pack(self.subkey_fmt, doc_id)
                    for t, tf in PyCounter(toks).items():
                        sput.append((t, did, struct.pack("<f", float(tf))))
                    lput.append((b"len", did, struct.pack("<I", len(toks))))
                if sput:
                    self.store.bucket(searchable_bucket(name)).map_put_many(sput)
                if lput:
                    self.store.bucket(length_bucket(name)).map_put_many(lput)
        return errs

    def _filterable_indexed_docs(self, prop_name: str):
        """Bitmap of docs whose filterable postings exist for the prop: the
        null bucket gets exactly one entry (TRUE or FALSE) per doc when
        filterable indexing is active, so its union is the indexed set."""
        nb = self.store.bucket(null_bucket(prop_name))
        if nb is None:
            from weaviate_tpu.storage.bitmap import Bitmap

            return Bitmap()
        return nb.roaring_get(NULL_TRUE).or_(nb.roaring_get(NULL_FALSE))

    def unindexed_filterable(self, doc_count: int) -> dict[str, object]:
        """{prop: bitmap of docs MISSING filterable postings} — incremental
        detection for the startup reindexer
        (inverted_reindexer_missing_text_filterable.go): a prop written both
        before and after its indexFilterable flip reports exactly the
        pre-flip docs, not all-or-nothing."""
        if doc_count == 0:
            return {}
        all_docs = self._all.roaring_get(ALL_DOCS_KEY)
        out: dict[str, object] = {}
        for prop in self.class_def.properties:
            pt = prop.primitive_type()
            if pt is None or pt.base in (DataType.GEO_COORDINATES, DataType.BLOB):
                continue
            if not prop.index_filterable:
                continue
            missing = all_docs.and_not(self._filterable_indexed_docs(prop.name))
            if len(missing):
                out[prop.name] = missing
        return out

    def backfill_filterable(self, missing: dict[str, object], rows) -> dict[str, int]:
        """Index the filterable + null postings for each prop's MISSING docs
        (missing = unindexed_filterable() result; rows = (doc_id, properties)
        over the union of missing docs — one hydration pass covers every
        prop, and already-indexed docs are left untouched).
        -> {prop: docs indexed}."""
        targets = [
            (name,
             self.store.bucket(filterable_bucket(name)),
             self.store.bucket(null_bucket(name)),
             bm)
            for name, bm in missing.items()
        ]
        counts = {name: 0 for name, _, _, _ in targets}
        for doc_id, properties in rows:
            toks_by_prop = self.analyzer.analyze(
                {name: properties.get(name) for name, _, _, _ in targets})
            for name, fb, nb, bm in targets:
                if not bm.contains(doc_id):
                    continue
                toks = toks_by_prop.get(name)
                nb.roaring_add_many(NULL_TRUE if toks is None else NULL_FALSE, [doc_id])
                if toks:
                    for t in set(toks):
                        fb.roaring_add_many(t, [doc_id])
                counts[name] += 1
        return counts

    def delete_object(self, doc_id: int, properties: dict) -> None:
        tokens_by_prop = self.analyzer.analyze(properties)
        self._all.roaring_remove_many(ALL_DOCS_KEY, [doc_id])
        did = struct.pack(self.subkey_fmt, doc_id)  # matches add_object
        for prop in self.class_def.properties:
            pt = prop.primitive_type()
            if pt is None or pt.base in (DataType.GEO_COORDINATES, DataType.BLOB):
                continue
            toks = tokens_by_prop.get(prop.name)
            if prop.index_filterable:
                nb = self.store.bucket(null_bucket(prop.name))
                nb.roaring_remove_many(NULL_TRUE if toks is None else NULL_FALSE, [doc_id])
                if toks:
                    fb = self.store.bucket(filterable_bucket(prop.name))
                    for t in set(toks):
                        fb.roaring_remove_many(t, [doc_id])
            if (
                prop.index_searchable
                and pt.base in (DataType.TEXT, DataType.STRING)
                and toks
            ):
                sb = self.store.bucket(searchable_bucket(prop.name))
                for t in set(toks):
                    sb.map_delete(t, did)
                lb = self.store.bucket(length_bucket(prop.name))
                lb.map_delete(b"len", did)

    def update_object(self, doc_id_old: int, props_old: dict, doc_id_new: int, props_new: dict) -> None:
        self.delete_object(doc_id_old, props_old)
        self.add_object(doc_id_new, props_new)

    # -- read helpers --------------------------------------------------------

    def all_doc_ids(self) -> Bitmap:
        return self._all.roaring_get(ALL_DOCS_KEY)

    def doc_count(self) -> int:
        return len(self.all_doc_ids())
