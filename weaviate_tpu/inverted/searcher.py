"""Filter evaluation: where-clause tree -> doc-ID Bitmap (AllowList).

Reference: inverted/searcher.go:157 (DocIDs) + searcher_doc_bitmap.go:25-109
(per-clause docBitmap, sroar AND/OR/AndNot merges) + like_regexp.go.

Operator semantics (entities/filters/filters.go:24-35):
Equal / NotEqual / GreaterThan(Equal) / LessThan(Equal) / Like / IsNull /
ContainsAny / ContainsAll / WithinGeoRange + And / Or / Not combinators.
Range operators run as lexicographic key-range scans over the byte-sortable
token keys (analyzer.encode_*).
"""

from __future__ import annotations

import bisect
import re
from typing import Callable, Optional

from weaviate_tpu.entities.filters import (
    Clause,
    FilterValidationError,
    GeoRange,
    LocalFilter,
    Operator,
    like_to_regex,
)
from weaviate_tpu.entities.schema import ClassDef, DataType
from weaviate_tpu.inverted.analyzer import filter_value_token
from weaviate_tpu.inverted.index import (
    NULL_TRUE,
    InvertedIndex,
    filterable_bucket,
)
from weaviate_tpu.storage.bitmap import Bitmap


class FilterSearcher:
    def __init__(
        self,
        inverted: InvertedIndex,
        class_def: ClassDef,
        geo_search: Optional[Callable[[str, GeoRange], Bitmap]] = None,
        ref_resolver: Optional[Callable[[list[str], Clause], Bitmap]] = None,
    ):
        self.inverted = inverted
        self.class_def = class_def
        self.geo_search = geo_search
        self.ref_resolver = ref_resolver

    def doc_ids(self, flt: LocalFilter) -> Bitmap:
        return self._eval(flt.root)

    # -- tree ----------------------------------------------------------------

    def _eval(self, c: Clause) -> Bitmap:
        if c.operator is Operator.AND:
            out: Optional[Bitmap] = None
            for op in c.operands:
                b = self._eval(op)
                out = b if out is None else out.and_(b)
            return out or Bitmap()
        if c.operator is Operator.OR:
            out = Bitmap()
            for op in c.operands:
                out = out.or_(self._eval(op))
            return out
        if c.operator is Operator.NOT:
            # complement against the live universe (searcher uses the doc
            # universe the same way for NotEqual)
            universe = self.inverted.all_doc_ids()
            out = Bitmap()
            for op in c.operands:
                out = out.or_(self._eval(op))
            return universe.and_not(out)
        return self._eval_value(c)

    # -- leaves --------------------------------------------------------------

    def _prop(self, c: Clause):
        if not c.on:
            raise FilterValidationError("filter clause without path")
        name = c.on[0]
        if len(c.on) > 1:
            if name == "id" or name == "_id":
                raise FilterValidationError("id path cannot be nested")
            if self.ref_resolver is None:
                raise FilterValidationError("reference filters not supported here")
            return None  # handled by caller via ref path
        prop = self.class_def.get_property(name)
        if prop is None and name not in ("id", "_id", "_creationTimeUnix", "_lastUpdateTimeUnix"):
            raise FilterValidationError(f"unknown property {name!r} in filter")
        return prop

    def _eval_value(self, c: Clause) -> Bitmap:
        if len(c.on) > 1:
            # cross-reference path: [RefProp, TargetClass, targetProp...]
            if self.ref_resolver is None:
                raise FilterValidationError("reference filters not supported")
            return self.ref_resolver(c.on, c)
        name = c.on[0]
        if name in ("id", "_id"):
            return self._eval_id(c)
        prop = self._prop(c)
        if prop is None:
            raise FilterValidationError(f"unknown property {name!r}")
        pt = prop.primitive_type()
        if pt is None:
            raise FilterValidationError(
                f"property {name!r} is a reference; use a nested path"
            )
        if c.operator is Operator.WITHIN_GEO_RANGE:
            if pt.base is not DataType.GEO_COORDINATES:
                raise FilterValidationError("WithinGeoRange needs a geoCoordinates property")
            if self.geo_search is None:
                raise FilterValidationError("geo index not available")
            return self.geo_search(name, c.value)
        if c.operator is Operator.IS_NULL:
            from weaviate_tpu.inverted.index import null_bucket

            nb = self.inverted.store.bucket(null_bucket(name))
            if nb is None:
                return Bitmap()
            nulls = nb.roaring_get(NULL_TRUE)
            if c.value in (False, None) or (isinstance(c.value, bool) and not c.value):
                return self.inverted.all_doc_ids().and_not(nulls)
            return nulls
        if not prop.index_filterable:
            raise FilterValidationError(f"property {name!r} is not indexFilterable")
        bucket = self.inverted.store.bucket(filterable_bucket(name))
        if bucket is None:
            return Bitmap()

        if c.operator in (Operator.CONTAINS_ANY, Operator.CONTAINS_ALL):
            values = c.value if isinstance(c.value, list) else [c.value]
            out: Optional[Bitmap] = None
            for v in values:
                tok = filter_value_token(pt, prop.tokenization, v)
                b = bucket.roaring_get(tok)
                if c.operator is Operator.CONTAINS_ANY:
                    out = b if out is None else out.or_(b)
                else:
                    out = b if out is None else out.and_(b)
            return out or Bitmap()

        if c.operator is Operator.LIKE:
            rx = re.compile(like_to_regex(str(c.value)).encode("utf-8"))
            out = Bitmap()
            for key in bucket.keys():
                if rx.match(key):
                    out = out.or_(bucket.roaring_get(key))
            return out

        tok = filter_value_token(pt, prop.tokenization, c.value)
        if c.operator is Operator.EQUAL:
            return bucket.roaring_get(tok)
        if c.operator is Operator.NOT_EQUAL:
            return self.inverted.all_doc_ids().and_not(bucket.roaring_get(tok))
        if c.operator in (
            Operator.GREATER_THAN,
            Operator.GREATER_THAN_EQUAL,
            Operator.LESS_THAN,
            Operator.LESS_THAN_EQUAL,
        ):
            return self._range(bucket, tok, c.operator)
        raise FilterValidationError(f"unsupported operator {c.operator}")

    def _range(self, bucket, tok: bytes, op: Operator) -> Bitmap:
        keys = bucket.keys()
        lo = bisect.bisect_left(keys, tok)
        out = Bitmap()
        if op is Operator.GREATER_THAN:
            start = bisect.bisect_right(keys, tok)
            sel = keys[start:]
        elif op is Operator.GREATER_THAN_EQUAL:
            sel = keys[lo:]
        elif op is Operator.LESS_THAN:
            sel = keys[:lo]
        else:  # LESS_THAN_EQUAL
            sel = keys[: bisect.bisect_right(keys, tok)]
        for k in sel:
            out = out.or_(bucket.roaring_get(k))
        return out

    def _eval_id(self, c: Clause) -> Bitmap:
        """id filters resolve through the uuid->docID mapping supplied by the
        shard (searcher_doc_bitmap uuid path). Requires an id_resolver."""
        raise FilterValidationError(
            "id-path filters must be evaluated by the shard (uuid index)"
        )
