"""Device (TPU) BM25 engine: dense impact rows + one top_k per query.

The keyword half of hybrid search, on the same chip as the vector half.
Reference behavior: adapters/repos/db/inverted/bm25_searcher.go:77 (BM25F
over map buckets); this engine produces the same ranking as the host
MaxScore engine (inverted/bm25.py) and falls back to it wherever the
host path is strictly better:

- additional_explanations (per-term breakdown needs posting positions),
- empty/unknown terms only, or a corpus too small to be worth a device
  round trip (DEVICE_MIN_POSTINGS),
- backend init failure (no usable jax device).

Dense rows are cached on device per (property, term) under the shard
write generation — the same invalidation discipline as the host engine's
posting/length caches (bm25.py), including the mid-write guard: the
writer bumps the generation BEFORE mutating, so a row built mid-write is
never pinned under the new generation. allowLists ride along as a dense
bool mask, cached per (filter key, generation) like the vector side's
scatter-packed masks (index/tpu.py).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.index.interface import AllowList
from weaviate_tpu.inverted.bm25 import BM25Searcher

# below this many total postings the host engine wins: one relay round
# trip costs more than scoring a handful of arrays in numpy
DEVICE_MIN_POSTINGS = 0  # tuned by bench; 0 = always device when eligible

# device bytes pinned for dense rows (a row is n_pad * 4 bytes; at 1M docs
# each cached term costs ~4 MB)
_ROW_CACHE_MAX_BYTES = 256 * 1024 * 1024


class DeviceBM25:
    """Wraps a host BM25Searcher; owns the device row/mask caches."""

    def __init__(self, searcher: BM25Searcher, gen_fn=None):
        self.searcher = searcher
        self._gen_fn = gen_fn if gen_fn is not None else searcher._gen_fn
        # (prop, term) -> (gen, n_pad, device row [n_pad] f32)
        self._rows: OrderedDict[tuple, tuple] = OrderedDict()
        self._row_bytes = 0
        # filter key -> (gen, n_pad, device bool mask [n_pad])
        # id(bitmap) -> (gen, n_pad, device mask, pinned bitmap)
        self._masks: dict[int, tuple] = {}
        self._jax = None  # lazy import: module import must not init backend

    # -- plumbing ------------------------------------------------------------

    def _backend(self):
        if self._jax is None:
            import os  # noqa: PLC0415

            import jax  # noqa: PLC0415

            from weaviate_tpu.ops import bm25_scan  # noqa: PLC0415

            # honor JAX_PLATFORMS even when a site hook imported jax before
            # this process's env was consulted (same 12-factor contract as
            # __main__.py) — without this, a host pinned to an unreachable
            # accelerator hangs HERE on first keyword query instead of
            # serving on the backend the env asked for
            if os.environ.get("JAX_PLATFORMS"):
                jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
            jax.devices()  # raises if no backend comes up
            self._jax = (jax, bm25_scan)
        return self._jax

    def _gen(self):
        return self._gen_fn() if self._gen_fn is not None else None

    def _evict_dead(self, gen) -> None:
        """Drop rows/masks from older generations before building new ones
        (the old generation's device memory must be reclaimable NOW — a
        reindex sweep would otherwise double the footprint)."""
        dead = [k for k, v in self._rows.items() if v[0] != gen]
        for k in dead:
            _, _, row = self._rows.pop(k)
            self._row_bytes -= row.nbytes
        self._masks = {k: v for k, v in self._masks.items() if v[0] == gen}

    # -- dense row cache -----------------------------------------------------

    def _dense_row(self, unit, n_pad: int, gen):
        """Fully-scaled dense impact row for one scoring unit, built on
        device and cached under the write generation."""
        jax, bm25_scan = self._backend()
        import jax.numpy as jnp  # noqa: PLC0415

        key = (unit.prop, unit.term, unit.weight)
        hit = self._rows.get(key)
        if hit is not None and hit[0] == gen and hit[1] == n_pad:
            self._rows.move_to_end(key)
            return hit[2]
        # full per-posting scores, host side (f64 math, one pass) — the
        # scatter into doc-id space is the device's job
        scores = unit._score(unit.ids, unit.tf).astype(np.float32)
        ids = unit.ids.astype(np.int64)
        ids = np.where(ids < n_pad, ids, n_pad).astype(np.int32)
        ids, scores = bm25_scan.pad_postings(ids, scores, n_pad)
        zeros = jnp.zeros((n_pad + 1,), jnp.float32)
        row = bm25_scan.build_dense_row(
            jnp.asarray(ids), jnp.asarray(scores), zeros)
        if gen is not None and self._gen() == gen:
            old = self._rows.pop(key, None)
            if old is not None:
                self._row_bytes -= old[2].nbytes
            self._rows[key] = (gen, n_pad, row)
            self._row_bytes += row.nbytes
            while self._row_bytes > _ROW_CACHE_MAX_BYTES and len(self._rows) > 1:
                _, (_, _, e) = self._rows.popitem(last=False)
                self._row_bytes -= e.nbytes
        return row

    def _allow_mask(self, allow_list: AllowList, n_pad: int, gen):
        jax, _ = self._backend()
        import jax.numpy as jnp  # noqa: PLC0415

        # keyed by the Bitmap's identity, with the Bitmap itself PINNED in
        # the entry: without the strong ref, an evicted/uncached filter's
        # Bitmap could be freed and a different filter's Bitmap could
        # recycle the same address within one generation — the hit check
        # compares the stored object so a recycled id can never alias
        key = id(allow_list)
        hit = self._masks.get(key)
        if hit is not None and hit[0] == gen and hit[1] == n_pad \
                and hit[3] is allow_list:
            return hit[2]
        host = np.zeros((n_pad,), dtype=bool)
        ids = allow_list.to_array().astype(np.int64)
        host[ids[ids < n_pad]] = True
        mask = jnp.asarray(host)
        if gen is not None and self._gen() == gen:
            if len(self._masks) >= 16:
                self._masks.pop(next(iter(self._masks)))
            self._masks[key] = (gen, n_pad, mask, allow_list)
        return mask

    # -- search --------------------------------------------------------------

    def search(
        self,
        query: str,
        limit: int,
        properties: Optional[Sequence[str]] = None,
        allow_list: Optional[AllowList] = None,
        additional_explanations: bool = False,
    ) -> list[tuple[int, float, Optional[dict]]]:
        """Same contract as BM25Searcher.search. Explanations and device
        init failures fall back to the host engine."""
        if additional_explanations or limit <= 0:
            return self.searcher.search(
                query, limit, properties=properties, allow_list=allow_list,
                additional_explanations=additional_explanations)
        s = self.searcher
        props = s._searchable_props(properties)
        n_docs = max(s._doc_count(), 1)
        gen = self._gen()
        units = s._build_units(query, props, n_docs)
        if not units:
            return []
        total_postings = sum(u.ids.size for u in units)
        if total_postings < DEVICE_MIN_POSTINGS:
            return s.search(query, limit, properties=properties,
                            allow_list=allow_list)
        try:
            jax, bm25_scan = self._backend()
            import jax.numpy as jnp  # noqa: PLC0415
        except Exception:
            return s.search(query, limit, properties=properties,
                            allow_list=allow_list)

        max_id = max(int(u.ids[-1]) for u in units)  # ids are doc-sorted
        n_pad = bm25_scan.n_bucket(max_id)
        self._evict_dead(gen)
        total = self._dense_row(units[0], n_pad, gen)
        for u in units[1:]:
            total = bm25_scan.add_rows(total, self._dense_row(u, n_pad, gen))
        mask = self._allow_mask(allow_list, n_pad, gen) \
            if allow_list is not None else None
        k = min(bm25_scan.k_bucket(limit), n_pad)
        scores, ids = bm25_scan.dense_topk(total, k, mask)
        scores = np.asarray(scores)[:limit]
        ids = np.asarray(ids)[:limit]
        keep = ids >= 0
        return [(int(d), float(v), None)
                for d, v in zip(ids[keep], scores[keep])]
