"""Device (TPU) BM25 engine: dense impact rows + one top_k per query.

The keyword half of hybrid search, on the same chip as the vector half.
Reference behavior: adapters/repos/db/inverted/bm25_searcher.go:77 (BM25F
over map buckets); this engine produces the same ranking as the host
MaxScore engine (inverted/bm25.py) and falls back to it wherever the
host path is strictly better:

- additional_explanations (per-term breakdown needs posting positions),
- empty/unknown terms only, or a corpus too small to be worth a device
  round trip (DEVICE_MIN_POSTINGS),
- backend init failure (no usable jax device).

Dense rows are cached on device per (property, term) under the shard
write generation — the same invalidation discipline as the host engine's
posting/length caches (bm25.py), including the mid-write guard: the
writer bumps the generation BEFORE mutating, so a row built mid-write is
never pinned under the new generation. allowLists ride along as a dense
bool mask, cached per (filter key, generation) like the vector side's
scatter-packed masks (index/tpu.py).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.index.interface import AllowList
from weaviate_tpu.inverted.bm25 import BM25Searcher
from weaviate_tpu.monitoring import costmodel
from weaviate_tpu.monitoring.metrics import record_device_fallback

# below this many total postings the host engine wins: one relay round
# trip costs more than scoring a handful of arrays in numpy
DEVICE_MIN_POSTINGS = 0  # tuned by bench; 0 = always device when eligible

# device bytes pinned for dense rows (a row is n_pad * 4 bytes; at 1M docs
# each cached term costs ~4 MB). A batch sweep whose distinct-term working
# set exceeds this THRASHES (each slice's builds evict the previous
# slice's rows, so the next sweep rebuilds everything); on a 16 GB-HBM
# chip 512 MB alongside a 512 MB store is the right trade, and heavy
# keyword fleets can raise it via WEAVIATE_TPU_BM25_ROW_CACHE_MB.
try:
    _ROW_CACHE_MAX_BYTES = int(
        os.environ.get("WEAVIATE_TPU_BM25_ROW_CACHE_MB") or 512
    ) * 1024 * 1024
except ValueError:  # malformed value must not take the server down
    _ROW_CACHE_MAX_BYTES = 512 * 1024 * 1024

# transient device bytes one batched matmul may stack ([U_pad, n_pad] f32);
# batches whose distinct-unit set would exceed this are processed in
# slices (one dispatch + one fetch per slice) — bounds the working set so
# a wide BatchSearch cannot starve concurrent vector queries of HBM
_BATCH_STACK_MAX_BYTES = 256 * 1024 * 1024


class DeviceBM25:
    """Wraps a host BM25Searcher; owns the device row/mask caches."""

    def __init__(self, searcher: BM25Searcher, gen_fn=None):
        self.searcher = searcher
        self._gen_fn = gen_fn if gen_fn is not None else searcher._gen_fn
        # (prop, term) -> (gen, n_pad, device row [n_pad] f32)
        self._rows: OrderedDict[tuple, tuple] = OrderedDict()
        self._row_bytes = 0
        # id(bitmap) -> (gen, n_pad, device mask, pinned bitmap)
        self._masks: dict[int, tuple] = {}
        self._npad_hwm: Optional[tuple] = None  # (gen, n_pad floor)
        # guards _rows/_masks/_row_bytes/_npad_hwm: concurrent readers
        # share one engine per shard (shard.object_search takes no lock on
        # the read path), and two threads evicting at once must not race
        # the pops or drift the byte accounting
        self._cache_lock = threading.RLock()
        self._jax = None  # lazy import: module import must not init backend
        # shape of the most recent search_batch dispatch as a shared
        # cost-model shape (monitoring/costmodel.py): bench's keyword
        # roofline row reads it — flops = 2·Q·U·n_pad per matmul sweep,
        # HBM traffic = the [U, n_pad] f32 row matrix read once
        self.last_batch_shape: Optional[costmodel.DispatchShape] = None

    # -- plumbing ------------------------------------------------------------

    def _backend(self):
        if self._jax is None:
            import jax  # noqa: PLC0415

            from weaviate_tpu.ops import bm25_scan  # noqa: PLC0415

            # honor the CURRENT process env even when a site hook imported
            # jax earlier and froze jax.config.jax_platforms to the env of
            # that moment (same 12-factor contract as __main__.py) —
            # without this, a host pinned to an unreachable accelerator
            # hangs HERE on first keyword query instead of serving on the
            # backend the env asks for. Env-wins is deliberate: config
            # cannot distinguish "explicitly updated" from "snapshotted at
            # import", so the live env var is the operator's intent; a
            # script that pins the platform via jax.config.update must set
            # JAX_PLATFORMS too (tests/conftest.py does exactly that).
            live = getattr(getattr(jax._src, "xla_bridge", None),
                           "_backends", None)  # don't fight a LIVE backend
            if os.environ.get("JAX_PLATFORMS") and not live:
                jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
            jax.devices()  # raises if no backend comes up
            self._jax = (jax, bm25_scan)
        return self._jax

    def _gen(self):
        return self._gen_fn() if self._gen_fn is not None else None

    def _npad(self, max_id: int, gen) -> int:
        """Dense-row length for this request: the bucket for max_id, but
        never below the generation's high-water mark — without the
        monotone floor, queries alternating between low-id and high-id
        terms would invalidate each other's cached rows (n_pad is part of
        the row-cache hit check) and re-scatter every time."""
        from weaviate_tpu.ops import bm25_scan  # noqa: PLC0415

        want = bm25_scan.n_bucket(max_id)
        with self._cache_lock:
            cur = self._npad_hwm
            if cur is not None and cur[0] == gen:
                want = max(want, cur[1])
                self._npad_hwm = (gen, want)
            elif cur is None or self._gen() == gen:
                # only the LIVE generation may reset the floor — a
                # straggler from an older generation must not clobber the
                # newer generation's high-water mark
                self._npad_hwm = (gen, want)
        return want

    def _evict_dead(self) -> None:
        """Drop rows/masks whose generation is no longer LIVE before
        building new ones (the old generation's device memory must be
        reclaimable NOW — a reindex sweep would otherwise double the
        footprint). Compares against the generation read at eviction time,
        NOT a caller-supplied one: an in-flight query that captured the
        previous generation must never wipe the current generation's
        cache."""
        live = self._gen()
        with self._cache_lock:
            dead = [k for k, v in self._rows.items() if v[0] != live]
            for k in dead:
                entry = self._rows.pop(k, None)
                if entry is not None:
                    self._row_bytes -= entry[2].nbytes
            self._masks = {k: v for k, v in self._masks.items()
                           if v[0] == live}

    # -- dense row cache -----------------------------------------------------

    def _dense_row(self, unit, n_pad: int, gen):
        """Fully-scaled dense impact row for one scoring unit, built on
        device and cached under the write generation."""
        jax, bm25_scan = self._backend()
        import jax.numpy as jnp  # noqa: PLC0415

        key = (unit.prop, unit.term, unit.weight)
        with self._cache_lock:
            hit = self._rows.get(key)
            if hit is not None and hit[0] == gen and hit[1] == n_pad:
                self._rows.move_to_end(key)
                return hit[2]
        # full per-posting scores, host side (f64 math, one pass) — the
        # scatter into doc-id space is the device's job. Built OUTSIDE the
        # lock: two threads may redundantly build the same row (last write
        # wins), but a slow scatter never blocks other queries' cache hits.
        scores = unit._score(unit.ids, unit.tf).astype(np.float32)
        ids = unit.ids.astype(np.int64)
        ids = np.where(ids < n_pad, ids, n_pad).astype(np.int32)
        ids, scores = bm25_scan.pad_postings(ids, scores, n_pad)
        zeros = jnp.zeros((n_pad + 1,), jnp.float32)
        row = bm25_scan.build_dense_row(
            jnp.asarray(ids), jnp.asarray(scores), zeros)
        if gen is not None and self._gen() == gen:
            with self._cache_lock:
                old = self._rows.pop(key, None)
                if old is not None:
                    self._row_bytes -= old[2].nbytes
                self._rows[key] = (gen, n_pad, row)
                self._row_bytes += row.nbytes
                while self._row_bytes > _ROW_CACHE_MAX_BYTES \
                        and len(self._rows) > 1:
                    _, (_, _, e) = self._rows.popitem(last=False)
                    self._row_bytes -= e.nbytes
        return row

    def _allow_mask(self, allow_list: AllowList, n_pad: int, gen):
        jax, _ = self._backend()
        import jax.numpy as jnp  # noqa: PLC0415

        # keyed by the Bitmap's identity, with the Bitmap itself PINNED in
        # the entry: without the strong ref, an evicted/uncached filter's
        # Bitmap could be freed and a different filter's Bitmap could
        # recycle the same address within one generation — the hit check
        # compares the stored object so a recycled id can never alias
        key = id(allow_list)
        with self._cache_lock:
            hit = self._masks.get(key)
            if hit is not None and hit[0] == gen and hit[1] == n_pad \
                    and hit[3] is allow_list:
                return hit[2]
        host = np.zeros((n_pad,), dtype=bool)
        ids = allow_list.to_array().astype(np.int64)
        host[ids[ids < n_pad]] = True
        mask = jnp.asarray(host)
        if gen is not None and self._gen() == gen:
            with self._cache_lock:
                if len(self._masks) >= 16:
                    self._masks.pop(next(iter(self._masks)), None)
                self._masks[key] = (gen, n_pad, mask, allow_list)
        return mask

    # -- search --------------------------------------------------------------

    def search(
        self,
        query: str,
        limit: int,
        properties: Optional[Sequence[str]] = None,
        allow_list: Optional[AllowList] = None,
        additional_explanations: bool = False,
    ) -> list[tuple[int, float, Optional[dict]]]:
        """Same contract as BM25Searcher.search. Explanations and device
        init failures fall back to the host engine."""
        if additional_explanations or limit <= 0:
            return self.searcher.search(
                query, limit, properties=properties, allow_list=allow_list,
                additional_explanations=additional_explanations)
        s = self.searcher
        props = s._searchable_props(properties)
        if any(w <= 0 for _, w in props):
            # non-positive boosts ("prop^0", "prop^-1") break the
            # score-0-means-empty sentinel the device packing relies on —
            # the host engine ranks them correctly, so it serves them
            return s.search(query, limit, properties=properties,
                            allow_list=allow_list)
        # gen BEFORE _doc_count/_build_units: the _dense_row insert guard
        # re-reads the generation after compute, so the guarded window must
        # span EVERYTHING idf depends on — captured after the count, a
        # write landing in between could pin stale-idf rows under the new
        # generation and serve them until the next write
        gen = self._gen()
        n_docs = max(s._doc_count(), 1)
        units = s._build_units(query, props, n_docs)
        if not units:
            return []
        total_postings = sum(u.ids.size for u in units)
        if total_postings < DEVICE_MIN_POSTINGS:
            return s.search(query, limit, properties=properties,
                            allow_list=allow_list)
        try:
            jax, bm25_scan = self._backend()
            import jax.numpy as jnp  # noqa: PLC0415
        except Exception as e:
            # a dead backend silently serving every keyword query at host
            # speed is the bench.py zipf regression all over again — count
            # it and log (rate-limited) before degrading
            record_device_fallback("bm25_device.search", "backend_init", e)
            return s.search(query, limit, properties=properties,
                            allow_list=allow_list)

        max_id = max(int(u.ids[-1]) for u in units)  # ids are doc-sorted
        n_pad = self._npad(max_id, gen)
        self._evict_dead()
        total = self._dense_row(units[0], n_pad, gen)
        for u in units[1:]:
            total = bm25_scan.add_rows(total, self._dense_row(u, n_pad, gen))
        mask = self._allow_mask(allow_list, n_pad, gen) \
            if allow_list is not None else None
        k = min(bm25_scan.k_bucket(limit), n_pad)
        packed = bm25_scan.dense_topk(total, k, mask)
        scores, ids = bm25_scan.unpack_topk(packed, k)  # ONE blocking fetch
        scores = scores[:limit]
        ids = ids[:limit]
        keep = ids >= 0
        return [(int(d), float(v), None)
                for d, v in zip(ids[keep], scores[keep])]

    def search_batch(
        self,
        queries: Sequence[str],
        limit: int,
        properties: Optional[Sequence[str]] = None,
    ) -> Optional[list[list[tuple[int, float, None]]]]:
        """Q plain keyword queries in ONE device dispatch + ONE fetch:
        stack the distinct units' dense rows [U, n], build a [Q, U]
        selection matrix host-side, and let batch_topk's matmul produce
        every query's top-k. Returns None when the device path is
        unavailable (callers fall back to per-query host scoring).
        No allowList/explanations here — those park a query outside the
        batch lane (usecases/traverser.py get_class_batched eligibility)."""
        # cleared on EVERY path that doesn't dispatch: a caller reading
        # stats after a fallback must see None, not a previous batch's shape
        self.last_batch_shape = None
        if limit <= 0:
            return [[] for _ in queries]
        try:
            jax, bm25_scan = self._backend()
            import jax.numpy as jnp  # noqa: PLC0415
        except Exception as e:
            record_device_fallback("bm25_device.search_batch", "backend_init",
                                   e, note="batch lane falls back to "
                                   "per-query host scoring")
            return None
        s = self.searcher
        props = s._searchable_props(properties)
        if any(w <= 0 for _, w in props):
            return None  # non-positive boosts: host engine (see search())
        gen = self._gen()  # before _doc_count — same window as search()
        n_docs = max(s._doc_count(), 1)
        per_query_units = [s._build_units(q, props, n_docs) for q in queries]
        all_units = [u for units in per_query_units for u in units]
        if not all_units:
            return [[] for _ in queries]
        max_id = max(int(u.ids[-1]) for u in all_units)
        n_pad = self._npad(max_id, gen)
        self._evict_dead()
        # greedy slicing under the transient-stack budget: each slice's
        # DISTINCT units fit _BATCH_STACK_MAX_BYTES once stacked; a slice
        # still amortizes its dispatch+fetch over many queries
        max_units = max(int(_BATCH_STACK_MAX_BYTES // (n_pad * 4)),
                        max(len(u) for u in per_query_units), 1)
        out: list[list[tuple[int, float, None]]] = []
        stats = {"q": len(queries), "u": 0, "n_pad": n_pad, "slices": 0,
                 "qu": 0}  # qu = sum over slices of q_slice*u_slice
        qi = 0
        while qi < len(queries):
            ukeys: dict[tuple, object] = {}
            slice_units: list = []
            j = qi
            while j < len(queries):
                units = per_query_units[j]
                new = {(u.prop, u.term, u.weight): u for u in units
                       if (u.prop, u.term, u.weight) not in ukeys}
                if ukeys and len(ukeys) + len(new) > max_units:
                    break
                ukeys.update(new)
                slice_units.append(units)
                j += 1
            out.extend(self._matmul_slice(
                slice_units, ukeys, n_pad, gen, limit, jnp, bm25_scan))
            stats["u"] += len(ukeys)
            stats["qu"] += len(slice_units) * len(ukeys)
            stats["slices"] += 1
            qi = j
        # flops = 2 * n_pad * sum(q_slice*u_slice): a multi-slice sweep
        # does NOT multiply every query by every slice's units, so the
        # effective per-query unit width is qu/q
        self.last_batch_shape = costmodel.DispatchShape(
            costmodel.TIER_BM25_MATMUL,
            n=stats["n_pad"],
            dim=stats["qu"] / max(stats["q"], 1),
            batch=stats["q"],
            bytes_per_row=stats["u"] * 4,
            k=int(limit),
            extra=stats)
        return out

    @property
    def last_batch_stats(self) -> Optional[dict]:
        """Flat dict view of the last batch dispatch's shape (the
        pre-costmodel field name; bench rows and tests read it)."""
        s = self.last_batch_shape
        return None if s is None else s.describe()

    def _matmul_slice(self, per_query_units, ukeys, n_pad, gen, limit,
                      jnp, bm25_scan):
        """One batch_topk dispatch + one fetch for a slice of queries whose
        distinct units are already bounded by the caller."""
        if not ukeys:
            return [[] for _ in per_query_units]
        rows = [self._dense_row(u, n_pad, gen) for u in ukeys.values()]
        u_pad = bm25_scan.k_bucket(len(rows))
        if u_pad > len(rows):
            zero = jnp.zeros((n_pad,), jnp.float32)
            rows.extend([zero] * (u_pad - len(rows)))
        upos = {key: i for i, key in enumerate(ukeys)}
        qc = bm25_scan._QCHUNK
        q_pad = -(-len(per_query_units) // qc) * qc
        sel = np.zeros((q_pad, u_pad), dtype=np.float32)
        for qi, units in enumerate(per_query_units):
            for u in units:
                # += not =: a repeated property (["body", "body"]) yields
                # duplicate units that the per-query paths score twice
                sel[qi, upos[(u.prop, u.term, u.weight)]] += 1.0
        k = min(bm25_scan.k_bucket(limit), n_pad)
        packed = bm25_scan.batch_topk(jnp.stack(rows), jnp.asarray(sel), k)
        scores_all, ids_all = bm25_scan.topk_ops.unpack_topk(
            np.asarray(packed))  # ONE blocking fetch for the slice
        out: list[list[tuple[int, float, None]]] = []
        for qi in range(len(per_query_units)):
            scores = scores_all[qi][:limit]
            ids = ids_all[qi][:limit]
            keep = ids >= 0
            out.append([(int(d), float(v), None)
                        for d, v in zip(ids[keep], scores[keep])])
        return out
