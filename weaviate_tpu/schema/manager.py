"""Schema manager: class/property DDL, validation, persistence, migration.

Reference: usecases/schema/manager.go — class/property CRUD validated against
the vector-index config parser injected at configure_api.go:228-231; DDL is
propagated cluster-wide via 2-phase transactions (transactions.go:26-32:
add_class / add_property / delete_class / update_class / read_schema);
persisted to BoltDB (adapters/repos/schema/repo.go); drives migrate.Migrator
to create/drop indexes. Persistence here is an atomically-replaced JSON file;
the tx broadcast seam (`tx`) is filled by cluster.TxManager in multi-node
deployments and is None single-node.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Optional

from weaviate_tpu.cluster.sharding import ShardingConfig, ShardingState
from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.entities.schema import (
    ClassDef,
    Property,
    Schema,
    SchemaError,
    validate_class_name,
    validate_property_name,
)

# transaction types (usecases/schema/transactions.go:26-32)
TX_ADD_CLASS = "add_class"
TX_ADD_PROPERTY = "add_property"
TX_DELETE_CLASS = "delete_class"
TX_UPDATE_CLASS = "update_class"
TX_READ_SCHEMA = "read_schema"

RESERVED_PROPERTY_NAMES = {"id", "_id", "_additional", "vector"}


class SchemaValidationError(SchemaError):
    pass


class SchemaManager:
    def __init__(
        self,
        persist_path: str,
        migrator=None,
        node_names: Optional[list[str]] = None,
        tx=None,
        default_vectorizer: str = "none",
        node_source=None,
    ):
        """`migrator` is the DB (db.DB implements the migrate surface:
        add_class/drop_class/update_class/update_vector_config).
        `node_source` (callable -> list[str]) supplies LIVE membership for
        new classes (gossip auto-discovery); the chosen assignment is
        persisted into shardingConfig so restarts and late joiners keep the
        exact ring regardless of current membership."""
        self.persist_path = persist_path
        self.migrator = migrator
        self.node_names = node_names or ["node-0"]
        self.node_source = node_source
        self.tx = tx  # cluster.TxManager or None (single node)
        self.scaler = None  # usecases/scaler hook, set by cluster wiring
        self.default_vectorizer = default_vectorizer
        # set by App: name -> bool, is this vectorizer an enabled module?
        self.vectorizer_validator = None
        self.schema = Schema()
        self.sharding_states: dict[str, ShardingState] = {}
        self._callbacks: list[Callable[[Schema], None]] = []
        self._lock = threading.RLock()
        os.makedirs(os.path.dirname(persist_path) or ".", exist_ok=True)
        self._load()

    # -- persistence (adapters/repos/schema/repo.go) -------------------------

    def _load(self) -> None:
        if not os.path.exists(self.persist_path):
            return
        with open(self.persist_path) as f:
            data = json.load(f)
        self.schema = Schema.from_dict(data)
        for cd in self.schema.classes.values():
            self._mk_sharding_state(cd)
            if self.migrator is not None:
                self.migrator.add_class(
                    cd, self._parse_vi_config(cd), self.sharding_states[cd.name]
                )

    def _save(self) -> None:
        tmp = self.persist_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.schema.to_dict(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.persist_path)

    def register_schema_update_callback(self, cb: Callable[[Schema], None]) -> None:
        """GraphQL-rebuild seam (configure_api.go:289
        RegisterSchemaUpdateCallback)."""
        self._callbacks.append(cb)

    def _notify(self) -> None:
        for cb in self._callbacks:
            cb(self.schema)

    # -- helpers -------------------------------------------------------------

    def _parse_vi_config(self, cd: ClassDef) -> vi.HnswUserConfig:
        try:
            return vi.parse_and_validate_config(cd.vector_index_type, cd.vector_index_config)
        except vi.ConfigValidationError as e:
            raise SchemaValidationError(str(e)) from e

    def _current_nodes(self) -> list[str]:
        if self.node_source is not None:
            live = sorted(self.node_source())
            if live:
                return live
        return self.node_names

    def _mk_sharding_state(self, cd: ClassDef) -> ShardingState:
        # a previously chosen node assignment (persisted, or shipped in the
        # 2PC payload by the coordinator) is authoritative — every node must
        # build the SAME ring even if its current membership view differs.
        # Legacy classes without one fall back to the STATIC node list, never
        # live membership: _load() runs before gossip has converged, and a
        # half-empty view would silently re-ring existing data
        names = (cd.sharding_config or {}).get("nodes") or self.node_names
        cfg = ShardingConfig.from_dict(cd.sharding_config, len(names))
        repl = (cd.replication_config or {}).get("factor")
        if repl:
            cfg.replicas = int(repl)
        st = ShardingState(cd.name, cfg, names)
        self.sharding_states[cd.name] = st
        cd.sharding_config = {**cfg.to_dict(), "nodes": list(names)}
        return st

    def get_schema(self) -> Schema:
        return self.schema

    def get_class(self, name: str) -> Optional[ClassDef]:
        return self.schema.get(name)

    def resolve_class_name(self, name: str) -> Optional[str]:
        """Case-tolerant class lookup (the REST API capitalizes)."""
        if self.schema.get(name) is not None:
            return name
        cap = name[:1].upper() + name[1:]
        if self.schema.get(cap) is not None:
            return cap
        return None

    def sharding_state(self, class_name: str) -> Optional[ShardingState]:
        return self.sharding_states.get(class_name)

    # -- DDL (usecases/schema/add.go, delete.go, update.go) ------------------

    def add_class(self, class_def: ClassDef | dict) -> ClassDef:
        if isinstance(class_def, dict):
            class_def = ClassDef.from_dict(class_def)
        with self._lock:
            name = validate_class_name(class_def.name)
            class_def.name = name
            if self.schema.get(name) is not None:
                raise SchemaValidationError(f"class {name!r} already exists")
            if not class_def.vectorizer:
                class_def.vectorizer = self.default_vectorizer
            if (
                class_def.vectorizer
                and class_def.vectorizer != "none"
                and self.vectorizer_validator is not None
                and not self.vectorizer_validator(class_def.vectorizer)
            ):
                raise SchemaValidationError(
                    f"vectorizer {class_def.vectorizer!r} is not an enabled "
                    "module (check ENABLE_MODULES)"
                )
            for p in class_def.properties:
                self._validate_property(class_def, p, check_dup=False)
            seen = set()
            for p in class_def.properties:
                low = p.name.lower()
                if low in seen:
                    raise SchemaValidationError(f"duplicate property {p.name!r}")
                seen.add(low)
            vi_cfg = self._parse_vi_config(class_def)  # validates
            # the COORDINATOR fixes the node assignment and ships it in the
            # 2PC payload (and persists it) — remote views must not re-derive
            # the ring from possibly-divergent membership
            if not (class_def.sharding_config or {}).get("nodes"):
                class_def.sharding_config = {
                    **(class_def.sharding_config or {}),
                    "nodes": self._current_nodes(),
                }
            if self.tx is not None:
                self.tx.broadcast_commit(TX_ADD_CLASS, {"class": class_def.to_dict()})
            self.apply_add_class(class_def, vi_cfg)
            return class_def

    def apply_add_class(self, class_def: ClassDef, vi_cfg=None) -> None:
        """Commit phase (local apply; also the remote-node entry point)."""
        with self._lock:
            if vi_cfg is None:
                vi_cfg = self._parse_vi_config(class_def)
            self.schema.classes[class_def.name] = class_def
            state = self._mk_sharding_state(class_def)
            if self.migrator is not None:
                self.migrator.add_class(class_def, vi_cfg, state)
            self._save()
            self._notify()

    def delete_class(self, name: str) -> None:
        with self._lock:
            resolved = self.resolve_class_name(name)
            if resolved is None:
                raise SchemaValidationError(f"class {name!r} not found")
            if self.tx is not None:
                self.tx.broadcast_commit(TX_DELETE_CLASS, {"class": resolved})
            self.apply_delete_class(resolved)

    def apply_delete_class(self, name: str) -> None:
        with self._lock:
            self.schema.classes.pop(name, None)
            self.sharding_states.pop(name, None)
            if self.migrator is not None:
                self.migrator.drop_class(name)
            self._save()
            self._notify()

    def _validate_property(self, cd: ClassDef, prop: Property, check_dup: bool = True) -> None:
        validate_property_name(prop.name)
        if prop.name.lower() in RESERVED_PROPERTY_NAMES:
            raise SchemaValidationError(f"property name {prop.name!r} is reserved")
        if check_dup and cd.get_property(prop.name) is not None:
            raise SchemaValidationError(f"property {prop.name!r} already exists")
        if not prop.data_type:
            raise SchemaValidationError(f"property {prop.name!r} has no dataType")
        if prop.primitive_type() is None:
            # cross-reference: every target class must exist (or be self)
            for target in prop.data_type:
                if target != cd.name and self.schema.get(target) is None:
                    raise SchemaValidationError(
                        f"property {prop.name!r}: unknown reference target {target!r}"
                    )

    def add_property(self, class_name: str, prop: Property | dict) -> Property:
        if isinstance(prop, dict):
            prop = Property.from_dict(prop)
        with self._lock:
            resolved = self.resolve_class_name(class_name)
            if resolved is None:
                raise SchemaValidationError(f"class {class_name!r} not found")
            cd = self.schema.get(resolved)
            self._validate_property(cd, prop)
            if self.tx is not None:
                self.tx.broadcast_commit(
                    TX_ADD_PROPERTY, {"class": resolved, "property": prop.to_dict()}
                )
            self.apply_add_property(resolved, prop)
            return prop

    def apply_add_property(self, class_name: str, prop: Property) -> None:
        with self._lock:
            cd = self.schema.get(class_name)
            if cd is None:
                return
            if cd.get_property(prop.name) is None:
                cd.properties.append(prop)
            if self.migrator is not None:
                self.migrator.update_class(cd)
            self._save()
            self._notify()

    def update_class(self, class_name: str, updated: dict) -> ClassDef:
        """Mutable: vectorIndexConfig hot fields, invertedIndexConfig,
        description, moduleConfig. Immutable: vectorizer, vectorIndexType,
        shardingConfig (usecases/schema update validation)."""
        with self._lock:
            resolved = self.resolve_class_name(class_name)
            if resolved is None:
                raise SchemaValidationError(f"class {class_name!r} not found")
            cd = self.schema.get(resolved)
            if "vectorizer" in updated and updated["vectorizer"] != cd.vectorizer:
                raise SchemaValidationError("vectorizer is immutable")
            if (
                "vectorIndexType" in updated
                and updated["vectorIndexType"] != cd.vector_index_type
            ):
                raise SchemaValidationError("vectorIndexType is immutable")
            if "shardingConfig" in updated:
                new_sh = ShardingConfig.from_dict(updated["shardingConfig"], len(self.node_names))
                cur_sh = ShardingConfig.from_dict(cd.sharding_config, len(self.node_names))
                if new_sh.desired_count != cur_sh.desired_count:
                    raise SchemaValidationError("shardingConfig.desiredCount is immutable")
            if "properties" in updated:
                from weaviate_tpu.entities.schema import Property

                cur_props = [p.to_dict() for p in cd.properties]
                # normalize through Property so a fetch-tweak-PUT payload
                # with omitted default keys compares equal
                try:
                    new_props = [Property.from_dict(p).to_dict()
                                 for p in updated["properties"]]
                except (KeyError, TypeError, AttributeError) as e:
                    raise SchemaValidationError(
                        f"malformed properties payload: {e}") from e
                by_name = lambda props: sorted(props, key=lambda p: p.get("name", ""))  # noqa: E731
                if by_name(new_props) != by_name(cur_props):
                    # silent-ignore would ack a change that never happened;
                    # reject like the reference's update validation (new
                    # props go through POST .../properties; index-flag
                    # migration is the startup reindexer's job)
                    raise SchemaValidationError(
                        "properties are immutable on class update; add new "
                        "properties via POST /v1/schema/{class}/properties")
            payload = {"class": resolved, "updated": updated}
            if self.tx is not None:
                self.tx.broadcast_commit(TX_UPDATE_CLASS, payload)
            self.apply_update_class(resolved, updated)
            return self.schema.get(resolved)

    def apply_update_class(self, class_name: str, updated: dict) -> None:
        with self._lock:
            cd = self.schema.get(class_name)
            if cd is None:
                return
            if "vectorIndexConfig" in updated:
                old_cfg = self._parse_vi_config(cd)
                try:
                    new_cfg = vi.parse_and_validate_config(
                        cd.vector_index_type, updated["vectorIndexConfig"]
                    )
                    vi.validate_config_update(old_cfg, new_cfg)
                except vi.ConfigValidationError as e:
                    raise SchemaValidationError(str(e)) from e
                cd.vector_index_config = updated["vectorIndexConfig"]
                if self.migrator is not None:
                    self.migrator.update_vector_config(class_name, new_cfg)
            if "invertedIndexConfig" in updated:
                cd.inverted_index_config = updated["invertedIndexConfig"]
            if "description" in updated:
                cd.description = updated["description"]
            if "moduleConfig" in updated:
                cd.module_config = updated["moduleConfig"]
            if "replicationConfig" in updated:
                # replication-factor change: rebuild the ring with the new
                # replica count and hand the local shards to the scaler
                # (usecases/scaler/scaler.go trigger path). The file push
                # runs BEFORE the new state activates, so in-flight writes
                # keep targeting the old replica set during the copy; writes
                # landing in that window reach the new replica via read
                # repair afterwards.
                old_state = self.sharding_states.get(class_name)
                cd.replication_config = updated["replicationConfig"]
                new_state = self._mk_sharding_state(cd)
                if self.scaler is not None and old_state is not None:
                    self.scaler.scale(class_name, old_state, new_state)
                if self.migrator is not None and hasattr(self.migrator, "update_sharding_state"):
                    self.migrator.update_sharding_state(class_name, new_state)
            if self.migrator is not None:
                self.migrator.update_class(cd)
            self._save()
            self._notify()

    # -- shards status (schema/shards REST surface) --------------------------

    def shards_status(self, class_name: str) -> list[dict]:
        resolved = self.resolve_class_name(class_name)
        if resolved is None or self.migrator is None:
            raise SchemaValidationError(f"class {class_name!r} not found")
        idx = self.migrator.get_index(resolved)
        return idx.shards_status() if idx is not None else []

    def update_shard_status(self, class_name: str, shard_name: str, status: str) -> None:
        resolved = self.resolve_class_name(class_name)
        idx = self.migrator.get_index(resolved) if self.migrator else None
        if idx is None or shard_name not in idx.shards:
            raise SchemaValidationError(f"shard {class_name}/{shard_name} not found")
        idx.shards[shard_name].set_status(status.upper())
