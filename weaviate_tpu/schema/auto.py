"""Auto-schema: infer classes and properties from object payloads.

Reference: usecases/objects/auto_schema.go — when AUTOSCHEMA_ENABLED (default
true), an import referencing a missing class creates it, and missing
properties are added with inferred data types (defaults configurable:
AUTOSCHEMA_DEFAULT_STRING=text, _NUMBER=number, _DATE=date).
"""

from __future__ import annotations

import datetime
from typing import Any, Optional

from weaviate_tpu.entities.schema import ClassDef, Property, SchemaError, datatype_of_value


def _looks_like_date(v: str) -> bool:
    try:
        datetime.datetime.fromisoformat(v.replace("Z", "+00:00"))
        return True
    except (ValueError, TypeError):
        return False


class AutoSchema:
    def __init__(
        self,
        manager,
        enabled: bool = True,
        default_string: str = "text",
        default_number: str = "number",
        default_date: str = "date",
    ):
        self.manager = manager
        self.enabled = enabled
        self.default_string = default_string
        self.default_number = default_number
        self.default_date = default_date

    def infer_type(self, value: Any) -> Optional[str]:
        """Delegates to entities.schema.datatype_of_value; layers the
        configurable defaults (string->text|date, number) on top."""
        if isinstance(value, str):
            return self.default_date if _looks_like_date(value) else self.default_string
        if isinstance(value, float):
            return self.default_number
        if isinstance(value, list) and value:
            if isinstance(value[0], str):
                inner = self.infer_type(value[0])
                return f"{inner}[]"
            if isinstance(value[0], dict):
                return "object"  # list of nested objects: not auto-indexable
        if isinstance(value, dict) and not (
            {"latitude", "longitude"} <= set(value)
            or ("input" in value or "internationalFormatted" in value)
        ):
            return "object"  # plain nested object: not auto-indexable
        if isinstance(value, dict) and ("input" in value or "internationalFormatted" in value):
            return "phoneNumber"
        try:
            return datatype_of_value(value).value
        except SchemaError:
            return None

    def ensure(self, class_name: str, properties: dict) -> str:
        """Create the class and/or add missing properties as needed.
        -> resolved class name. Raises if auto-schema disabled and missing."""
        resolved = self.manager.resolve_class_name(class_name)
        if resolved is None:
            if not self.enabled:
                from weaviate_tpu.schema.manager import SchemaValidationError

                raise SchemaValidationError(f"class {class_name!r} not found")
            cd = ClassDef(name=class_name[:1].upper() + class_name[1:], properties=[])
            self.manager.add_class(cd)
            resolved = cd.name
        if not self.enabled or not properties:
            return resolved
        cd = self.manager.get_class(resolved)
        for key, value in properties.items():
            if cd.get_property(key) is not None or value is None:
                continue
            dt = self.infer_type(value)
            if dt is None or dt == "object":
                continue
            self.manager.add_property(resolved, Property(name=key, data_type=[dt]))
        return resolved
