"""Schema subsystem: manager, validation, auto-schema, persistence.

Reference: usecases/schema (manager, 2-phase cluster transactions),
adapters/repos/schema (BoltDB persistence), usecases/objects/auto_schema.go.
"""

from weaviate_tpu.schema.manager import SchemaManager, SchemaValidationError
from weaviate_tpu.schema.auto import AutoSchema

__all__ = ["SchemaManager", "SchemaValidationError", "AutoSchema"]
