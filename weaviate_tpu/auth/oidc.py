"""OIDC token validation backed by the issuer's JWKS.

Reference: usecases/auth/authentication/oidc/ — fetch the issuer's
discovery document, pull the JWKS, verify RS256 bearer tokens (signature,
issuer, audience, expiry), and map the configured claims onto a Principal.
Plugs into the existing `Authenticator.oidc_validator` seam.

Signature verification is RSASSA-PKCS1-v1_5/SHA-256 implemented directly on
big-int modular exponentiation — no third-party JWT/crypto dependency on the
serving path (the test suite uses `cryptography` only to mint keys and sign
tokens against a fake issuer).

Key handling: keys are cached by kid; an unknown kid triggers one JWKS
refetch (rotation) with a cooldown so a flood of forged kids cannot hammer
the issuer.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
import urllib.request
from typing import Callable, Optional

from weaviate_tpu.auth.auth import Principal, UnauthorizedError

# DER DigestInfo prefix for SHA-256 (RFC 8017, EMSA-PKCS1-v1_5)
_SHA256_PREFIX = bytes.fromhex("3031300d060960864801650304020105000420")
_REFRESH_COOLDOWN = 30.0  # seconds between JWKS refetches


def _b64url(data: str) -> bytes:
    return base64.urlsafe_b64decode(data + "=" * (-len(data) % 4))


def _b64url_uint(data: str) -> int:
    return int.from_bytes(_b64url(data), "big")


def _rsa_pkcs1v15_sha256_verify(n: int, e: int, message: bytes, sig: bytes) -> bool:
    k = (n.bit_length() + 7) // 8
    if len(sig) != k:
        return False
    m = pow(int.from_bytes(sig, "big"), e, n)
    em = m.to_bytes(k, "big")
    digest = hashlib.sha256(message).digest()
    pad_len = k - 3 - len(_SHA256_PREFIX) - len(digest)
    if pad_len < 8:
        return False
    expected = b"\x00\x01" + b"\xff" * pad_len + b"\x00" + _SHA256_PREFIX + digest
    return hmac.compare_digest(em, expected)


class OIDCValidator:
    """Callable[[token], Principal] for Authenticator.oidc_validator."""

    def __init__(self, oidc_cfg, http_get: Optional[Callable[[str], bytes]] = None,
                 timeout: float = 10.0, leeway: float = 30.0):
        self.cfg = oidc_cfg
        self.timeout = timeout
        self.leeway = leeway
        self._http_get = http_get or self._default_get
        self._keys: dict[str, tuple[int, int]] = {}  # kid -> (n, e)
        self._last_fetch = 0.0
        self._lock = threading.Lock()

    def _default_get(self, url: str) -> bytes:
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return resp.read()

    # -- JWKS ----------------------------------------------------------------

    def _load_keys(self) -> None:
        issuer = (self.cfg.issuer or "").rstrip("/")
        if not issuer:
            raise UnauthorizedError("OIDC issuer not configured")
        discovery = json.loads(
            self._http_get(f"{issuer}/.well-known/openid-configuration")
        )
        jwks_uri = discovery.get("jwks_uri")
        if not jwks_uri:
            raise UnauthorizedError("OIDC discovery document has no jwks_uri")
        jwks = json.loads(self._http_get(jwks_uri))
        keys: dict[str, tuple[int, int]] = {}
        for k in jwks.get("keys", []):
            if k.get("kty") != "RSA" or not k.get("n") or not k.get("e"):
                continue
            keys[k.get("kid", "")] = (_b64url_uint(k["n"]), _b64url_uint(k["e"]))
        if not keys:
            raise UnauthorizedError("issuer JWKS contains no usable RSA keys")
        self._keys = keys
        self._last_fetch = time.monotonic()

    def _key_for(self, kid: str) -> Optional[tuple[int, int]]:
        with self._lock:
            if not self._keys:
                self._load_keys()
            key = self._keys.get(kid)
            if key is None and kid not in self._keys:
                # possible rotation: refetch, rate-limited
                if time.monotonic() - self._last_fetch > _REFRESH_COOLDOWN:
                    self._load_keys()
                    key = self._keys.get(kid)
            return key

    # -- validation ----------------------------------------------------------

    def __call__(self, token: str) -> Principal:
        parts = token.split(".")
        if len(parts) != 3:
            raise UnauthorizedError("malformed bearer token")
        try:
            header = json.loads(_b64url(parts[0]))
            claims = json.loads(_b64url(parts[1]))
            sig = _b64url(parts[2])
        except (ValueError, json.JSONDecodeError):
            raise UnauthorizedError("malformed bearer token") from None

        if header.get("alg") != "RS256":
            raise UnauthorizedError(
                f"unsupported token alg {header.get('alg')!r} (RS256 only)"
            )
        try:
            key = self._key_for(header.get("kid", ""))
        except OSError as e:
            raise UnauthorizedError(f"cannot reach OIDC issuer: {e}") from e
        if key is None:
            raise UnauthorizedError("token signed with unknown key")
        signed = f"{parts[0]}.{parts[1]}".encode("ascii")
        if not _rsa_pkcs1v15_sha256_verify(key[0], key[1], signed, sig):
            raise UnauthorizedError("token signature verification failed")

        now = time.time()
        exp = claims.get("exp")
        if exp is not None and now > float(exp) + self.leeway:
            raise UnauthorizedError("token expired")
        nbf = claims.get("nbf")
        if nbf is not None and now < float(nbf) - self.leeway:
            raise UnauthorizedError("token not yet valid")
        issuer = (self.cfg.issuer or "").rstrip("/")
        if claims.get("iss", "").rstrip("/") != issuer:
            raise UnauthorizedError("token issuer mismatch")
        client_id = getattr(self.cfg, "client_id", "")
        if client_id and not getattr(self.cfg, "skip_client_id_check", False):
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if client_id not in auds:
                raise UnauthorizedError("token audience mismatch")

        username = claims.get(self.cfg.username_claim or "sub")
        if not username:
            raise UnauthorizedError(
                f"token missing username claim {self.cfg.username_claim or 'sub'!r}"
            )
        groups = []
        if self.cfg.groups_claim:
            groups = list(claims.get(self.cfg.groups_claim) or [])
        return Principal(username=str(username), groups=groups)
