"""Authentication (anonymous / API key / OIDC) and admin-list authorization.

Reference: usecases/auth/ — the authentication composer picks the first
scheme that applies to a request (authentication/composer), API keys map
positionally onto AUTHENTICATION_APIKEY_USERS, and authorization is the
adminlist model: admins may do everything, readonly users only get/list,
anonymous counts as the pseudo-user "anonymous" when enabled.

OIDC here validates structure only (issuer/client-id config is accepted and
bearer tokens are parsed for the username claim) — signature verification
needs the issuer's JWKS, an external fetch, so it is pluggable via
`Authenticator.oidc_validator`.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field
from typing import Callable, Optional

from weaviate_tpu.config.config import AuthConfig, AuthzConfig


class AuthError(Exception):
    pass


class UnauthorizedError(AuthError):
    """401: no/invalid credentials."""


class ForbiddenError(AuthError):
    """403: authenticated but not allowed."""


@dataclass
class Principal:
    username: str
    groups: list[str] = field(default_factory=list)
    anonymous: bool = False


ANONYMOUS = Principal(username="anonymous", anonymous=True)

READ_VERBS = frozenset({"get", "list"})


class Authenticator:
    """Scheme composer (usecases/auth/authentication)."""

    def __init__(self, cfg: AuthConfig,
                 oidc_validator: Optional[Callable[[str], Principal]] = None):
        self.cfg = cfg
        self.oidc_validator = oidc_validator
        # positional key->user mapping (environment.go: one user for all keys
        # or one user per key)
        self._key_to_user: dict[str, str] = {}
        if cfg.apikey.enabled:
            users = cfg.apikey.users
            for i, key in enumerate(cfg.apikey.allowed_keys):
                self._key_to_user[key] = users[0] if len(users) == 1 else users[i]

    def principal_from_bearer(self, token: Optional[str]) -> Principal:
        """Resolve an Authorization: Bearer token (or None) to a Principal."""
        if token:
            if self.cfg.apikey.enabled and token in self._key_to_user:
                return Principal(username=self._key_to_user[token])
            if self.cfg.oidc.enabled:
                if self.oidc_validator is None:
                    # fail closed: accepting unverified JWTs would let any
                    # forged token impersonate any user
                    raise UnauthorizedError(
                        "OIDC is enabled but no token validator is configured")
                return self.oidc_validator(token)
            raise UnauthorizedError("invalid token")
        if self.cfg.anonymous.enabled:
            return ANONYMOUS
        raise UnauthorizedError("anonymous access not enabled, credentials required")

    def unverified_claims_validator(self) -> Callable[[str], Principal]:
        """A validator that trusts JWT claims WITHOUT signature verification.
        Only for tests/dev behind an authenticating proxy — production must
        wire a JWKS-backed validator instead."""

        def validate(token: str) -> Principal:
            p = self._parse_jwt_unverified(token)
            if p is None:
                raise UnauthorizedError("malformed bearer token")
            return p

        return validate

    def _parse_jwt_unverified(self, token: str) -> Optional[Principal]:
        parts = token.split(".")
        if len(parts) != 3:
            return None
        try:
            pad = "=" * (-len(parts[1]) % 4)
            claims = json.loads(base64.urlsafe_b64decode(parts[1] + pad))
        except Exception:
            return None
        username = claims.get(self.cfg.oidc.username_claim or "sub")
        if not username:
            return None
        groups = claims.get(self.cfg.oidc.groups_claim) if self.cfg.oidc.groups_claim else []
        return Principal(username=str(username), groups=list(groups or []))


class Authorizer:
    """Admin-list authorization (usecases/auth/authorization/adminlist)."""

    def __init__(self, cfg: AuthzConfig):
        self.cfg = cfg

    def authorize(self, principal: Principal, verb: str, resource: str) -> None:
        """Raise ForbiddenError unless `principal` may `verb` on `resource`.
        With the admin list disabled everything is allowed (reference
        default)."""
        if not self.cfg.admin_list_enabled:
            return
        name = principal.username
        if name in self.cfg.admin_users:
            return
        if verb in READ_VERBS and name in self.cfg.readonly_users:
            return
        raise ForbiddenError(
            f"user {name!r} may not {verb} {resource!r} (adminlist)")
