from weaviate_tpu.auth.auth import (
    AuthError,
    Authenticator,
    Authorizer,
    ForbiddenError,
    Principal,
    UnauthorizedError,
)

__all__ = [
    "AuthError",
    "Authenticator",
    "Authorizer",
    "ForbiddenError",
    "Principal",
    "UnauthorizedError",
]
