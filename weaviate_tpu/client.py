"""Python client for a weaviate_tpu server.

Reference: client/ (the generated Go client used by acceptance tests) and
the weaviate-python-client surface users actually write against. The client
speaks the same public /v1 REST API any third-party client would — nothing
in here reaches into server internals — so it doubles as the acceptance
harness the reference drives through its generated client.

    client = Client("http://localhost:8080", api_key="...")
    client.schema.create_class({"class": "Article", ...})
    client.data_object.create({"title": "hi"}, "Article", vector=[...])
    res = (client.query.get("Article", ["title"])
           .with_near_vector({"vector": [...]})
           .with_limit(5)
           .do())
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Optional, Sequence


class ClientError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class _Transport:
    def __init__(self, base_url: str, api_key: Optional[str] = None,
                 bearer_token: Optional[str] = None, timeout: float = 60.0):
        self.base = base_url.rstrip("/")
        self.token = api_key or bearer_token
        self.timeout = timeout

    def request(self, method: str, path: str, body: Any = None,
                params: Optional[dict] = None) -> tuple[int, Any]:
        url = f"{self.base}{path}"
        if params:
            clean = {k: v for k, v in params.items() if v is not None}
            if clean:
                url += "?" + urllib.parse.urlencode(clean)
        data = json.dumps(body).encode("utf-8") if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                return resp.status, json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:
            raw = e.read()
            payload = None
            if raw:
                try:
                    payload = json.loads(raw)
                except json.JSONDecodeError:
                    payload = raw.decode("utf-8", "replace")
            return e.code, payload

    def expect(self, method: str, path: str, body: Any = None,
               params: Optional[dict] = None, ok=(200, 201, 204)) -> Any:
        status, payload = self.request(method, path, body, params)
        if status not in ok:
            raise ClientError(status, json.dumps(payload) if payload else "")
        return payload


class _SchemaApi:
    def __init__(self, t: _Transport):
        self._t = t

    def get(self) -> dict:
        return self._t.expect("GET", "/v1/schema")

    def create_class(self, class_def: dict) -> dict:
        return self._t.expect("POST", "/v1/schema", class_def)

    def delete_class(self, name: str) -> None:
        self._t.expect("DELETE", f"/v1/schema/{name}")

    def update_config(self, name: str, updated: dict) -> dict:
        return self._t.expect("PUT", f"/v1/schema/{name}", updated)

    def add_property(self, name: str, prop: dict) -> dict:
        return self._t.expect("POST", f"/v1/schema/{name}/properties", prop)

    def get_class_shards(self, name: str) -> list:
        return self._t.expect("GET", f"/v1/schema/{name}/shards")


class _DataObjectApi:
    def __init__(self, t: _Transport):
        self._t = t

    def create(self, properties: dict, class_name: str,
               uuid: Optional[str] = None, vector: Optional[Sequence[float]] = None,
               consistency_level: Optional[str] = None) -> str:
        body: dict = {"class": class_name, "properties": properties}
        if uuid:
            body["id"] = uuid
        if vector is not None:
            body["vector"] = list(map(float, vector))
        out = self._t.expect("POST", "/v1/objects", body,
                             params={"consistency_level": consistency_level})
        return out["id"]

    def get_by_id(self, uuid: str, class_name: Optional[str] = None,
                  with_vector: bool = False,
                  consistency_level: Optional[str] = None) -> Optional[dict]:
        path = (f"/v1/objects/{class_name}/{uuid}" if class_name
                else f"/v1/objects/{uuid}")
        params = {"consistency_level": consistency_level}
        if with_vector:
            params["include"] = "vector"
        status, payload = self._t.request("GET", path, params=params)
        if status == 404:
            return None
        if status != 200:
            raise ClientError(status, json.dumps(payload) if payload else "")
        return payload

    def exists(self, uuid: str, class_name: Optional[str] = None) -> bool:
        path = (f"/v1/objects/{class_name}/{uuid}" if class_name
                else f"/v1/objects/{uuid}")
        status, _ = self._t.request("HEAD", path)
        return status == 204

    def replace(self, properties: dict, class_name: str, uuid: str,
                vector: Optional[Sequence[float]] = None) -> dict:
        body: dict = {"class": class_name, "properties": properties}
        if vector is not None:
            body["vector"] = list(map(float, vector))
        return self._t.expect("PUT", f"/v1/objects/{class_name}/{uuid}", body)

    def update(self, properties: dict, class_name: str, uuid: str) -> None:
        self._t.expect("PATCH", f"/v1/objects/{class_name}/{uuid}",
                       {"class": class_name, "properties": properties})

    def delete(self, uuid: str, class_name: Optional[str] = None,
               consistency_level: Optional[str] = None) -> None:
        path = (f"/v1/objects/{class_name}/{uuid}" if class_name
                else f"/v1/objects/{uuid}")
        self._t.expect("DELETE", path,
                       params={"consistency_level": consistency_level})

    def reference_add(self, from_class: str, from_uuid: str, prop: str,
                      to_class: str, to_uuid: str) -> None:
        beacon = f"weaviate://localhost/{to_class}/{to_uuid}"
        self._t.expect(
            "POST", f"/v1/objects/{from_class}/{from_uuid}/references/{prop}",
            {"beacon": beacon})


class _BatchApi:
    def __init__(self, t: _Transport):
        self._t = t

    def create_objects(self, objects: list[dict],
                       consistency_level: Optional[str] = None) -> list[dict]:
        return self._t.expect("POST", "/v1/batch/objects", {"objects": objects},
                              params={"consistency_level": consistency_level})

    def delete_objects(self, class_name: str, where: dict,
                       dry_run: bool = False, output: str = "minimal") -> dict:
        return self._t.expect("DELETE", "/v1/batch/objects", {
            "match": {"class": class_name, "where": where},
            "dryRun": dry_run, "output": output})


def _gql_value(v: Any) -> str:
    """Python -> GraphQL literal (enum-ish keys handled by callers)."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return json.dumps(v)
    if isinstance(v, str):
        return json.dumps(v)
    if isinstance(v, list):
        return "[" + ", ".join(_gql_value(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ", ".join(f"{k}: {_gql_value(x)}" for k, x in v.items()) + "}"
    if v is None:
        return "null"
    return str(v)


_ENUM_KEYS = {"operator", "order", "fusionType"}


def _gql_args(args: dict) -> str:
    parts = []
    for k, v in args.items():
        if k in _ENUM_KEYS and isinstance(v, str):
            parts.append(f"{k}: {v}")
        elif isinstance(v, dict):
            inner = _gql_args(v)
            parts.append(f"{k}: {{{inner}}}")
        elif isinstance(v, list) and v and isinstance(v[0], dict):
            items = ", ".join(f"{{{_gql_args(x)}}}" for x in v)
            parts.append(f"{k}: [{items}]")
        else:
            parts.append(f"{k}: {_gql_value(v)}")
    return ", ".join(parts)


class QueryBuilder:
    """Fluent Get query (the with_* builder surface users know)."""

    def __init__(self, t: _Transport, class_name: str, properties: Sequence[str]):
        self._t = t
        self.class_name = class_name
        self.properties = list(properties)
        self._args: dict = {}
        self._additional: list[str] = []

    def with_near_vector(self, near: dict) -> "QueryBuilder":
        self._args["nearVector"] = near
        return self

    def with_near_object(self, near: dict) -> "QueryBuilder":
        self._args["nearObject"] = near
        return self

    def with_near_text(self, near: dict) -> "QueryBuilder":
        self._args["nearText"] = near
        return self

    def with_bm25(self, query: str, properties: Optional[list[str]] = None) -> "QueryBuilder":
        arg: dict = {"query": query}
        if properties:
            arg["properties"] = properties
        self._args["bm25"] = arg
        return self

    def with_hybrid(self, query: str, alpha: Optional[float] = None,
                    vector: Optional[list[float]] = None) -> "QueryBuilder":
        arg: dict = {"query": query}
        if alpha is not None:
            arg["alpha"] = alpha
        if vector is not None:
            arg["vector"] = vector
        self._args["hybrid"] = arg
        return self

    def with_where(self, where: dict) -> "QueryBuilder":
        self._args["where"] = where
        return self

    def with_sort(self, sort: list[dict] | dict) -> "QueryBuilder":
        self._args["sort"] = sort if isinstance(sort, list) else [sort]
        return self

    def with_limit(self, limit: int) -> "QueryBuilder":
        self._args["limit"] = limit
        return self

    def with_offset(self, offset: int) -> "QueryBuilder":
        self._args["offset"] = offset
        return self

    def with_after(self, after: str) -> "QueryBuilder":
        self._args["after"] = after
        return self

    def with_ask(self, ask: dict) -> "QueryBuilder":
        self._args["ask"] = ask
        return self

    def with_additional(self, props: Sequence[str] | str) -> "QueryBuilder":
        self._additional.extend([props] if isinstance(props, str) else props)
        return self

    def with_consistency_level(self, level: str) -> "QueryBuilder":
        self._args["consistencyLevel"] = level
        return self

    def build(self) -> str:
        args = f"({_gql_args(self._args)})" if self._args else ""
        fields = " ".join(self.properties)
        if self._additional:
            fields += " _additional { " + " ".join(self._additional) + " }"
        return f"{{ Get {{ {self.class_name}{args} {{ {fields} }} }} }}"

    def do(self) -> list[dict]:
        payload = self._t.expect("POST", "/v1/graphql", {"query": self.build()})
        if payload.get("errors"):
            raise ClientError(422, json.dumps(payload["errors"]))
        return payload["data"]["Get"][self.class_name]


class _QueryApi:
    def __init__(self, t: _Transport):
        self._t = t

    def get(self, class_name: str, properties: Sequence[str]) -> QueryBuilder:
        return QueryBuilder(self._t, class_name, properties)

    def aggregate(self, class_name: str, fields: str) -> dict:
        q = f"{{ Aggregate {{ {class_name} {{ {fields} }} }} }}"
        payload = self.raw(q)
        return payload["data"]["Aggregate"][class_name]

    def raw(self, query: str, variables: Optional[dict] = None) -> dict:
        body: dict = {"query": query}
        if variables:
            body["variables"] = variables
        return self._t.expect("POST", "/v1/graphql", body)


class _BackupApi:
    def __init__(self, t: _Transport):
        self._t = t

    def create(self, backend: str, backup_id: str,
               include: Optional[list[str]] = None,
               exclude: Optional[list[str]] = None) -> dict:
        body: dict = {"id": backup_id}
        if include:
            body["include"] = include
        if exclude:
            body["exclude"] = exclude
        return self._t.expect("POST", f"/v1/backups/{backend}", body)

    def status(self, backend: str, backup_id: str) -> dict:
        return self._t.expect("GET", f"/v1/backups/{backend}/{backup_id}")

    def restore(self, backend: str, backup_id: str,
                include: Optional[list[str]] = None) -> dict:
        body: dict = {}
        if include:
            body["include"] = include
        return self._t.expect("POST", f"/v1/backups/{backend}/{backup_id}/restore", body)

    def restore_status(self, backend: str, backup_id: str) -> dict:
        return self._t.expect("GET", f"/v1/backups/{backend}/{backup_id}/restore")


class _ClassificationApi:
    def __init__(self, t: _Transport):
        self._t = t

    def schedule(self, body: dict) -> dict:
        return self._t.expect("POST", "/v1/classifications", body)

    def get(self, job_id: str) -> dict:
        return self._t.expect("GET", f"/v1/classifications/{job_id}")


class _ClusterApi:
    def __init__(self, t: _Transport):
        self._t = t

    def get_nodes_status(self) -> list[dict]:
        return self._t.expect("GET", "/v1/nodes")["nodes"]


class _ModulesApi:
    """User-facing module endpoints under /v1/modules/<module>/ (the
    contextionary extensions surface)."""

    def __init__(self, t: _Transport):
        self._t = t

    def create_extension(self, module: str, concept: str, definition: str,
                         weight: float = 1.0) -> dict:
        return self._t.expect(
            "POST", f"/v1/modules/{module}/extensions",
            {"concept": concept, "definition": definition, "weight": weight})

    def get_extensions(self, module: str) -> list[dict]:
        return self._t.expect(
            "GET", f"/v1/modules/{module}/extensions")["extensions"]

    def get_concept(self, module: str, concept: str) -> dict:
        return self._t.expect(
            "GET",
            f"/v1/modules/{module}/concepts/{urllib.parse.quote(concept)}")


class Client:
    def __init__(self, url: str = "http://localhost:8080",
                 api_key: Optional[str] = None,
                 bearer_token: Optional[str] = None, timeout: float = 60.0):
        self._t = _Transport(url, api_key, bearer_token, timeout)
        self.schema = _SchemaApi(self._t)
        self.data_object = _DataObjectApi(self._t)
        self.batch = _BatchApi(self._t)
        self.query = _QueryApi(self._t)
        self.backup = _BackupApi(self._t)
        self.classification = _ClassificationApi(self._t)
        self.cluster = _ClusterApi(self._t)
        self.modules = _ModulesApi(self._t)

    def is_ready(self) -> bool:
        try:
            status, _ = self._t.request("GET", "/v1/.well-known/ready")
            return status == 200
        except OSError:
            return False

    def is_live(self) -> bool:
        try:
            status, _ = self._t.request("GET", "/v1/.well-known/live")
            return status == 200
        except OSError:
            return False

    def get_meta(self) -> dict:
        return self._t.expect("GET", "/v1/meta")
