from weaviate_tpu.server.app import App
from weaviate_tpu.server.rest import RestServer

__all__ = ["App", "RestServer"]
