"""gRPC Search service.

Reference: adapters/handlers/grpc/server.go — `StartAndListen` (:35) exposes
`Weaviate.Search` (:66): build traverser.GetParams from the proto
(searchParamsFromProto, :137), call Traverser.GetClass, marshal results
(searchResultsToProto, :85).

TPU extension: BatchSearch maps onto Traverser.get_class_batched so N
concurrent kNN queries ride one device dispatch instead of N.
"""

from __future__ import annotations

import json
import time
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.grpcapi import weaviate_pb2 as pb
from weaviate_tpu.monitoring import incidents, tracing
from weaviate_tpu.serving import robustness
from weaviate_tpu.server import reply_native
from weaviate_tpu.usecases.traverser import GetParams

_SERVICE = "weaviatetpu.v1.Weaviate"


def _request_meta(context) -> tuple[str, Optional[str], float, float,
                                    Optional[str]]:
    """(request_id, traceparent, explicit_timeout_ms, transport_timeout_ms,
    raw_tenant) from invocation metadata. The request id (inbound
    ``x-request-id`` honored, else generated) is the gRPC twin of the REST
    X-Request-Id header; `_set_reply_meta` echoes it back. The EXPLICIT
    deadline is the ``x-request-timeout-ms`` metadata entry (the REST
    header's twin — an intentional caller override, may extend past the
    config default); the TRANSPORT deadline is
    ``context.time_remaining()`` — usually just the stub's generous
    default (e.g. 30 s), so the servicer treats it as a CAP on the config
    default, never as an override: an implicit client timeout must not
    silently opt the request out of the operator's QUERY_TIMEOUT_MS. 0 =
    absent for either. ``raw_tenant`` is the UNVALIDATED ``x-tenant-id``
    entry — the servicer validates it (robustness.validate_tenant_id)
    and aborts INVALID_ARGUMENT on an injection-shaped value, the REST
    400's twin."""
    md = {}
    try:
        md = {k.lower(): v for k, v in (context.invocation_metadata() or ())}
    except Exception:  # noqa: BLE001 — metadata is best-effort plumbing
        pass
    transport_ms = 0.0
    try:
        tr = context.time_remaining()
        if tr is not None:
            transport_ms = float(tr) * 1000.0
    except Exception:  # noqa: BLE001 — deadline introspection is optional
        pass
    explicit_ms = 0.0
    raw = md.get("x-request-timeout-ms")
    if raw:
        try:
            explicit_ms = float(raw)
        except ValueError:
            pass  # malformed metadata entry: ignore, keep the defaults
    return tracing.clean_request_id(md.get("x-request-id")), \
        md.get("traceparent"), explicit_ms, transport_ms, \
        md.get("x-tenant-id")


def _set_reply_meta(context, rid: str, trace) -> None:
    """Trailing metadata on EVERY reply, tracing on or off: the request id
    for log joining, plus — when this request was traced — the server's
    W3C traceparent so the caller can join its own trace to ours."""
    md = [("x-request-id", rid)]
    if trace is not None:
        md.append(("traceparent", trace.traceparent()))
    try:
        context.set_trailing_metadata(tuple(md))
    except Exception:  # noqa: BLE001 — metadata is best-effort plumbing
        pass


def _collect_fast(results, req: pb.SearchRequest):
    """(raws, dists, certs) for the native marshaller — ONLY when every
    result can be emitted verbatim from its storage image (no property
    filtering, no vectors, no scores, objects pristine); None otherwise.
    The single source of fast-path eligibility for both the per-reply and
    whole-batch builders."""
    if req.properties or "vector" in req.additional_properties:
        return None
    raws, dists, certs = [], [], []
    for r in results:
        raw = r.raw_pristine()
        if raw is None or r.score is not None or r.explain_score:
            return None
        raws.append(raw)
        dists.append(r.distance)
        certs.append(r.certainty)
    return raws, dists, certs


def fast_reply_bytes(results, req: pb.SearchRequest,
                     took: float) -> Optional[bytes]:
    """Serialized SearchReply via the native marshaller, or None => use the
    upb path (result_to_proto), which is always correct."""
    triple = _collect_fast(results, req)
    if triple is None:
        return None
    return reply_native.build_search_reply(*triple, took)


def params_from_proto(req: pb.SearchRequest) -> GetParams:
    """searchParamsFromProto twin (server.go:137)."""
    near_vector = None
    if req.HasField("near_vector") and len(req.near_vector.vector):
        near_vector = {"vector": list(req.near_vector.vector)}
        if req.near_vector.HasField("certainty"):
            near_vector["certainty"] = req.near_vector.certainty
        if req.near_vector.HasField("distance"):
            near_vector["distance"] = req.near_vector.distance
    near_object = None
    if req.HasField("near_object") and req.near_object.id:
        near_object = {"id": req.near_object.id}
        if req.near_object.HasField("certainty"):
            near_object["certainty"] = req.near_object.certainty
        if req.near_object.HasField("distance"):
            near_object["distance"] = req.near_object.distance
    bm25 = None
    if req.HasField("bm25") and req.bm25.query:
        bm25 = {"query": req.bm25.query}
        if req.bm25.properties:
            bm25["properties"] = list(req.bm25.properties)
    hybrid = None
    if req.HasField("hybrid") and (req.hybrid.query or len(req.hybrid.vector)):
        hybrid = {"query": req.hybrid.query}
        if len(req.hybrid.vector):
            hybrid["vector"] = list(req.hybrid.vector)
        if req.hybrid.HasField("alpha"):
            hybrid["alpha"] = req.hybrid.alpha
        if req.hybrid.fusion_type:
            hybrid["fusionType"] = req.hybrid.fusion_type
    filters = None
    if req.where_json:
        filters = LocalFilter.from_dict(json.loads(req.where_json))
    include_vector = "vector" in req.additional_properties
    return GetParams(
        class_name=req.class_name,
        properties=list(req.properties),
        filters=filters,
        near_vector=near_vector,
        near_object=near_object,
        keyword_ranking=bm25,
        hybrid=hybrid,
        limit=int(req.limit) or 0,
        offset=int(req.offset),
        include_vector=include_vector,
        consistency_level=req.consistency_level or None,
    )


def result_to_proto(r, req: pb.SearchRequest) -> pb.SearchResult:
    """searchResultsToProto twin (server.go:85)."""
    obj = r.obj
    if req.properties:
        props = obj.properties or {}
        props_json = json.dumps(
            {k: v for k, v in props.items() if k in req.properties},
            default=str)
    else:
        # unfiltered replies reuse the stored JSON verbatim — the hot path
        # never parses or re-serializes properties (props_json_bytes is None
        # once the dict was materialized/mutated)
        raw = obj.props_json_bytes()
        props_json = (raw.decode("utf-8") if raw is not None
                      else json.dumps(obj.properties or {}, default=str))
    out = pb.SearchResult(
        id=obj.uuid,
        properties_json=props_json,
        creation_time_unix=obj.creation_time_unix,
        last_update_time_unix=obj.last_update_time_unix,
    )
    addl = set(req.additional_properties)
    if r.distance is not None:
        out.distance = float(r.distance)
    if r.certainty is not None:
        out.certainty = float(r.certainty)
    if r.score is not None:
        out.score = float(r.score)
    if r.explain_score:
        out.explain_score = r.explain_score
    if "vector" in addl and obj.vector is not None:
        out.vector.extend(float(x) for x in obj.vector)
    return out


class SearchServicer:
    def __init__(self, app):
        self.app = app

    def _timeout_ms(self, explicit_ms: float, transport_ms: float) -> float:
        """The effective deadline: an EXPLICIT x-request-timeout-ms wins
        outright (the REST header's semantics — an intentional override
        may extend past the default); otherwise the config default capped
        by the transport deadline (the stub's implicit 30 s timeout must
        not override the operator's QUERY_TIMEOUT_MS — see
        _request_meta); 0 = unbounded."""
        if explicit_ms > 0:
            return explicit_ms
        bounds = [v for v in (transport_ms,
                              self.app.config.robustness.query_timeout_ms)
                  if v > 0]
        return min(bounds) if bounds else 0.0

    @staticmethod
    def _note_slo(outcome: str, start: float,
                  tenant: Optional[str] = None) -> None:
        """SLO accounting (monitoring/incidents.py): the gRPC twin of the
        REST _dispatch classification — one-comparison no-op when the
        plane is off, exception-guarded internally."""
        incidents.note_request(
            outcome, (time.perf_counter() - start) * 1000.0, tenant)

    def _abort_lifecycle(self, context, rid: str, e: BaseException,
                         trace=None) -> None:
        """Map robustness errors to their canonical gRPC codes. Shed
        replies carry retry-after-s in trailing metadata (the Retry-After
        twin) so clients back off instead of retrying in lockstep.
        set_trailing_metadata REPLACES what _set_reply_meta installed, so
        the request id AND (for traced requests) the traceparent are
        re-included — the error-reply header-echo contract holds on the
        shed path too."""
        if isinstance(e, robustness.OverloadedError):
            md = [("x-request-id", rid),
                  ("retry-after-s", f"{e.retry_after_s:.3f}")]
            if trace is not None:
                md.append(("traceparent", trace.traceparent()))
            try:
                context.set_trailing_metadata(tuple(md))
            except Exception:  # noqa: BLE001 — metadata is best-effort
                pass
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))

    def Search(self, request: pb.SearchRequest, context) -> pb.SearchReply:
        start = time.perf_counter()
        rid, traceparent, expl_tmo, trans_tmo, raw_tenant = \
            _request_meta(context)
        with tracing.request("grpc", "Search", traceparent=traceparent,
                             request_id=rid,
                             class_name=request.class_name) as tr:
            _set_reply_meta(context, rid, tr)
            try:
                # inside the traced scope, after _set_reply_meta: the
                # invalid-tenant abort must carry the request-id /
                # traceparent echo like every other error reply
                tenant = robustness.validate_tenant_id(raw_tenant)
            except ValueError as e:
                # caller-mistake aborts count as "client" like the REST
                # twin — identical workloads must burn identically
                self._note_slo("client", start)
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                return
            if tenant:
                tracing.annotate_current("tenant", tenant)
            try:
                params = params_from_proto(request)
            except Exception as e:
                self._note_slo("client", start, tenant)
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                return
            try:
                with robustness.tenant_concurrency(tenant), \
                        robustness.tenant_scope(tenant), \
                        robustness.deadline_scope(
                            self._timeout_ms(expl_tmo, trans_tmo)):
                    results = self.app.traverser.get_class(params)
            except (robustness.DeadlineExceededError,
                    robustness.OverloadedError) as e:
                self._note_slo(
                    "shed" if isinstance(e, robustness.OverloadedError)
                    else "deadline", start, tenant)
                self._abort_lifecycle(context, rid, e, trace=tr)
                return
            except ValueError as e:
                self._note_slo("client", start, tenant)
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                return
            except Exception as e:
                self._note_slo("error", start, tenant)
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")
                return
            self._note_slo("ok", start, tenant)
            took = time.perf_counter() - start
            fast = fast_reply_bytes(results, request, took)
            if fast is not None:
                return fast  # pre-serialized; the passthrough serializer ships it
            reply = pb.SearchReply(took_seconds=took)
            reply.results.extend(result_to_proto(r, request) for r in results)
            return reply

    def _raw_batch_lane(self, request: pb.BatchSearchRequest,
                        start: float) -> Optional[bytes]:
        """Zero-object serving lane: when every slot is a plain same-class
        nearVector query with verbatim replies, the whole batch runs as
        device search -> packed native point-gets -> packed native reply
        marshalling, with no per-result Python objects anywhere. None =>
        the general path (which is always correct) serves the batch."""
        reqs = request.requests
        if not reqs:
            return None
        f0 = reqs[0]
        cls, limit = f0.class_name, int(f0.limit)
        explorer = self.app.traverser.explorer
        k = limit or explorer.query_limit
        if k > explorer.max_results:
            return None
        dim = len(f0.near_vector.vector) if f0.HasField("near_vector") else 0
        if dim == 0:
            return None
        for r in reqs:
            if (r.class_name != cls or int(r.limit) != limit or r.offset
                    or r.properties or r.additional_properties or r.where_json
                    or r.consistency_level
                    or not r.HasField("near_vector")
                    or len(r.near_vector.vector) != dim
                    or r.near_vector.HasField("certainty")
                    or r.near_vector.HasField("distance")
                    or r.HasField("near_object") or r.HasField("bm25")
                    or r.HasField("hybrid")):
                return None
        resolved = self.app.schema.resolve_class_name(cls)
        idx = self.app.db.get_index(resolved) if resolved else None
        if idx is None:
            return None
        shard = idx.single_local_shard()
        if shard is None:
            return None
        if not shard.raw_plane_ready():
            return None  # before ANY device work: the general path searches once
        q = np.empty((len(reqs), dim), dtype=np.float32)
        for i, r in enumerate(reqs):
            q[i] = np.fromiter(r.near_vector.vector, np.float32, dim)
        try:
            out = shard.search_raw_packed(q, k)
        except Exception:  # noqa: BLE001 — the general path re-runs + reports
            return None
        if out is None:
            return None
        vbuf, voffs, vflags, flat_dists, counts = out
        return reply_native.build_batch_reply_packed(
            vbuf, voffs, vflags, flat_dists, counts,
            time.perf_counter() - start)

    def BatchSearch(self, request: pb.BatchSearchRequest, context) -> pb.BatchSearchReply:
        """Per-slot error isolation end to end: a malformed request or failed
        query yields a reply with error_message; the other slots still ride
        the shared device dispatch."""
        start = time.perf_counter()
        rid, traceparent, expl_tmo, trans_tmo, raw_tenant = \
            _request_meta(context)
        with tracing.request("grpc", "BatchSearch", traceparent=traceparent,
                             request_id=rid,
                             slots=len(request.requests)) as tr:
            _set_reply_meta(context, rid, tr)
            try:
                # traced + metadata-echoed like the Search twin above
                tenant = robustness.validate_tenant_id(raw_tenant)
            except ValueError as e:
                self._note_slo("client", start)
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                return
            if tenant:
                tracing.annotate_current("tenant", tenant)
            try:
                # ONE deadline scopes the whole batch (the RPC is the unit
                # the caller is waiting on); per-slot shed/expired errors
                # land in their slot's error_message via get_class_batched
                with robustness.tenant_concurrency(tenant), \
                        robustness.tenant_scope(tenant), \
                        robustness.deadline_scope(
                            self._timeout_ms(expl_tmo, trans_tmo)):
                    reply = self._batch_search(request, start)
                self._note_slo("ok", start, tenant)
                return reply
            except (robustness.DeadlineExceededError,
                    robustness.OverloadedError) as e:
                self._note_slo(
                    "shed" if isinstance(e, robustness.OverloadedError)
                    else "deadline", start, tenant)
                self._abort_lifecycle(context, rid, e, trace=tr)
            except ValueError as e:
                self._note_slo("client", start, tenant)
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except Exception as e:
                # the Search-twin classification: a batch-only outage must
                # spend availability budget like a single-query one
                self._note_slo("error", start, tenant)
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")

    def _batch_search(self, request: pb.BatchSearchRequest, start: float):
        # with the coalescer on, a NARROW batch (up to max_request_rows —
        # the widest request the coalescer admits) skips the raw lane: its
        # own dispatch would run underfilled, while the general path merges
        # the slots with other in-flight requests into one padded dispatch.
        # STRICTLY wider batches keep the raw lane — they already fill a
        # dispatch and its reply marshalling is strictly cheaper.
        co = getattr(self.app, "coalescer", None)
        if co is None or len(request.requests) > co.max_request_rows:
            raw = self._raw_batch_lane(request, start)
            if raw is not None:
                return raw
        slot_params: list = [None] * len(request.requests)
        parse_errs: dict[int, str] = {}
        for i, r in enumerate(request.requests):
            try:
                slot_params[i] = params_from_proto(r)
            except Exception as e:
                parse_errs[i] = str(e)
        valid = [(i, p) for i, p in enumerate(slot_params) if i not in parse_errs]
        results = self.app.traverser.get_class_batched([p for _, p in valid]) if valid else []
        took = time.perf_counter() - start
        slot_out: dict[int, object] = {i: res for (i, _), res in zip(valid, results)}
        if not parse_errs and len(valid) == len(request.requests):
            whole = self._whole_batch_fast(request, slot_out, took)
            if whole is not None:
                return whole
        # assemble the outer BatchSearchReply as wire bytes so fast-path
        # slots (native-marshalled, see fast_reply_bytes) splice in without
        # ever becoming Python message objects; slow slots serialize via upb
        # and splice the same way — concatenated length-delimited field 1
        # entries ARE the repeated `replies` encoding
        chunks: list[bytes] = []
        for i, req in enumerate(request.requests):
            body: Optional[bytes] = None
            if i not in parse_errs:
                slot = slot_out.get(i)
                if slot is not None and not isinstance(slot, Exception):
                    body = fast_reply_bytes(slot, req, took)
            if body is None:
                one = pb.SearchReply(took_seconds=took)
                if i in parse_errs:
                    one.error_message = parse_errs[i]
                else:
                    slot = slot_out.get(i)
                    if isinstance(slot, Exception):
                        one.error_message = str(slot)
                    elif slot is not None:
                        one.results.extend(result_to_proto(r, req) for r in slot)
                body = one.SerializeToString()
            chunks.append(b"\x0a" + reply_native.varint(len(body)) + body)
        return b"".join(chunks)

    def _whole_batch_fast(self, request, slot_out, took) -> Optional[bytes]:
        """One native call serializes the ENTIRE BatchSearchReply when every
        slot is fast-eligible; None falls back to per-slot assembly."""
        raws: list[bytes] = []
        dists: list = []
        certs: list = []
        counts: list[int] = []
        for i, req in enumerate(request.requests):
            slot = slot_out.get(i)
            if slot is None or isinstance(slot, Exception):
                return None
            triple = _collect_fast(slot, req)
            if triple is None:
                return None
            raws.extend(triple[0])
            dists.extend(triple[1])
            certs.extend(triple[2])
            counts.append(len(triple[0]))
        return reply_native.build_batch_reply(raws, dists, certs, counts, took)


def _serialize_passthrough(msg):
    """Responses are either upb messages or pre-serialized wire bytes from
    the native marshaller — both ship as-is."""
    if isinstance(msg, (bytes, bytearray)):
        return bytes(msg)
    return msg.SerializeToString()


def _handlers(servicer) -> grpc.GenericRpcHandler:
    return grpc.method_handlers_generic_handler(_SERVICE, {
        "Search": grpc.unary_unary_rpc_method_handler(
            servicer.Search,
            request_deserializer=pb.SearchRequest.FromString,
            response_serializer=_serialize_passthrough,
        ),
        "BatchSearch": grpc.unary_unary_rpc_method_handler(
            servicer.BatchSearch,
            request_deserializer=pb.BatchSearchRequest.FromString,
            response_serializer=_serialize_passthrough,
        ),
    })


class GrpcServer:
    """StartAndListen twin (server.go:35)."""

    def __init__(self, app, host: str = "127.0.0.1", port: int = 0, max_workers: int = 16):
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        self.server.add_generic_rpc_handlers((_handlers(SearchServicer(app)),))
        self.port = self.server.add_insecure_port(f"{host}:{port}")

    def start(self) -> None:
        self.server.start()

    def stop(self, grace: Optional[float] = 1.0) -> None:
        self.server.stop(grace).wait()


class SearchClient:
    """Minimal client (the generated-stub equivalent, for tests/tools)."""

    def __init__(self, target: str):
        self.channel = grpc.insecure_channel(target)
        self._search = self.channel.unary_unary(
            f"/{_SERVICE}/Search",
            request_serializer=pb.SearchRequest.SerializeToString,
            response_deserializer=pb.SearchReply.FromString,
        )
        self._batch = self.channel.unary_unary(
            f"/{_SERVICE}/BatchSearch",
            request_serializer=pb.BatchSearchRequest.SerializeToString,
            response_deserializer=pb.BatchSearchReply.FromString,
        )

    def search(self, request: pb.SearchRequest, timeout: float = 30.0,
               metadata=None) -> pb.SearchReply:
        # metadata: e.g. (("x-request-timeout-ms", "50"),) — the server-side
        # deadline (shed/expire without a client-side transport deadline)
        return self._search(request, timeout=timeout, metadata=metadata)

    def batch_search(self, request: pb.BatchSearchRequest,
                     timeout: float = 60.0,
                     metadata=None) -> pb.BatchSearchReply:
        return self._batch(request, timeout=timeout, metadata=metadata)

    def close(self) -> None:
        self.channel.close()
