"""REST API: the full /v1 surface on a threaded stdlib HTTP server.

Reference: adapters/handlers/rest/ — go-swagger generated ops wired in
configure_api.go:293-300 (objects CRUD, batch, schema, graphql, backups,
nodes, meta, well-known, classifications). Here the routing is one regex
table; handlers translate HTTP <-> the use-case managers exactly like the
reference's handlers_*.go files, including Weaviate's error envelope
`{"error": [{"message": ...}]}`.

Threaded (not async) on purpose: handlers call synchronous use-case code
whose hot path is a device dispatch; the GIL releases during device work so
concurrent queries still batch. /metrics is mounted on the main port and,
when PROMETHEUS_MONITORING_ENABLED, on its own port (configure_api.go:116).
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from weaviate_tpu.auth import ForbiddenError, UnauthorizedError
from weaviate_tpu.monitoring import incidents, tracing
from weaviate_tpu.serving import robustness
from weaviate_tpu.schema.manager import SchemaError
from weaviate_tpu.usecases.objects import NotFoundError, ObjectsError
from weaviate_tpu.version import __version__ as VERSION

_UUID_RE = r"[0-9a-fA-F-]{36}"


class HTTPError(Exception):
    def __init__(self, status: int, message: str = ""):
        self.status = status
        self.message = message


def _err_body(message: str) -> dict:
    return {"error": [{"message": message}]}


class _Routes:
    def __init__(self):
        self.table: list[tuple[str, re.Pattern, str]] = []

    def add(self, method: str, pattern: str, name: str):
        self.table.append((method, re.compile("^" + pattern + "$"), name))

    def match(self, method: str, path: str):
        allowed = []
        for m, pat, name in self.table:
            mt = pat.match(path)
            if mt:
                if m == method or (m == "GET" and method == "HEAD" and name == "meta"):
                    return name, mt
                allowed.append(m)
        if allowed:
            raise HTTPError(405, f"method {method} not allowed")
        raise HTTPError(404, f"no route for {path}")


ROUTES = _Routes()
for _m, _p, _n in [
    ("GET", r"/v1/meta", "meta"),
    ("GET", r"/v1/\.well-known/openid-configuration", "openid"),
    ("GET", r"/v1/\.well-known/live", "live"),
    ("GET", r"/v1/\.well-known/ready", "ready"),
    ("GET", r"/v1/schema", "schema_list"),
    ("POST", r"/v1/schema", "schema_create"),
    ("GET", r"/v1/schema/(?P<cls>[^/]+)", "schema_get"),
    ("PUT", r"/v1/schema/(?P<cls>[^/]+)", "schema_update"),
    ("DELETE", r"/v1/schema/(?P<cls>[^/]+)", "schema_delete"),
    ("POST", r"/v1/schema/(?P<cls>[^/]+)/properties", "schema_add_property"),
    ("GET", r"/v1/schema/(?P<cls>[^/]+)/shards", "shards_get"),
    ("PUT", r"/v1/schema/(?P<cls>[^/]+)/shards/(?P<shard>[^/]+)", "shard_update"),
    ("GET", r"/v1/objects", "objects_list"),
    ("POST", r"/v1/objects", "objects_create"),
    ("POST", r"/v1/objects/validate", "objects_validate"),
    # class-scoped must come before legacy so /v1/objects/Class/uuid wins
    ("GET", rf"/v1/objects/(?P<cls>[^/]+)/(?P<id>{_UUID_RE})", "object_get"),
    ("HEAD", rf"/v1/objects/(?P<cls>[^/]+)/(?P<id>{_UUID_RE})", "object_head"),
    ("PUT", rf"/v1/objects/(?P<cls>[^/]+)/(?P<id>{_UUID_RE})", "object_put"),
    ("PATCH", rf"/v1/objects/(?P<cls>[^/]+)/(?P<id>{_UUID_RE})", "object_patch"),
    ("DELETE", rf"/v1/objects/(?P<cls>[^/]+)/(?P<id>{_UUID_RE})", "object_delete"),
    ("GET", rf"/v1/objects/(?P<id>{_UUID_RE})", "object_get"),
    ("HEAD", rf"/v1/objects/(?P<id>{_UUID_RE})", "object_head"),
    ("PUT", rf"/v1/objects/(?P<id>{_UUID_RE})", "object_put"),
    ("PATCH", rf"/v1/objects/(?P<id>{_UUID_RE})", "object_patch"),
    ("DELETE", rf"/v1/objects/(?P<id>{_UUID_RE})", "object_delete"),
    ("POST", rf"/v1/objects/(?P<cls>[^/]+)/(?P<id>{_UUID_RE})/references/(?P<prop>[^/]+)", "ref_add"),
    ("PUT", rf"/v1/objects/(?P<cls>[^/]+)/(?P<id>{_UUID_RE})/references/(?P<prop>[^/]+)", "ref_put"),
    ("DELETE", rf"/v1/objects/(?P<cls>[^/]+)/(?P<id>{_UUID_RE})/references/(?P<prop>[^/]+)", "ref_delete"),
    ("POST", r"/v1/batch/objects", "batch_objects"),
    ("DELETE", r"/v1/batch/objects", "batch_delete"),
    ("POST", r"/v1/batch/references", "batch_references"),
    ("POST", r"/v1/graphql", "graphql"),
    ("POST", r"/v1/graphql/batch", "graphql_batch"),
    ("GET", r"/v1/nodes", "nodes"),
    ("GET", r"/metrics", "metrics"),
    # completed-request trace ring (monitoring/tracing.py) — same
    # authorizer as the pprof surface below: span trees name classes and
    # filters and are not for anonymous remote clients
    ("GET", r"/debug/traces", "debug_traces"),
    # rolling perf-attribution window (monitoring/perf.py): roofline,
    # duty cycle, host-overhead ledger percentiles — same authorizer as
    # pprof (it names classes and exposes serving internals)
    ("GET", r"/debug/perf", "debug_perf"),
    # shadow recall auditor window (monitoring/quality.py): online
    # recall/RBO/distance-error estimates per tier + audit accounting —
    # the quality twin of /debug/perf, same authorizer
    ("GET", r"/debug/quality", "debug_quality"),
    # per-index/shard health introspection (index/tpu.py health()):
    # tombstone fractions, snapshot/staged generations, PQ state,
    # cache residency — same authorizer (it names classes)
    ("GET", r"/debug/index", "debug_index"),
    # device/host/disk byte ledger (monitoring/memory.py): per-component
    # bytes, write-path lifecycle, exhaustion forecast — same authorizer
    ("GET", r"/debug/memory", "debug_memory"),
    # incident flight recorder + ops-event journal (monitoring/
    # incidents.py): recent bundle index + journal tail, and an explicit
    # dump trigger — same authorizer as pprof (bundles name classes,
    # tenants, and config)
    ("GET", r"/debug/incidents", "debug_incidents"),
    ("POST", r"/debug/incidents/dump", "debug_incidents_dump"),
    # config-declared SLOs: multi-window burn rates + budget remaining
    ("GET", r"/debug/slo", "debug_slo"),
    # self-tuning control plane (serving/controller.py): per-controller
    # state, knob values vs configured defaults, brownout-ladder stage,
    # recent actuations — same authorizer (it names tenants and config)
    ("GET", r"/debug/controllers", "debug_controllers"),
    # the debug surface's index page: every /debug endpoint, one line each
    ("GET", r"/debug/?", "debug_root"),
    # always-mounted profiling surface (configure_api.go:25 net/http/pprof)
    ("GET", r"/debug/pprof/?", "pprof_index"),
    ("GET", r"/debug/pprof/profile", "pprof_profile"),
    ("GET", r"/debug/pprof/trace", "pprof_trace"),
    ("GET", r"/debug/pprof/goroutine", "pprof_goroutine"),
    ("GET", r"/debug/pprof/heap", "pprof_heap"),
    ("GET", r"/debug/pprof/cmdline", "pprof_cmdline"),
    ("POST", r"/v1/backups/(?P<backend>[^/]+)", "backup_create"),
    ("GET", r"/v1/backups/(?P<backend>[^/]+)/(?P<id>[^/]+)", "backup_status"),
    ("POST", r"/v1/backups/(?P<backend>[^/]+)/(?P<id>[^/]+)/restore", "backup_restore"),
    ("GET", r"/v1/backups/(?P<backend>[^/]+)/(?P<id>[^/]+)/restore", "backup_restore_status"),
    ("POST", r"/v1/classifications", "classification_create"),
    ("GET", r"/v1/classifications/(?P<id>[^/]+)", "classification_get"),
    # module REST extensions: /v1/modules/<module>/<module-defined subpath>
    # (the reference mounts each module's RootHandler at this prefix,
    # middlewares.go:66)
    ("GET", r"/v1/modules/(?P<module>[^/]+)(?P<rest>/.*)", "module_rest"),
    ("POST", r"/v1/modules/(?P<module>[^/]+)(?P<rest>/.*)", "module_rest"),
    ("PUT", r"/v1/modules/(?P<module>[^/]+)(?P<rest>/.*)", "module_rest"),
    ("DELETE", r"/v1/modules/(?P<module>[^/]+)(?P<rest>/.*)", "module_rest"),
]:
    ROUTES.add(_m, _p, _n)

_WRITE_METHODS = {"POST": "create", "PUT": "update", "PATCH": "update", "DELETE": "delete"}


class Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    app = None  # injected by RestServer

    # silence default stderr logging
    def log_message(self, fmt, *args):
        pass

    # -- plumbing ------------------------------------------------------------

    def _json_body(self):
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        self._body_consumed = True
        if not raw:
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise HTTPError(400, f"invalid json: {e}") from None

    def _drain_body(self):
        """Consume an unread request body so an early error reply doesn't
        desynchronize the keep-alive stream (the next request would otherwise
        parse the stale body bytes as its request line)."""
        if getattr(self, "_body_consumed", False):
            return
        self._body_consumed = True
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except (TypeError, ValueError):
            length = 0
        while length > 0:
            chunk = self.rfile.read(min(length, 65536))
            if not chunk:
                break
            length -= len(chunk)

    def _reply(self, status: int, body=None, raw: Optional[bytes] = None,
               content_type: str = "application/json"):
        self._drain_body()
        data = raw if raw is not None else (
            b"" if body is None else json.dumps(body).encode())
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        # every response (success AND error) carries the request id —
        # inbound X-Request-Id honored, else generated — so client logs
        # join to server traces/slow-query lines without tracing enabled
        rid = getattr(self, "_request_id", None)
        if rid:
            self.send_header("X-Request-Id", rid)
        # shed responses (429) carry the server's drain estimate so
        # well-behaved clients back off instead of retrying in lockstep
        ra = getattr(self, "_retry_after", None)
        if ra is not None:
            self.send_header("Retry-After", str(max(1, math.ceil(ra))))
        # ...and a traced request emits its W3C traceparent (this server's
        # root span id), so a caller can join its own outbound trace to
        # the /debug/traces entry this request produced
        tp = getattr(self, "_traceparent", None)
        if tp:
            self.send_header("traceparent", tp)
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    def _principal(self):
        auth = self.headers.get("Authorization") or ""
        token = auth[7:] if auth.startswith("Bearer ") else None
        return self.app.authenticator.principal_from_bearer(token)

    # plumbing/introspection routes never open a trace: they are not the
    # serving path, and tracing /debug/traces would feed the ring with
    # reads of itself
    _UNTRACED = frozenset({
        "live", "ready", "openid", "metrics", "debug_traces", "debug_perf",
        "debug_quality", "debug_index", "debug_memory", "debug_root",
        "debug_incidents", "debug_incidents_dump", "debug_slo",
        "debug_controllers",
        "pprof_index", "pprof_profile", "pprof_trace", "pprof_goroutine",
        "pprof_heap", "pprof_cmdline",
    })

    def _request_timeout_ms(self, route: str) -> float:
        """Per-request deadline in ms: the caller's X-Request-Timeout-Ms
        wins, else the config default (QUERY_TIMEOUT_MS); <= 0 (or a
        plumbing route) = unbounded. A malformed header is a caller error,
        not a silently-unbounded request."""
        if route in self._UNTRACED:
            return 0.0
        hdr = self.headers.get("X-Request-Timeout-Ms")
        if hdr:
            try:
                v = float(hdr)
            except ValueError:
                raise HTTPError(
                    400, f"invalid X-Request-Timeout-Ms: {hdr!r}") from None
            if v > 0:
                return v
            # <= 0 falls through to the config default (the gRPC twin's
            # semantics): a client cannot opt OUT of the operator's
            # deadline by sending 0
        return self.app.config.robustness.query_timeout_ms

    def _dispatch(self):
        self._body_consumed = False
        # request id before anything can fail: the error envelope carries
        # the header too (satellite contract: EVERY response has one);
        # cleaned — an inbound id is echoed into a response header and must
        # not be able to smuggle CR/LF
        self._request_id = tracing.clean_request_id(
            self.headers.get("X-Request-Id"))
        self._traceparent = None
        self._retry_after = None
        try:
            # tenant identity: X-Tenant-Id is an ACCOUNTING key (budgets,
            # metrics), so unlike X-Request-Id an invalid value is a 400,
            # never cleaned-and-used — two spellings of one tenant must
            # not split its budget, and injection bytes must not reach a
            # metric label or log line
            try:
                tenant = robustness.validate_tenant_id(
                    self.headers.get("X-Tenant-Id"))
            except ValueError as e:
                raise HTTPError(400, str(e)) from None
            parsed = urlparse(self.path)
            self.query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            name, mt = ROUTES.match(self.command, parsed.path)
            # unlike the reference's unauthenticated DefaultServeMux
            # side-mount (configure_api.go:25), pprof goes through the same
            # authorizer as the data plane — thread stacks and CPU profiles
            # are not for anonymous remote clients
            if name not in ("live", "ready", "openid", "metrics"):
                principal = self._principal()
                verb = _WRITE_METHODS.get(self.command, "get")
                self.app.authorizer.authorize(principal, verb, parsed.path)
            handler = getattr(self, "h_" + name)
            # the deadline scope wraps the WHOLE handler (serving/
            # robustness.py): it propagates via contextvars through the
            # graphql executor and traverser into coalescer lanes and
            # shard dispatches; 0 => a no-op scope. The tenant scope rides
            # the same plumbing (None => class-name default downstream);
            # the concurrency gate sheds an over-parallel tenant HERE,
            # before the handler does any per-request work.
            # SLO accounting (monitoring/incidents.py): every serving
            # request's outcome + wall duration feeds the burn-rate
            # engine under the same taxonomy the shed/deadline counters
            # use. Plumbing/introspection routes are exempt (they are not
            # the serving SLO); note_request is a one-comparison no-op
            # when the plane is off and exception-guarded internally.
            slo = name not in self._UNTRACED
            t0 = time.perf_counter() if slo else 0.0
            try:
                with robustness.tenant_concurrency(tenant), \
                        robustness.tenant_scope(tenant), \
                        robustness.deadline_scope(
                            self._request_timeout_ms(name)):
                    if tracing.get_tracer() is None \
                            or name in self._UNTRACED:
                        handler(**mt.groupdict())
                    else:
                        attrs = {"route": name}
                        if tenant:
                            attrs["tenant"] = tenant
                        with tracing.request(
                                "rest", f"{self.command} {parsed.path}",
                                traceparent=self.headers.get("traceparent"),
                                request_id=self._request_id, **attrs) as tr:
                            if tr is not None:
                                self._traceparent = tr.traceparent()
                            handler(**mt.groupdict())
            except robustness.OverloadedError:
                if slo:
                    incidents.note_request(
                        "shed", (time.perf_counter() - t0) * 1000.0, tenant)
                raise
            except robustness.DeadlineExceededError:
                if slo:
                    incidents.note_request(
                        "deadline", (time.perf_counter() - t0) * 1000.0,
                        tenant)
                raise
            except (HTTPError, UnauthorizedError, ForbiddenError,
                    NotFoundError, ObjectsError, SchemaError, ValueError,
                    BrokenPipeError):
                # caller mistakes (4xx family) and client disconnects:
                # counted toward request totals, never against the
                # availability error budget
                if slo:
                    incidents.note_request(
                        "client", (time.perf_counter() - t0) * 1000.0,
                        tenant)
                raise
            except Exception:
                if slo:
                    incidents.note_request(
                        "error", (time.perf_counter() - t0) * 1000.0,
                        tenant)
                raise
            else:
                if slo:
                    incidents.note_request(
                        "ok", (time.perf_counter() - t0) * 1000.0, tenant)
        except HTTPError as e:
            self._reply(e.status, _err_body(e.message))
        except UnauthorizedError as e:
            self._reply(401, _err_body(str(e)))
        except ForbiddenError as e:
            self._reply(403, _err_body(str(e)))
        except NotFoundError as e:
            self._reply(404, _err_body(str(e)))
        except robustness.OverloadedError as e:
            # shed by admission control: 429 + Retry-After (the server's
            # queue-drain estimate) so clients back off with jitter
            self._retry_after = e.retry_after_s
            self._reply(429, _err_body(str(e)))
        except robustness.DeadlineExceededError as e:
            self._reply(504, _err_body(str(e)))
        except (ObjectsError, SchemaError, ValueError) as e:
            self._reply(422, _err_body(str(e)))
        except BrokenPipeError:
            pass
        except Exception as e:  # internal
            self._reply(500, _err_body(f"{type(e).__name__}: {e}"))

    do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = do_HEAD = _dispatch

    # -- well-known / meta ---------------------------------------------------

    def h_meta(self):
        self._reply(200, self.app.meta())

    def h_openid(self):
        oidc = self.app.config.auth.oidc
        if not oidc.enabled:
            self._reply(404, _err_body("OIDC not configured"))
            return
        self._reply(200, {"href": f"{oidc.issuer}/.well-known/openid-configuration",
                          "clientId": oidc.client_id})

    def h_live(self):
        self._reply(200, raw=b"")

    def h_ready(self):
        self._reply(200, raw=b"")

    def h_metrics(self):
        self._reply(200, raw=self.app.metrics.expose(),
                    content_type="text/plain; version=0.0.4")

    # -- tracing (monitoring/tracing.py) -------------------------------------

    def h_debug_traces(self):
        t = tracing.get_tracer()
        if t is None:
            self._reply(200, {"enabled": False, "traces": []})
            return
        traces = t.snapshot()
        try:
            limit = int(self.query.get("limit", 0) or 0)
        except ValueError:
            limit = 0
        if limit > 0:
            traces = traces[-limit:]
        self._reply(200, {"enabled": True, "count": len(traces),
                          "traces": traces})

    def h_debug_perf(self):
        from weaviate_tpu.monitoring import perf

        w = perf.get_window()
        if w is None:
            self._reply(200, {"enabled": False})
            return
        self._reply(200, {"enabled": True, **w.summary()})

    def h_debug_quality(self):
        from weaviate_tpu.monitoring import quality

        a = quality.get_auditor()
        if a is None:
            self._reply(200, {"enabled": False})
            return
        self._reply(200, {"enabled": True, **a.summary()})

    def h_debug_memory(self):
        from weaviate_tpu.monitoring import memory

        led = memory.get_ledger()
        if led is None:
            self._reply(200, {"enabled": False})
            return
        self._reply(200, {"enabled": True, **led.summary()})

    def h_debug_incidents(self):
        """Recent bundle index + journal tail (monitoring/incidents.py)."""
        rec = incidents.get_recorder()
        journal = incidents.get_journal()
        if rec is None and journal is None:
            self._reply(200, {"enabled": False})
            return
        out: dict = {"enabled": True}
        if rec is not None:
            out["recorder"] = rec.stats()
            out["bundles"] = rec.index()
        if journal is not None:
            try:
                limit = int(self.query.get("limit", 128) or 128)
            except ValueError:
                limit = 128
            out["journal"] = {"counts": journal.counts(),
                              "tail": journal.tail(limit)}
        self._reply(200, out)

    def h_debug_incidents_dump(self):
        """Explicit bundle trigger (synchronous, rate-limit-exempt: an
        operator asking for a dump should get one)."""
        rec = incidents.get_recorder()
        if rec is None:
            self._reply(503, _err_body(
                "incident recorder disabled (INCIDENTS_ENABLED)"))
            return
        path = rec.dump_now(
            "manual", reason="explicit POST /debug/incidents/dump",
            force=True)
        if path is None:
            self._reply(500, _err_body("bundle capture failed"))
            return
        self._reply(200, {"file": path})

    def h_debug_slo(self):
        eng = incidents.get_engine()
        if eng is None:
            self._reply(200, {"enabled": False})
            return
        self._reply(200, {"enabled": True, **eng.summary()})

    def h_debug_controllers(self):
        """Control-plane state (serving/controller.py): per-controller
        sense/decide state, every knob's current value vs its configured
        default, the brownout-ladder stage, and recent actuations."""
        from weaviate_tpu.serving import controller

        p = controller.get_plane()
        if p is None:
            self._reply(200, {"enabled": False})
            return
        self._reply(200, {"enabled": True, **p.summary()})

    def h_debug_index(self):
        out = {}
        # snapshot the live registries before iterating (db.py's own
        # defensive idiom): concurrent class/shard creation must not 500
        # a health endpoint with a dict-changed-size error
        for cls, idx in list(self.app.db.indexes.items()):
            out[cls] = {name: shard.debug_health()
                        for name, shard in list(idx.shards.items())}
        self._reply(200, {"indexes": out})

    def h_debug_root(self):
        """The /debug index page: every debug endpoint with a one-line
        description (same authorizer as all of them)."""
        self._reply(200, {"endpoints": {
            "/debug/traces": "completed request traces ring (span trees "
                             "with device-time attribution; "
                             "TRACING_ENABLED)",
            "/debug/perf": "rolling device-performance window: roofline, "
                           "duty cycle, host-overhead ledger percentiles "
                           "(rides TRACING_ENABLED)",
            "/debug/quality": "shadow recall auditor window: online "
                              "recall/RBO/distance-error per tier, audit "
                              "accounting (RECALL_AUDIT_SAMPLE_RATE > 0)",
            "/debug/index": "per-index/shard health: live/tombstone "
                            "counts, snapshot + staged generations, PQ "
                            "state, cache residency (always on)",
            "/debug/memory": "device/host/disk byte ledger: per-component "
                             "bytes, write-path lifecycle, COW costs, "
                             "exhaustion forecast + headroom alerts "
                             "(MEMORY_LEDGER_ENABLED, default on)",
            "/debug/incidents": "incident flight recorder: recent bundle "
                                "index + ops-event journal tail "
                                "(INCIDENTS_ENABLED, default on)",
            "/debug/incidents/dump": "POST: capture a bundle now "
                                     "(rate-limit-exempt)",
            "/debug/slo": "config-declared SLOs: 5m/1h burn rates, error "
                          "budget remaining, alert state "
                          "(SLO_AVAILABILITY_TARGET / SLO_LATENCY_P99_MS)",
            "/debug/controllers": "self-tuning control plane: brownout "
                                  "ladder stage, knob values vs "
                                  "configured defaults, recent "
                                  "actuations (CONTROL_PLANE_ENABLED)",
            "/debug/pprof/": "profiling surface index",
            "/debug/pprof/profile": "sampled CPU profile "
                                    "(?seconds=N&hz=N)",
            "/debug/pprof/trace": "JAX device trace capture (?seconds=N)",
            "/debug/pprof/goroutine": "all-thread stack dump",
            "/debug/pprof/heap": "heap allocation summary (?limit=N)",
            "/debug/pprof/cmdline": "process command line",
        }})

    # -- profiling (monitoring/profiling.py; pprof surface) ------------------

    def h_pprof_index(self):
        from weaviate_tpu.monitoring import profiling

        self._reply(200, raw=profiling.index().encode(), content_type="text/plain")

    def h_pprof_profile(self):
        from weaviate_tpu.monitoring import profiling

        text = self.app.stack_sampler.profile(
            seconds=float(self.query.get("seconds", 5)),
            hz=int(self.query.get("hz", 100)),
        )
        self._reply(200, raw=text.encode(), content_type="text/plain")

    def h_pprof_trace(self):
        from weaviate_tpu.monitoring import profiling

        try:
            text = profiling.device_trace(
                self.app.db.root_path,
                seconds=float(self.query.get("seconds", 3)),
            )
        except profiling.TraceBusyError as e:
            self._reply(409, {"error": [{"message": str(e)}]})
            return
        self._reply(200, raw=text.encode(), content_type="text/plain")

    def h_pprof_goroutine(self):
        from weaviate_tpu.monitoring import profiling

        self._reply(200, raw=profiling.thread_dump().encode(), content_type="text/plain")

    def h_pprof_heap(self):
        from weaviate_tpu.monitoring import profiling

        text = profiling.heap_profile(limit=int(self.query.get("limit", 30)))
        self._reply(200, raw=text.encode(), content_type="text/plain")

    def h_pprof_cmdline(self):
        from weaviate_tpu.monitoring import profiling

        self._reply(200, raw=profiling.cmdline().encode(), content_type="text/plain")

    # -- schema --------------------------------------------------------------

    def h_schema_list(self):
        self._reply(200, self.app.schema.get_schema().to_dict())

    def h_schema_create(self):
        body = self._json_body() or {}
        cd = self.app.schema.add_class(body)
        self._reply(200, cd.to_dict())

    def _resolved(self, cls: str) -> str:
        resolved = self.app.schema.resolve_class_name(cls)
        if resolved is None:
            raise NotFoundError(f"class {cls!r} not found")
        return resolved

    def h_schema_get(self, cls):
        cd = self.app.schema.get_class(self._resolved(cls))
        self._reply(200, cd.to_dict())

    def h_schema_update(self, cls):
        body = self._json_body() or {}
        cd = self.app.schema.update_class(self._resolved(cls), body)
        self._reply(200, cd.to_dict())

    def h_schema_delete(self, cls):
        self.app.schema.delete_class(self._resolved(cls))
        self._reply(200)

    def h_schema_add_property(self, cls):
        body = self._json_body() or {}
        prop = self.app.schema.add_property(self._resolved(cls), body)
        self._reply(200, prop.to_dict())

    def h_shards_get(self, cls):
        self._reply(200, self.app.schema.shards_status(self._resolved(cls)))

    def h_shard_update(self, cls, shard):
        body = self._json_body() or {}
        status = body.get("status", "")
        self.app.schema.update_shard_status(self._resolved(cls), shard, status)
        self._reply(200, {"status": status})

    # -- objects -------------------------------------------------------------

    def _include_vector(self) -> bool:
        return "vector" in (self.query.get("include") or "")

    def _cl(self):
        return self.query.get("consistency_level")

    def h_objects_list(self):
        objs = self.app.objects.list_objects(
            class_name=self.query.get("class"),
            limit=int(self.query.get("limit", 25)),
            offset=int(self.query.get("offset", 0)),
            after=self.query.get("after"),
            include_vector=self._include_vector(),
        )
        self._reply(200, {
            "objects": [o.to_rest(self._include_vector()) for o in objs],
            "totalResults": len(objs),
        })

    def h_objects_create(self):
        obj = self.app.objects.add(self._json_body() or {}, cl=self._cl())
        self._reply(200, obj.to_rest(include_vector=True))

    def h_objects_validate(self):
        self.app.objects.validate(self._json_body() or {})
        self._reply(200)

    def h_object_get(self, id, cls=None):
        obj = self.app.objects.get(
            id, cls, include_vector=self._include_vector(), cl=self._cl())
        self._reply(200, obj.to_rest(self._include_vector()))

    def h_object_head(self, id, cls=None):
        if self.app.objects.exists(id, cls):
            self._reply(204)
        else:
            self._reply(404)

    def h_object_put(self, id, cls=None):
        body = self._json_body() or {}
        if cls:
            body.setdefault("class", cls)
        body["id"] = id
        obj = self.app.objects.update(id, body, cl=self._cl())
        self._reply(200, obj.to_rest(include_vector=True))

    def h_object_patch(self, id, cls=None):
        body = self._json_body() or {}
        class_name = cls or body.get("class")
        if not class_name:
            raise HTTPError(422, "PATCH requires the class name")
        self.app.objects.merge(
            id, class_name, body.get("properties") or {}, vector=body.get("vector"),
            cl=self._cl())
        self._reply(204)

    def h_object_delete(self, id, cls=None):
        self.app.objects.delete(id, cls, cl=self._cl())
        self._reply(204)

    # -- references ----------------------------------------------------------

    def h_ref_add(self, cls, id, prop):
        body = self._json_body() or {}
        self.app.objects.add_reference(id, cls, prop, body.get("beacon", ""))
        self._reply(200)

    def h_ref_put(self, cls, id, prop):
        body = self._json_body()
        beacons = [b.get("beacon", "") for b in body] if isinstance(body, list) else []
        self.app.objects.put_references(id, cls, prop, beacons)
        self._reply(200)

    def h_ref_delete(self, cls, id, prop):
        body = self._json_body() or {}
        self.app.objects.delete_reference(id, cls, prop, body.get("beacon", ""))
        self._reply(204)

    # -- batch ---------------------------------------------------------------

    def h_batch_objects(self):
        body = self._json_body() or {}
        payloads = body.get("objects") or []
        results = self.app.batch.add_objects(payloads, cl=self._cl())
        out = []
        for r in results:
            if r.err:
                out.append({
                    **(r.original or {}),
                    "result": {"status": "FAILED",
                               "errors": {"error": [{"message": r.err}]}},
                })
            else:
                out.append({**r.obj.to_rest(include_vector=False),
                            "result": {"status": "SUCCESS"}})
        self._reply(200, out)

    def h_batch_delete(self):
        body = self._json_body() or {}
        match = body.get("match") or {}
        out = self.app.batch.delete_objects(
            match.get("class", ""),
            match.get("where"),
            dry_run=bool(body.get("dryRun", False)),
            output=body.get("output", "minimal"),
        )
        self._reply(200, out)

    def h_batch_references(self):
        body = self._json_body() or []
        if not isinstance(body, list):
            raise HTTPError(400, "batch references body must be a list")
        self._reply(200, self.app.batch.add_references(body))

    # -- graphql -------------------------------------------------------------

    def h_graphql(self):
        body = self._json_body() or {}
        self._reply(200, self.app.graphql.execute(
            body.get("query") or "", body.get("variables")))

    def h_graphql_batch(self):
        body = self._json_body() or []
        if not isinstance(body, list):
            raise HTTPError(400, "graphql batch body must be a list")
        pool = getattr(self.app, "serving_pool", None)
        if pool is not None and len(body) > 1:
            # coalescing on: run the slots CONCURRENTLY so their kNN
            # dispatches admission-queue into one padded device batch (the
            # REST twin of gRPC BatchSearch) instead of serializing one
            # one-wide dispatch per slot. graphql.execute returns per-query
            # error envelopes, so slot isolation matches the serial path.
            # Each slot runs under a COPY of this handler's context (one
            # copy per slot — a shared Context cannot be entered twice
            # concurrently), so the request's trace span reaches the pool
            # threads and the coalescer lanes they submit into.
            import contextvars

            ctxs = [contextvars.copy_context() for _ in body]
            out = list(pool.map(
                lambda qc: qc[1].run(
                    self.app.graphql.execute,
                    qc[0].get("query") or "", qc[0].get("variables")),
                zip(body, ctxs)))
            self._reply(200, out)
            return
        self._reply(200, [
            self.app.graphql.execute(q.get("query") or "", q.get("variables"))
            for q in body
        ])

    # -- nodes ---------------------------------------------------------------

    def h_nodes(self):
        if self.app.cluster is not None:
            self._reply(200, {"nodes": self.app.cluster.nodes_status()})
            return
        shards = []
        total = 0
        for cls, idx in self.app.db.indexes.items():
            for name, shard in idx.shards.items():
                cnt = shard.object_count()
                total += cnt
                shards.append({"name": name, "class": cls, "objectCount": cnt})
        self._reply(200, {"nodes": [{
            "name": self.app.config.cluster.hostname or "node1",
            "status": "HEALTHY",
            "version": VERSION,
            "gitHash": "",
            "stats": {"objectCount": total, "shardCount": len(shards)},
            "shards": shards,
        }]})

    # -- backups / classifications (wired when subsystems present) -----------

    def _backup_or_501(self):
        if self.app.backup_scheduler is None:
            raise HTTPError(501, "backup subsystem not configured")
        return self.app.backup_scheduler

    def h_backup_create(self, backend):
        s = self._backup_or_501()
        body = self._json_body() or {}
        self._reply(200, s.backup(backend, body))

    def h_backup_status(self, backend, id):
        s = self._backup_or_501()
        self._reply(200, s.backup_status(backend, id))

    def h_backup_restore(self, backend, id):
        s = self._backup_or_501()
        body = self._json_body() or {}
        self._reply(200, s.restore(backend, id, body))

    def h_backup_restore_status(self, backend, id):
        s = self._backup_or_501()
        self._reply(200, s.restore_status(backend, id))

    def _classifier_or_501(self):
        if self.app.classifier is None:
            raise HTTPError(501, "classification subsystem not configured")
        return self.app.classifier

    def h_classification_create(self):
        c = self._classifier_or_501()
        self._reply(201, c.schedule(self._json_body() or {}))

    def h_classification_get(self, id):
        c = self._classifier_or_501()
        st = c.get(id)
        if st is None:
            raise NotFoundError(f"classification {id} not found")
        self._reply(200, st)

    def h_module_rest(self, module, rest):
        if self.app.modules is None:
            self._reply(404, _err_body("no modules enabled"))
            return
        body = self._json_body() if self.command in ("POST", "PUT") else None
        status, payload = self.app.modules.handle_module_rest(
            module, self.command, rest, body)
        self._reply(status, payload)


class _MetricsHandler(BaseHTTPRequestHandler):
    """Dedicated metrics listener (configure_api.go:116-121: Prometheus on
    its own port when PROMETHEUS_MONITORING_ENABLED)."""

    app = None

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        if urlparse(self.path).path != "/metrics":
            self.send_error(404)
            return
        data = self.app.metrics.expose()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class RestServer:
    """Threaded HTTP server hosting the /v1 surface for an App."""

    def __init__(self, app, host: str = "127.0.0.1", port: int = 8080):
        self.app = app
        handler = type("BoundHandler", (Handler,), {"app": app})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._metrics_httpd: Optional[ThreadingHTTPServer] = None
        self._metrics_thread: Optional[threading.Thread] = None
        if app.config.monitoring.enabled:
            mhandler = type("BoundMetricsHandler", (_MetricsHandler,), {"app": app})
            self._metrics_httpd = ThreadingHTTPServer(
                (host, app.config.monitoring.port), mhandler)
            self._metrics_httpd.daemon_threads = True
            self.metrics_port = self._metrics_httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        if self._metrics_httpd is not None:
            self._metrics_thread = threading.Thread(
                target=self._metrics_httpd.serve_forever, daemon=True)
            self._metrics_thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self._metrics_httpd is not None:
            self._metrics_httpd.shutdown()
            self._metrics_httpd.server_close()
            if self._metrics_thread:
                self._metrics_thread.join(timeout=5)
