"""App: the whole-server object graph.

Reference: adapters/handlers/rest/configure_api.go:105 `configureAPI` — the
one place every singleton is wired: DB, schema manager (with the vector-index
config parser injected), objects/batch managers, traverser/explorer,
aggregator, GraphQL executor, auth, metrics. The REST/gRPC layers only ever
see this object.
"""

from __future__ import annotations

import os
from typing import Optional

from weaviate_tpu.auth import Authenticator, Authorizer
from weaviate_tpu.config import Config, load_config
from weaviate_tpu.db import DB
from weaviate_tpu.graphql import GraphQLExecutor
from weaviate_tpu.monitoring import noop_metrics
from weaviate_tpu.schema import AutoSchema, SchemaManager
from weaviate_tpu.usecases.aggregator import Aggregator
from weaviate_tpu.usecases.objects import BatchManager, ObjectsManager
from weaviate_tpu.usecases.traverser import Explorer, Traverser
from weaviate_tpu.version import __version__ as VERSION


class App:
    def __init__(self, config: Optional[Config] = None, data_path: Optional[str] = None,
                 metrics=None, modules=None):
        # no config given => read the process environment (environment.go)
        self.config = config or load_config()
        path = data_path or self.config.persistence.data_path
        os.makedirs(path, exist_ok=True)
        if metrics is not None:
            self.metrics = metrics
        elif self.config.monitoring.enabled:
            from weaviate_tpu.monitoring import get_metrics

            self.metrics = get_metrics()
        else:
            self.metrics = noop_metrics()

        # pprof twin: one sampler per process (profile runs are serialized
        # by its lock the way pprof serializes CPU profiles)
        from weaviate_tpu.monitoring.profiling import StackSampler

        self.stack_sampler = StackSampler()

        # fused device dispatch (index/tpu.py): apply the config knob to
        # the index layer's process-wide toggle — like the tracer, the
        # index reaches it without plumbing. Default on; the bench's
        # --fused A/B and FUSED_DISPATCH_ENABLED flip it.
        from weaviate_tpu.index import tpu as tpu_index

        self._fused_token = tpu_index.set_fused_enabled(
            self.config.fused_dispatch_enabled)
        # IVF scan plane (index/tpu.py, ROADMAP item 3): same
        # process-wide toggle shape — the index layer reads Config.ivf
        # without plumbing, and the token scopes the revert to THIS App
        self._ivf_token = tpu_index.set_ivf_config(self.config.ivf)

        # end-to-end request tracing (monitoring/tracing.py): the tracer is
        # a process-wide module global — shards and the coalescer reach it
        # without plumbing — installed here and cleared on shutdown.
        # Disabled => the global stays None and every tracing entry point
        # on the serving path is a one-comparison no-op.
        tc = self.config.tracing
        if tc.enabled:
            from weaviate_tpu.monitoring import perf, tracing

            self.tracer = tracing.configure(tracing.Tracer(
                sample_rate=tc.sample_rate,
                ring_size=tc.ring_size,
                slow_ms=tc.slow_query_threshold_ms,
                metrics=self.metrics))
            # continuous device-performance attribution (monitoring/
            # perf.py): rides the tracer's enablement — the perf window is
            # fed by every dispatch's cost-model shape, which the index
            # only builds while the tracer is up (one zero-cost contract
            # for both planes). /debug/perf + the rolling roofline gauges.
            self.perf_window = perf.configure(perf.PerfWindow(
                window_s=tc.perf_window_s,
                metrics=self.metrics,
                sample_hint=tc.sample_rate))
        else:
            self.tracer = None
            self.perf_window = None
        # online quality observability (monitoring/quality.py): the shadow
        # recall auditor is its own module global with the same lifecycle
        # discipline as the tracer/perf window — sample rate 0 (the
        # default) leaves the global None and every capture point on the
        # serving path a one-comparison no-op that constructs nothing.
        qc = self.config.quality
        if qc.audit_sample_rate > 0.0:
            from weaviate_tpu.monitoring import quality

            self.quality_auditor = quality.configure(quality.QualityAuditor(
                sample_rate=qc.audit_sample_rate,
                concurrency=qc.audit_concurrency,
                max_rows=qc.audit_max_rows,
                deadline_ms=qc.audit_deadline_ms,
                window_s=qc.window_s,
                alert_threshold=qc.alert_threshold,
                alert_min_samples=qc.alert_min_samples,
                metrics=self.metrics))
        else:
            self.quality_auditor = None
        # memory & capacity observability (monitoring/memory.py): the
        # device/host/disk byte ledger is ALWAYS-ON by default (unlike the
        # tracer it costs nothing on the search path — stamps ride the
        # write path only), installed before the DB so restore-time
        # flushes are accounted; same module-global lifecycle discipline.
        mc = self.config.memory
        if mc.ledger_enabled:
            from weaviate_tpu.monitoring import memory as memledger

            self.memory_ledger = memledger.configure(memledger.MemoryLedger(
                metrics=self.metrics,
                window_s=mc.window_s,
                headroom_alert_pct=mc.headroom_alert_pct,
                device_budget_bytes=mc.device_budget_bytes,
                host_budget_bytes=mc.host_budget_bytes))
            # the data volume backs the ledger's disk scope, so device/
            # host/disk capacity read from one /debug/memory page
            self.memory_ledger.set_disk_path(path)
        else:
            self.memory_ledger = None
        # incident flight recorder + SLO burn-rate engine (monitoring/
        # incidents.py): the capstone layer that connects the planes above
        # — an ops-event journal fed by their state transitions, config-
        # declared SLOs evaluated into 5m/1h burn rates, and trigger-
        # driven post-mortem bundles under INCIDENT_DIR. Same module-
        # global lifecycle discipline; disabled => the globals stay None
        # and every emit/note_request/trigger is a one-comparison no-op
        # that constructs nothing (spy-pinned in tests/test_incidents.py).
        ic = self.config.incidents
        if ic.enabled:
            from weaviate_tpu.monitoring import incidents
            from weaviate_tpu.monitoring import memory as memledger_mod

            self.ops_journal = incidents.OpsJournal(
                size=ic.journal_size, metrics=self.metrics)
            self.slo_engine = incidents.SloEngine(
                availability_target=ic.slo_availability_target,
                latency_p99_ms=ic.slo_latency_p99_ms,
                fast_burn_threshold=ic.slo_fast_burn,
                slow_burn_threshold=ic.slo_slow_burn,
                min_events=ic.slo_min_events,
                tenant_targets=ic.slo_tenant_targets,
                metrics=self.metrics)
            self.flight_recorder = incidents.FlightRecorder(
                ic.dir or os.path.join(path, "incidents"),
                max_bytes=ic.dir_max_bytes,
                rate_limit_s=ic.rate_limit_s,
                journal=self.ops_journal,
                engine=self.slo_engine,
                metrics=self.metrics)
            self.flight_recorder.set_config_fingerprint(
                self._config_fingerprint())
            # the bundle directory is a disk consumer the capacity plane
            # should see: registered as the ledger's `incident_bundles`
            # disk component (weakref provider, PR-9 idiom)
            memledger_mod.register_disk_provider(
                self.flight_recorder,
                lambda rec: {"incident_bundles": rec.dir_bytes()})
            incidents.configure(journal=self.ops_journal,
                                engine=self.slo_engine,
                                recorder=self.flight_recorder)
        else:
            self.ops_journal = None
            self.slo_engine = None
            self.flight_recorder = None
        # a SIGTERM mid device-trace capture must still stop the JAX
        # profiler (the r05 wedge): install the signal/atexit teardown
        # from the main thread while we are likely on it — REST handler
        # threads cannot install signal handlers themselves
        from weaviate_tpu.monitoring import profiling

        profiling.install_trace_teardown()
        if self.flight_recorder is not None:
            # chain the flight-recorder dump into the same teardown:
            # stop capture -> dump bundle -> re-deliver. The hook reads
            # the LIVE module global, so a cleanly shut-down App (already
            # unconfigured) dumps nothing at exit, while a process dying
            # with a live server preserves its evidence.
            from weaviate_tpu.monitoring import incidents

            profiling.register_teardown_hook(incidents.teardown_dump)

        # request-lifecycle robustness (serving/robustness.py): shed/
        # deadline counters bind to this App's metrics; the device circuit
        # breaker is a process-wide global (the device is shared — dispatch
        # failures are a property of the accelerator, not of one shard),
        # installed here and cleared on shutdown like the tracer.
        from weaviate_tpu.serving import robustness

        robustness.set_metrics(self.metrics)
        rb = self.config.robustness
        if rb.breaker_enabled:
            self.breaker = robustness.configure_breaker(
                robustness.CircuitBreaker(
                    failure_threshold=rb.breaker_failure_threshold,
                    reset_timeout_s=rb.breaker_reset_ms / 1000.0,
                    half_open_probes=rb.breaker_half_open_probes,
                    metrics=self.metrics))
        else:
            self.breaker = None
        # fault-injection harness (testing/faults.py): config-gated; off =>
        # the module global stays None and every injection point on the
        # serving path is a one-comparison no-op
        if rb.fault_injection:
            from weaviate_tpu.testing import faults

            self.fault_injector = faults.configure(faults.from_spec(
                rb.fault_injection, seed=rb.fault_injection_seed))
        else:
            self.fault_injector = None

        # distributed deployments (CLUSTER_HOSTNAME/CLUSTER_JOIN set) build
        # the full cluster graph: membership, cluster-API listener, schema
        # 2PC, replication, scaler (configure_api.go startupRoutine's
        # cluster.Init + clusterapi.Serve path). CLUSTER_JOIN entries are
        # "name@host:port".
        cl_cfg = self.config.cluster
        if cl_cfg.hostname or cl_cfg.join:
            from weaviate_tpu.cluster.node import ClusterNode

            node_name = cl_cfg.hostname or "node-0"
            # "name@host:port" entries are a static registry; bare
            # "host:port" entries are gossip SEEDS (memberlist-style
            # auto-discovery: the rest of the cluster is learned over UDP)
            peers = {}
            seeds = []
            for item in cl_cfg.join:
                if "@" in item:
                    pname, phost = item.split("@", 1)
                    peers[pname] = phost
                elif item.strip():
                    seeds.append(item.strip())
            node_names = sorted(set(peers) | {node_name})
            self.cluster_node = ClusterNode(
                path,
                node_name,
                node_names=node_names,
                bind_host="0.0.0.0",  # peers dial in from other machines
                bind_port=cl_cfg.data_bind_port,
                metrics=self.metrics,
                default_vectorizer=self.config.default_vectorizer_module,
                store_opts=self._store_opts(),
                enable_gossip=bool(seeds) or cl_cfg.gossip,
                gossip_bind_host="0.0.0.0",
                gossip_bind_port=max(cl_cfg.gossip_bind_port, 0),
            )
            self.cluster_node.start()
            self.cluster_node.join(peers)
            self.cluster_node.join_gossip(seeds)
            if not cl_cfg.ignore_schema_sync:
                self.cluster_node.sync_schema()
            self.db = self.cluster_node.db
            self.schema = self.cluster_node.schema
        else:
            self.cluster_node = None
            self.db = DB(path, metrics=self.metrics,
                         store_opts=self._store_opts())
            self.schema = SchemaManager(
                os.path.join(path, "schema.json"), migrator=self.db,
                default_vectorizer=self.config.default_vectorizer_module)
        # modules: explicit injection wins; else built from ENABLE_MODULES
        # (registerModules, configure_api.go:471)
        if modules is None:
            from weaviate_tpu.modules import build_provider

            modules = build_provider(self.config)
        if modules is not None:
            ref2vec = modules.get("ref2vec-centroid")
            if ref2vec is not None:
                ref2vec.set_db(self.db)
        self.modules = modules
        # class creation must fail fast on a vectorizer that is not an
        # enabled module (instead of importing vectorless objects)
        enabled = set(modules.names()) if modules is not None else set()
        self.schema.vectorizer_validator = (
            lambda name: name in enabled
        )
        self.auto_schema = (
            AutoSchema(
                self.schema,
                default_string=self.config.auto_schema.default_string,
                default_number=self.config.auto_schema.default_number,
                default_date=self.config.auto_schema.default_date,
            )
            if self.config.auto_schema.enabled
            else None
        )
        self.objects = ObjectsManager(
            self.db, self.schema, auto_schema=self.auto_schema,
            modules=self.modules, metrics=self.metrics)
        self.batch = BatchManager(self.objects)
        # cross-request query coalescing (serving/coalescer.py): disabled =>
        # self.coalescer is None and every read path below is untouched
        # (zero queue hops) — the knob must be a true no-op when off
        cc = self.config.coalescer
        # multi-tenant fairness: the bounded tenant-label mapper is sized
        # here (it lives on the metrics registry so robustness counters
        # and the coalescer share ONE top-K view of who is heavy)
        tn = self.config.tenancy
        self.metrics.tenant_labels.top_k = max(int(tn.metrics_top_k), 1)
        # front-door per-tenant concurrency gate: process-wide like the
        # breaker (the frontends check it before any per-request work)
        if tn.max_concurrent_requests > 0:
            self.tenant_gate = robustness.configure_tenant_gate(
                robustness.TenantConcurrencyGate(tn.max_concurrent_requests,
                                                 metrics=self.metrics))
        else:
            self.tenant_gate = None
        if cc.enabled:
            from concurrent.futures import ThreadPoolExecutor

            from weaviate_tpu.serving.coalescer import QueryCoalescer

            self.coalescer = QueryCoalescer(
                window_s=cc.window_ms / 1000.0,
                max_batch=cc.max_batch,
                max_request_rows=cc.max_request_rows,
                metrics=self.metrics,
                pipeline_depth=cc.pipeline_depth,
                max_queued_rows=cc.max_queued_rows,
                waiter_timeout_s=cc.wait_timeout_s,
                tenant_weights=tn.weights,
                tenant_rows_fraction=tn.max_queued_rows_fraction)
            # persistent slot pool for concurrent batch fan-out (REST
            # /v1/graphql/batch): per-request executors would pay thread
            # churn on the exact hot path the coalescer optimizes
            self.serving_pool = ThreadPoolExecutor(
                max_workers=32, thread_name_prefix="serving-batch")
        else:
            self.coalescer = None
            self.serving_pool = None
        # self-tuning degradation control plane (serving/controller.py):
        # the layer that ACTS on the observability stack — burn-rate
        # brownout, the recall-guarded candidate budget, coalescer
        # window/depth steering, tenant rate quotas. Module-global
        # lifecycle like the tracer; disabled (the default) => the
        # global stays None and every knob reader on the serving path is
        # a one-comparison no-op that constructs nothing (spy-pinned in
        # tests/test_controller.py). Wired AFTER the coalescer so the
        # plane captures its configured defaults.
        ctl = self.config.controller
        if ctl.enabled:
            from weaviate_tpu.serving import controller as control

            self.control_plane = control.configure(control.ControlPlane(
                config=ctl,
                coalescer=self.coalescer,
                metrics=self.metrics,
                tenant_weights=tn.weights))
        else:
            self.control_plane = None
        if self.flight_recorder is not None:
            # live serving stats ride into every bundle: the coalescer's
            # lane/shed/tenant picture and the front-door gate occupancy
            # (pull callables, each captured under its own guard)
            if self.coalescer is not None:
                self.flight_recorder.add_stats_provider(
                    "coalescer", self.coalescer.stats)
            if self.tenant_gate is not None:
                self.flight_recorder.add_stats_provider(
                    "tenant_gate", self.tenant_gate.stats)
            if self.control_plane is not None:
                # every bundle carries the control plane's knob/ladder
                # picture: a post-mortem must show what the controllers
                # were DOING around the incident, not just what the
                # sensors saw
                self.flight_recorder.add_stats_provider(
                    "controllers", self.control_plane.summary)
        self.explorer = Explorer(
            self.db, self.schema, modules=self.modules,
            query_limit=self.config.query_defaults_limit,
            max_results=self.config.query_maximum_results,
            coalescer=self.coalescer)
        self.traverser = Traverser(
            self.explorer,
            max_concurrent=self.config.maximum_concurrent_get_requests)
        self.aggregator = Aggregator(self.db, self.schema, self.explorer)
        self.graphql = GraphQLExecutor(self.traverser, self.aggregator, self.schema, self.db)
        oidc_validator = None
        if self.config.auth.oidc.enabled:
            from weaviate_tpu.auth.oidc import OIDCValidator

            oidc_validator = OIDCValidator(self.config.auth.oidc)
        self.authenticator = Authenticator(
            self.config.auth, oidc_validator=oidc_validator
        )
        self.authorizer = Authorizer(self.config.authz)
        from weaviate_tpu.usecases.backup import BackupScheduler

        if self.cluster_node is not None:
            self.backup_scheduler = BackupScheduler(
                self.db, self.schema, self.modules,
                node_name=self.cluster_node.node_name,
                cluster=self.cluster_node.cluster,
                node_client=self.cluster_node.transfer_client,
            )
            self.cluster_node.api.backup = self.backup_scheduler
        else:
            self.backup_scheduler = BackupScheduler(self.db, self.schema, self.modules)
        from weaviate_tpu.usecases.classification import Classifier

        self.classifier = Classifier(self.db, self.schema, self.modules)
        self.cluster = self.cluster_node  # /v1/nodes aggregation source
        # disk-pressure failure detection (storagestate READONLY automation)
        from weaviate_tpu.monitoring.disk import DiskMonitor

        self.disk_monitor = DiskMonitor(
            self.db,
            warning_pct=self.config.disk_use.warning_percentage,
            readonly_pct=self.config.disk_use.readonly_percentage,
        )
        self.disk_monitor.start()

        if self.config.index_missing_text_filterable_at_startup:
            # startup reindexer (inverted_reindexer_missing_text_filterable
            # analog): backfill filterable postings for props indexed before
            # their indexFilterable flag was enabled
            rebuilt = self.db.reindex_missing_filterable()
            if rebuilt:
                import logging

                logging.getLogger(__name__).info(
                    "filterable backfill rebuilt: %s", rebuilt)

    def _config_fingerprint(self) -> dict:
        """The serving-relevant config knobs + a short digest, stamped
        into every incident bundle so a post-mortem knows exactly what
        configuration produced it. Auth/secrets are deliberately absent."""
        import dataclasses
        import hashlib
        import json as _json

        c = self.config
        knobs = {
            "coalescer": dataclasses.asdict(c.coalescer),
            "tracing": dataclasses.asdict(c.tracing),
            "robustness": dataclasses.asdict(c.robustness),
            "tenancy": dataclasses.asdict(c.tenancy),
            "quality": dataclasses.asdict(c.quality),
            "memory": dataclasses.asdict(c.memory),
            "incidents": dataclasses.asdict(c.incidents),
            "controller": dataclasses.asdict(c.controller),
            "store_dtype": c.store_dtype,
            "device_mesh_shards": c.device_mesh_shards,
        }
        digest = hashlib.sha256(
            _json.dumps(knobs, sort_keys=True, default=str).encode()
        ).hexdigest()[:16]
        return {"sha256_16": digest, "knobs": knobs}

    def _store_opts(self) -> dict:
        """LSM tuning from env (PERSISTENCE_MEMTABLES_MAX_SIZE_MB,
        PERSISTENCE_FLUSH_IDLE_MEMTABLES_AFTER — environment.go surface)."""
        p = self.config.persistence
        return {
            "memtable_max_bytes": int(p.memtables_max_size_mb) * 1024 * 1024,
            "flush_idle_seconds": float(p.flush_idle_memtables_after),
        }

    # -- meta ----------------------------------------------------------------

    def meta(self) -> dict:
        """GET /v1/meta payload (handlers_meta)."""
        return {
            "hostname": self.config.origin or "http://[::]:8080",
            "version": VERSION,
            "modules": self.modules.meta() if self.modules is not None else {},
        }

    def shutdown(self) -> None:
        # the control plane goes FIRST: unconfigure stops the tick
        # thread and reverts every actuated knob to its configured
        # default while the objects it steered (coalescer, tracer,
        # auditor) are still alive — a shut-down App leaves no knob
        # residue behind (still-ours discipline like the tracer)
        if self.control_plane is not None:
            from weaviate_tpu.serving import controller as control

            control.unconfigure(self.control_plane)
        # queued coalescer waiters must wake (with a shutdown error
        # that sends their serving threads to the direct path) before the
        # shards they would dispatch to go away
        if self.coalescer is not None:
            self.coalescer.shutdown()
        # the fused-dispatch toggle reverts to the env default, but only
        # if OUR override is still the current one (a newer App's setting
        # survives) — the same still-ours discipline as the tracer below
        from weaviate_tpu.index import tpu as tpu_index

        tpu_index.unset_fused_enabled(getattr(self, "_fused_token", None))
        tpu_index.unset_ivf_config(getattr(self, "_ivf_token", None))
        if self.tracer is not None:
            from weaviate_tpu.monitoring import tracing

            # clear only if still ours: a newer App's tracer survives
            tracing.unconfigure(self.tracer)
        if self.perf_window is not None:
            from weaviate_tpu.monitoring import perf

            perf.unconfigure(self.perf_window)
        if self.quality_auditor is not None:
            from weaviate_tpu.monitoring import quality

            # same still-ours discipline; also stops the audit workers
            # and stashes the final summary for the CI artifact dump
            quality.unconfigure(self.quality_auditor)
        if self.memory_ledger is not None:
            from weaviate_tpu.monitoring import memory as memledger

            # still-ours discipline; stashes the final summary for the
            # debug_memory.json CI artifact
            memledger.unconfigure(self.memory_ledger)
        if self.ops_journal is not None:
            from weaviate_tpu.monitoring import incidents

            # still-ours discipline; stashes the journal's final summary
            # for the debug_incidents.json CI artifact and stops the
            # recorder worker — a cleanly shut-down App then dumps
            # nothing from the atexit/SIGTERM teardown hook
            incidents.unconfigure(journal=self.ops_journal,
                                  engine=self.slo_engine,
                                  recorder=self.flight_recorder)
        # robustness globals: same still-ours discipline as the tracer
        from weaviate_tpu.serving import robustness

        if self.breaker is not None:
            robustness.unconfigure_breaker(self.breaker)
        if self.tenant_gate is not None:
            robustness.unconfigure_tenant_gate(self.tenant_gate)
        robustness.unset_metrics(self.metrics)
        if self.fault_injector is not None:
            from weaviate_tpu.testing import faults

            faults.unconfigure(self.fault_injector)
        if self.serving_pool is not None:
            self.serving_pool.shutdown(wait=False)
        self.disk_monitor.shutdown()
        if self.cluster_node is not None:
            self.cluster_node.shutdown()
        else:
            self.db.shutdown()
