"""ctypes bridge to the native gRPC reply marshaller (native/reply.cpp).

Serializes a SearchReply's wire bytes straight from stored object images —
the per-result Python marshalling cost (~25us each: storobj decode, uuid
formatting, upb message construction) collapses to one C call per reply.
Reference analog: adapters/handlers/grpc/server.go marshals results in
compiled Go; this is the same tier for the Python runtime.

Falls back cleanly: `build_search_reply` returns None whenever the library
is unavailable or an image is rejected, and callers use the upb path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libreply.so")
_SRC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native", "reply.cpp")

_lib = None
_lib_failed = False
_lib_lock = threading.Lock()

_NAN = float("nan")


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_SO_PATH):
                os.makedirs(_NATIVE_DIR, exist_ok=True)
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
                     "-fPIC", "-o", _SO_PATH, _SRC_PATH],
                    check=True, capture_output=True)
            lib = ctypes.CDLL(_SO_PATH)
            lib.build_search_reply.restype = ctypes.c_int64
            lib.build_search_reply.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int64,
                ctypes.c_float,
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.c_int64,
            ]
            lib.build_batch_reply.restype = ctypes.c_int64
            lib.build_batch_reply.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_float,
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.c_int64,
            ]
            lib.build_batch_reply_packed.restype = ctypes.c_int64
            lib.build_batch_reply_packed.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int8),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_float,
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.c_int64,
            ]
            _lib = lib
        except Exception:  # noqa: BLE001 — native tier is best-effort
            _lib_failed = True
        return _lib


def available() -> bool:
    return _load() is not None


def _marshal_inputs(raws, dists, certs, extra_cap: int):
    """Shared ctypes marshalling for both builders: pointer/length arrays,
    NaN substitution for absent distance/certainty, output buffer sized so
    the native side can never overrun (props are a subset of each image)."""
    n = len(raws)
    raw_arr = (ctypes.c_char_p * n)(*raws)
    len_arr = (ctypes.c_int64 * n)(*[len(r) for r in raws])
    d_arr = (ctypes.c_double * n)(*[
        _NAN if d is None else float(d) for d in dists])
    c_arr = (ctypes.c_double * n)(*[
        _NAN if c is None else float(c) for c in certs])
    cap = sum(len(r) for r in raws) + n * 128 + extra_cap + 16
    out = (ctypes.c_ubyte * cap)()
    return n, raw_arr, len_arr, d_arr, c_arr, out, cap


def build_search_reply(
    raws: Sequence[bytes],
    dists: Sequence[Optional[float]],
    certs: Sequence[Optional[float]],
    took_seconds: float,
) -> Optional[bytes]:
    """-> serialized SearchReply bytes, or None to use the upb marshaller."""
    lib = _load()
    if lib is None:
        return None
    n, raw_arr, len_arr, d_arr, c_arr, out, cap = _marshal_inputs(
        raws, dists, certs, 0)
    wrote = lib.build_search_reply(raw_arr, len_arr, d_arr, c_arr, n,
                                   float(took_seconds), out, cap)
    if wrote < 0:
        return None
    return ctypes.string_at(out, wrote)


def build_batch_reply(
    raws: Sequence[bytes],
    dists: Sequence[Optional[float]],
    certs: Sequence[Optional[float]],
    counts: Sequence[int],
    took_seconds: float,
) -> Optional[bytes]:
    """-> serialized BatchSearchReply bytes for len(counts) replies whose
    results are flat runs in raws/dists/certs, or None for the upb path."""
    lib = _load()
    if lib is None:
        return None
    n, raw_arr, len_arr, d_arr, c_arr, out, cap = _marshal_inputs(
        raws, dists, certs, len(counts) * 16)
    cnt_arr = (ctypes.c_int64 * len(counts))(*counts)
    wrote = lib.build_batch_reply(raw_arr, len_arr, d_arr, c_arr, cnt_arr,
                                  len(counts), float(took_seconds), out, cap)
    if wrote < 0:
        return None
    return ctypes.string_at(out, wrote)


def build_batch_reply_packed(val_buf, val_offs, flags, flat_dists, counts,
                             took_seconds: float) -> Optional[bytes]:
    """Raw-lane twin of build_batch_reply: object images live in ONE arena
    (numpy uint8) at val_offs[i]..val_offs[i+1] — the layout the native LSM
    point-get plane emits — so no per-result Python objects exist anywhere
    on the path. flags[i]==0 drops that (deleted) hit from its reply."""
    import numpy as np

    lib = _load()
    if lib is None:
        return None
    n = len(flags)
    offs = np.ascontiguousarray(val_offs, dtype=np.int64)
    fl = np.ascontiguousarray(flags, dtype=np.int8)
    ds = np.ascontiguousarray(flat_dists, dtype=np.float32)
    cnts = np.ascontiguousarray(counts, dtype=np.int64)
    cap = int(offs[n]) + n * 128 + len(cnts) * 16 + 16
    out = (ctypes.c_ubyte * cap)()
    wrote = lib.build_batch_reply_packed(
        val_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        fl.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        ds.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        cnts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(cnts), float(took_seconds), out, cap)
    if wrote < 0:
        return None
    return ctypes.string_at(out, wrote)


def varint(v: int) -> bytes:
    """Protobuf varint (outer BatchSearchReply framing)."""
    b = bytearray()
    while v >= 0x80:
        b.append((v & 0x7F) | 0x80)
        v >>= 7
    b.append(v)
    return bytes(b)
