"""Multi-chip device data plane.

Reference parallelism (SURVEY.md §2.8): goroutine scatter-gather across
shards + HTTP between nodes (index.go:967-1046). TPU-native analog: one
logical index sharded row-wise over a jax.sharding Mesh — each chip holds a
[N/devices, D] slab in its HBM, a query batch is replicated, every chip
scores its slab and the per-chip top-k candidates are merged with an
all_gather over ICI (not host HTTP). Host-level (DCN / multi-node)
scatter-gather stays on the cluster API plane, mirroring the reference's
local-shard vs remote-shard split (index.go:996-1017).
"""

from weaviate_tpu.parallel.mesh_search import MeshSearchPlan, mesh_search_step

__all__ = ["MeshSearchPlan", "mesh_search_step"]
