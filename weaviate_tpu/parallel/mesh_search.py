"""Sharded batched kNN over a device mesh (shard_map + ICI collectives).

The device twin of Index.objectVectorSearch's errgroup fan-out + merge-sort
(adapters/repos/db/index.go:967-1046): instead of goroutines + HTTP, the
"fan-out" is SPMD execution of the same program on every chip over its local
HBM slab, and the "merge by distance" is an all_gather of [B, k] candidate
sets over ICI followed by a k-selection — all inside one jit.

Also provides the write path (sharded insert step): appends land on the chip
that owns the target slot via masked dynamic_update_slice, so a full
update+search step compiles into a single SPMD program (this is what
__graft_entry__.dryrun_multichip validates on a virtual mesh).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from weaviate_tpu.ops.distances import DISTANCE_FNS

SHARD_AXIS = "shard"


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def _local_topk(dists, k):
    neg, idx = jax.lax.top_k(-dists, k)
    return -neg, idx


@functools.partial(jax.jit, static_argnames=("k", "metric", "mesh"))
def distributed_search_step(store, tombs, n_per_shard, queries, k, metric, mesh):
    """One fully-sharded search step.

    store:   [n_dev * N_loc, D], sharded P('shard', None)  — HBM slabs
    tombs:   [n_dev * N_loc], sharded P('shard')           — tombstone mask
    n_per_shard: [n_dev] int32, replicated — live high-water mark per slab
    queries: [B, D], replicated
    -> (dists [B, k], global_rows [B, k]) replicated; global row = slab row +
       shard_index * N_loc (host maps rows→docIDs).
    """
    n_loc = store.shape[0] // mesh.devices.size

    def shard_fn(store_l, tombs_l, n_all, q):
        my = jax.lax.axis_index(SHARD_AXIS)
        n_mine = n_all[my]
        valid = jnp.logical_and(jnp.arange(n_loc) < n_mine, jnp.logical_not(tombs_l))
        d = DISTANCE_FNS[metric](q, store_l, None)
        d = jnp.where(valid[None, :], d, jnp.inf)
        d_top, i_top = _local_topk(d, k)
        i_glob = i_top + my * n_loc
        # merge across chips over ICI: gather all candidate sets, reselect
        d_all = jax.lax.all_gather(d_top, SHARD_AXIS, axis=1, tiled=True)  # [B, ndev*k]
        i_all = jax.lax.all_gather(i_glob, SHARD_AXIS, axis=1, tiled=True)
        d_fin, pos = _local_topk(d_all, k)
        i_fin = jnp.take_along_axis(i_all, pos, axis=1)
        return d_fin, jnp.where(jnp.isinf(d_fin), -1, i_fin).astype(jnp.int32)

    return jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS), P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )(store, tombs, n_per_shard, queries)


@functools.partial(jax.jit, static_argnames=("mesh",), donate_argnums=(0,))
def distributed_insert_step(store, chunk, target_shard, offset, mesh):
    """Sharded append: write `chunk` [C, D] into the slab of `target_shard`
    at local row `offset`. Chips other than the target write their own slab
    back unchanged (masked update keeps the program SPMD)."""
    n_loc = store.shape[0] // mesh.devices.size

    def shard_fn(store_l, chunk_r, tgt, off):
        my = jax.lax.axis_index(SHARD_AXIS)
        updated = jax.lax.dynamic_update_slice(store_l, chunk_r.astype(store_l.dtype), (off, 0))
        return jnp.where(my == tgt, updated, store_l)

    return jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS, None), P(), P(), P()),
        out_specs=P(SHARD_AXIS, None),
        check_vma=False,
    )(store, chunk, target_shard, offset)


class MeshSearchPlan:
    """A logical index spread over every chip of a mesh.

    Placement mirrors the sharding ring (usecases/sharding/state.go): docIDs
    are assigned round-robin to chips; each chip owns a [N_loc, D] slab.
    """

    def __init__(self, mesh: Mesh, dim: int, capacity_per_shard: int = 16384, metric: str = "l2-squared", dtype=jnp.float32):
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.dim = dim
        self.n_loc = capacity_per_shard
        self.metric = metric
        sh = NamedSharding(mesh, P(SHARD_AXIS, None))
        sh1 = NamedSharding(mesh, P(SHARD_AXIS))
        rep = NamedSharding(mesh, P())
        self.store = jax.device_put(jnp.zeros((self.n_dev * self.n_loc, dim), dtype), sh)
        self.tombs = jax.device_put(jnp.zeros((self.n_dev * self.n_loc,), jnp.bool_), sh1)
        self.n_per_shard = jax.device_put(jnp.zeros((self.n_dev,), jnp.int32), rep)
        self._counts = np.zeros(self.n_dev, dtype=np.int64)
        self._row_to_doc = np.full(self.n_dev * self.n_loc, -1, dtype=np.int64)

    def add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        """Round-robin the batch across shards, one insert step per shard."""
        doc_ids = np.asarray(doc_ids, dtype=np.int64)
        vectors = np.asarray(vectors, dtype=np.float32)
        target = doc_ids % self.n_dev
        for s in range(self.n_dev):
            sel = target == s
            if not sel.any():
                continue
            chunk = vectors[sel]
            off = int(self._counts[s])
            if off + chunk.shape[0] > self.n_loc:
                raise ValueError("mesh shard capacity exceeded")
            self.store = distributed_insert_step(
                self.store, jnp.asarray(chunk), jnp.int32(s), jnp.int32(off), self.mesh
            )
            rows = s * self.n_loc + off + np.arange(chunk.shape[0])
            self._row_to_doc[rows] = doc_ids[sel]
            self._counts[s] += chunk.shape[0]
        self.n_per_shard = jnp.asarray(self._counts.astype(np.int32))

    def search(self, queries: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
        d, rows = distributed_search_step(
            self.store, self.tombs, self.n_per_shard, jnp.asarray(queries, jnp.float32), k, self.metric, self.mesh
        )
        rows = np.asarray(rows)
        ids = np.where(rows >= 0, self._row_to_doc[np.clip(rows, 0, None)], -1)
        return ids, np.asarray(d)
