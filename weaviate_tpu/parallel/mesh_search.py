"""Sharded vector-store kernels over a device mesh (shard_map + ICI collectives).

The device twin of Index.objectVectorSearch's errgroup fan-out + merge-sort
(adapters/repos/db/index.go:967-1046): instead of goroutines + HTTP, the
"fan-out" is SPMD execution of the same program on every chip over its local
HBM slab, and the "merge by distance" is an all_gather of [B, k] candidate
sets over ICI followed by a k-selection — all inside one jit.

Every kernel here is a whole-mesh step:

- mesh_search_step:  chunked masked kNN per slab (tombstones + allowList
  bitmap, same semantics as the single-chip scan in index/tpu.py) with the
  cross-chip merge riding ICI. With ``fused=True`` every search kernel
  translates its LOCAL winners through its slab of the sharded slot->doc
  word table BEFORE the collective, so the gathered candidates already
  carry final doc ids and the merged output is the PR-14 packed [B, 3k]
  fused layout — one fetch, zero host translation, across chips.
- mesh_insert_step:  ALL shards land their staged rows in ONE program — the
  host ships a [n_dev, C, D] block sharded over the mesh, each chip writes its
  own chunk at its own offset (and derives l2 norms on device). No per-shard
  dispatch loop.
- mesh_delete_step:  tombstone scatter; each chip claims the global rows that
  fall inside its slab.
- mesh_grow_2d/1d:   geometric slab growth fully on device.

The serving-path index built on these kernels is
weaviate_tpu/index/mesh.py (vectorIndexType "hnsw_tpu_mesh").
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from weaviate_tpu.ops.distances import DISTANCE_FNS
from weaviate_tpu.ops.topk import (
    bitmap_to_mask, merge_top_k, pack_topk, rescore_distances,
    translate_pack,
)

SHARD_AXIS = "shard"

if hasattr(jax, "shard_map"):  # jax >= 0.6 spells it jax.shard_map(check_vma=)
    def shard_map_compat(f, *, mesh, in_specs, out_specs):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
else:  # older jax: jax.experimental.shard_map.shard_map(check_rep=)
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map_compat(f, *, mesh, in_specs, out_specs):
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False)

# rows of a slab scored per scan step (bounds the [B, chunk] block in HBM,
# same rationale as index/tpu.py _SCAN_CHUNK)
_MESH_SCAN_CHUNK = 131072


def _merge_across_shards(d_top, i_glob, k):
    """Cross-chip merge inside a shard_fn: all_gather the per-chip (dist,
    global-row) candidate sets over ICI, reselect k, pack. Shared by every
    search kernel so the merge semantics cannot diverge."""
    d_all = jax.lax.all_gather(d_top, SHARD_AXIS, axis=1, tiled=True)
    i_all = jax.lax.all_gather(i_glob, SHARD_AXIS, axis=1, tiled=True)
    neg, pos = jax.lax.top_k(-d_all, k)
    d_fin = -neg
    i_fin = jnp.take_along_axis(i_all, pos, axis=1)
    i_fin = jnp.where(jnp.isinf(d_fin), -1, i_fin).astype(jnp.int32)
    return pack_topk(d_fin, i_fin)


def _merge_across_shards_fused(d_top, i_loc, s2d_l, k):
    """Cross-chip merge with the slot->doc translation fused BEFORE the
    collective: each chip gathers its k winners' doc-id words from its
    LOCAL slab of the sharded [cap, 2] uint32 table (a k-row gather — the
    table itself never crosses ICI), packs (dist | id_lo | id_hi) into the
    PR-14 fused [B, 3k] layout, all_gathers the per-chip packed blocks,
    and reselects the final k by distance. The winning id words ride the
    selection, so the replicated output is ALREADY the fused layout:
    finalize stays one fetch / zero host translation across chips
    (the JGL015 invariant, mesh-shaped). Missing slots (i_loc < 0) carry
    the 0xFFFFFFFF sentinel words from translate_pack and +inf distance,
    so they lose every selection and unpack to the same 2**64-1 id the
    single-chip fused path emits."""
    packed_l = translate_pack(d_top, i_loc, s2d_l)          # [B, 3k] i32
    all_p = jax.lax.all_gather(packed_l, SHARD_AXIS, axis=1, tiled=True)
    b = d_top.shape[0]
    w = all_p.reshape(b, -1, 3, k)                          # [B, n_dev, 3, k]
    d_all = jax.lax.bitcast_convert_type(
        w[:, :, 0, :], jnp.float32).reshape(b, -1)
    lo_all = w[:, :, 1, :].reshape(b, -1)
    hi_all = w[:, :, 2, :].reshape(b, -1)
    neg, pos = jax.lax.top_k(-d_all, k)
    d_fin = -neg
    lo = jnp.take_along_axis(lo_all, pos, axis=1)
    hi = jnp.take_along_axis(hi_all, pos, axis=1)
    return jnp.concatenate(
        [jax.lax.bitcast_convert_type(d_fin, jnp.int32), lo, hi], axis=1)


def _merge_local(d_top, i_loc, s2d_l, my, n_loc, k, fused):
    """The shared per-shard epilogue of every mesh search kernel
    (i_loc [B, k] = LOCAL slab rows, -1 for missing): fused mode
    translates LOCAL winners through the local s2d slab and merges packed
    doc-id candidates; legacy mode rebases to global rows and merges
    (dist, row) pairs for the host-side slot->doc translation."""
    if fused:
        return _merge_across_shards_fused(d_top, i_loc, s2d_l, k)
    i_glob = jnp.where(i_loc >= 0, i_loc + my * n_loc, -1)
    return _merge_across_shards(d_top, i_glob, k)


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_spec(mesh: Mesh, *trailing_dims: None) -> NamedSharding:
    """NamedSharding splitting dim 0 over the mesh shard axis."""
    return NamedSharding(mesh, P(SHARD_AXIS, *trailing_dims))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "use_allow", "use_norms", "exact",
                     "fused", "mesh"),
)
def mesh_search_step(
    store, sq_norms, tombs, n_per_shard, allow_words, queries, s2d,
    k, metric, use_allow, use_norms, exact, fused, mesh,
):
    """Fully-sharded masked kNN.

    store:       [n_dev * n_loc, D] sharded P('shard', None) — HBM slabs
    sq_norms:    [n_dev * n_loc] f32 sharded (l2 only; pass zeros otherwise)
    tombs:       [n_dev * n_loc] bool sharded — tombstone mask
    n_per_shard: [n_dev] int32 replicated — live high-water mark per slab
    allow_words: [n_dev * n_loc / 32] uint32 sharded — packed filter bitmap
    queries:     [B, D] replicated
    s2d:         [n_dev * n_loc, 2] uint32 sharded — per-slab slot->doc id
                 words (consumed only under fused=True; XLA dead-code
                 eliminates the operand otherwise)
    -> fused=True: FUSED packed [B, 3k] i32 (translate_pack layout, doc ids
       already resolved on device), replicated.
       fused=False: packed [B, 2k] i32 (pack_topk), replicated; global
       row = slab row + shard_index * n_loc (the host maps rows -> docIDs).

    Per-chunk selection is lax.approx_min_k (the TPU PartialReduce primitive)
    unless exact; the cross-chunk and cross-chip merges are exact, mirroring
    the single-chip scan in index/tpu.py.
    """
    n_dev = mesh.devices.size
    n_loc = store.shape[0] // n_dev
    dim = store.shape[1]
    chunk = min(n_loc, _MESH_SCAN_CHUNK)
    nchunks = n_loc // chunk  # n_loc is a power of two, so this divides

    def shard_fn(store_l, norms_l, tombs_l, n_all, allow_l, q, s2d_l):
        my = jax.lax.axis_index(SHARD_AXIS)
        n_mine = n_all[my]
        b = q.shape[0]
        store_c = store_l.reshape(nchunks, chunk, dim)
        tombs_c = tombs_l.reshape(nchunks, chunk)
        norms_c = norms_l.reshape(nchunks, chunk) if use_norms else None
        allow_c = allow_l.reshape(nchunks, chunk // 32) if use_allow else None

        def step(carry, xs):
            best_d, best_i = carry
            ci, st, tb = xs[0], xs[1], xs[2]
            j = 3
            nm = None
            if use_norms:
                nm = xs[j]
                j += 1
            al = xs[j] if use_allow else None
            base = ci * chunk
            valid = jnp.logical_and(
                jnp.arange(chunk) + base < n_mine, jnp.logical_not(tb)
            )
            if use_allow:
                valid = jnp.logical_and(valid, bitmap_to_mask(al, chunk))
            d = DISTANCE_FNS[metric](q.astype(st.dtype), st, nm)
            d = jnp.where(valid[None, :], d, jnp.inf)
            if exact:
                neg, li = jax.lax.top_k(-d, k)
                td = -neg
            else:
                td, li = jax.lax.approx_min_k(d, k, recall_target=0.95)
            return merge_top_k(best_d, best_i, td, li + base, k), None

        init = (jnp.full((b, k), jnp.inf, jnp.float32), jnp.full((b, k), -1, jnp.int32))
        xs = [jnp.arange(nchunks), store_c, tombs_c]
        if use_norms:
            xs.append(norms_c)
        if use_allow:
            xs.append(allow_c)
        (d_top, i_top), _ = jax.lax.scan(step, init, tuple(xs))
        return _merge_local(d_top, i_top, s2d_l, my, n_loc, k, fused)

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS), P(SHARD_AXIS), P(),
            P(SHARD_AXIS), P(), P(SHARD_AXIS, None),
        ),
        out_specs=P(),
    )(store, sq_norms, tombs, n_per_shard, allow_words, queries, s2d)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "use_allow", "use_norms", "rg",
                     "active_g", "interpret", "fused", "mesh"),
)
def mesh_search_gmin_step(
    store, sq_norms, tombs, n_per_shard, allow_words, queries, s2d,
    k, metric, use_allow, use_norms, rg, active_g, interpret, fused, mesh,
):
    """Fused group-min kNN, mesh-sharded: each chip runs the SAME Pallas
    fast-scan + exact-rescore the single-chip index uses
    (ops/gmin_scan.gmin_topk) over its own HBM slab — distances never
    round-trip through HBM — and the cross-chip merge all_gathers k
    (dist, global-row) pairs over ICI and reselects, exactly like
    mesh_search_step. Same argument layout as mesh_search_step plus the
    gmin parameters (rg kept groups, active_g live slices per slab)."""
    from weaviate_tpu.ops import gmin_scan

    n_dev = mesh.devices.size
    n_loc = store.shape[0] // n_dev

    def shard_fn(store_l, norms_l, tombs_l, n_all, allow_l, q, s2d_l):
        my = jax.lax.axis_index(SHARD_AXIS)
        n_mine = n_all[my]
        norms = norms_l if use_norms else jnp.zeros_like(norms_l)
        # per-shard block layout computed in-graph: the mesh path has no
        # host-side generation cache, and the transpose is ~ms at slab scale
        blk_l = gmin_scan.build_rescore_blocks(store_l)
        d_top, i_top = gmin_scan.gmin_topk(
            store_l, norms, tombs_l, n_mine, q, allow_l, use_allow,
            k, metric, rg, active_g, interpret, blk_l)
        return _merge_local(d_top, i_top, s2d_l, my, n_loc, k, fused)

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS), P(SHARD_AXIS), P(),
            P(SHARD_AXIS), P(), P(SHARD_AXIS, None),
        ),
        out_specs=P(),
    )(store, sq_norms, tombs, n_per_shard, allow_words, queries, s2d)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "use_allow", "rg", "active_g",
                     "interpret", "fused", "mesh"),
)
def mesh_search_pq_gmin_step(
    codes, recon_norms, tombs, n_per_shard, allow_words, cb_chunks, flat_cb,
    queries, rot, s2d, k, metric, use_allow, rg, active_g, interpret, fused,
    mesh,
):
    """Codes-only fused ADC kNN, mesh-sharded: each chip runs the SAME
    reconstruction-as-matmul Pallas scan the single-chip index uses
    (ops/pq_gmin.pq_gmin_topk) over its own uint8 code slab — codes never
    expand in HBM — and the cross-chip merge all_gathers k (ADC dist,
    global-row) pairs over ICI and reselects, exactly like the dense
    mesh_search_gmin_step. ADC distances are deterministic per slab, so the
    merge is exact w.r.t. the quantizer."""
    from weaviate_tpu.ops import pq_gmin

    n_dev = mesh.devices.size
    n_loc = codes.shape[0] // n_dev

    def shard_fn(codes_l, norms_l, tombs_l, n_all, allow_l, cb_c, fcb, q, r,
                 s2d_l):
        my = jax.lax.axis_index(SHARD_AXIS)
        n_mine = n_all[my]
        d_top, i_top = pq_gmin.pq_gmin_topk(
            codes_l, norms_l, tombs_l, n_mine, q, cb_c, fcb, allow_l,
            use_allow, k, metric, rg, active_g, interpret, r,
            pq_gmin.build_codes_blocks(codes_l))
        return _merge_local(d_top, i_top, s2d_l, my, n_loc, k, fused)

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS), P(SHARD_AXIS), P(),
            P(SHARD_AXIS), P(), P(), P(), P(), P(SHARD_AXIS, None),
        ),
        out_specs=P(),
    )(codes, recon_norms, tombs, n_per_shard, allow_words, cb_chunks,
      flat_cb, queries, rot, s2d)


@functools.partial(
    jax.jit,
    static_argnames=("k", "r_chunk", "metric", "use_allow", "exact",
                     "do_rescore", "fused", "mesh"),
)
def mesh_search_pq_step(
    codes, recon_norms, tombs, n_per_shard, allow_words, codebook,
    rescore_store, queries, rot, s2d, k, r_chunk, metric, use_allow, exact,
    do_rescore, fused, mesh,
):
    """Mesh twin of the single-chip PQ reconstruction scan
    (index/tpu.py _search_pq_recon): each chip scans its OWN code slab —
    gather centroids per chunk into a [chunk, D] block, one bf16 matmul,
    collect per-chunk top-r — then exact-rescores its local candidate pool
    against its local rescore slab and keeps a local top-k; the cross-chip
    merge all_gathers k (dist, global-row) pairs per chip over ICI and
    reselects. Rescored distances are exact f32, so the final merge is
    exact.

    codes:        [n_dev * n_loc, M] sharded P('shard', None)
    recon_norms:  [n_dev * n_loc] f32 sharded (||reconstruction||^2)
    tombs:        [n_dev * n_loc] bool sharded
    n_per_shard:  [n_dev] int32 replicated
    allow_words:  [n_dev * n_loc / 32] uint32 sharded
    codebook:     [M, C, ds] f32 replicated
    rescore_store:[n_dev * n_loc, D] sharded (bf16/f32 row copy)
    -> packed [B, 2k] i32 replicated; rows are global (slab + shard*n_loc).
    """
    n_dev = mesh.devices.size
    n_loc = codes.shape[0] // n_dev
    m = codes.shape[1]
    _, c, ds = codebook.shape
    chunk = min(n_loc, _MESH_SCAN_CHUNK)
    nchunks = n_loc // chunk

    def shard_fn(codes_l, norms_l, tombs_l, n_all, allow_l, cb, rs_l, q, r,
                 s2d_l):
        my = jax.lax.axis_index(SHARD_AXIS)
        n_mine = n_all[my]
        b = q.shape[0]
        flat_cb = cb.reshape(m * c, ds).astype(jnp.bfloat16)
        seg_off = (jnp.arange(m, dtype=jnp.int32) * c)[None, :]
        codes_c = codes_l.reshape(nchunks, chunk, m)
        norms_c = norms_l.reshape(nchunks, chunk)
        tombs_c = tombs_l.reshape(nchunks, chunk)
        allow_c = allow_l.reshape(nchunks, chunk // 32) if use_allow else None
        # OPQ: the ADC scan runs in the quantizer's rotated space; the
        # float rescore below uses the RAW query (the rescore slab holds
        # unrotated rows)
        qr = jnp.matmul(q.astype(jnp.float32), r,
                        preferred_element_type=jnp.float32)
        qd = qr.astype(jnp.bfloat16)
        q_sq = jnp.sum(qr ** 2, axis=-1, keepdims=True)

        def step(_, xs):
            ci, cl, nl, tl = xs[0], xs[1], xs[2], xs[3]
            base = ci * chunk
            idx = cl.astype(jnp.int32) + seg_off
            recon = jnp.take(flat_cb, idx, axis=0).reshape(chunk, m * ds)
            qx = jnp.matmul(qd, recon.T, preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.DEFAULT)
            if metric == "l2-squared":
                d = jnp.maximum(q_sq - 2.0 * qx + nl[None, :], 0.0)
            elif metric == "dot":
                d = -qx
            else:
                d = 1.0 - qx
            valid = jnp.logical_and(jnp.arange(chunk) + base < n_mine,
                                    jnp.logical_not(tl))
            if use_allow:
                valid = jnp.logical_and(valid, bitmap_to_mask(xs[4], chunk))
            d = jnp.where(valid[None, :], d, jnp.inf)
            if exact:
                neg, li = jax.lax.top_k(-d, r_chunk)
                td = -neg
            else:
                td, li = jax.lax.approx_min_k(d, r_chunk, recall_target=0.95)
            return None, (td, li + base)

        xs = [jnp.arange(nchunks), codes_c, norms_c, tombs_c]
        if use_allow:
            xs.append(allow_c)
        _, (tds, lis) = jax.lax.scan(step, None, tuple(xs))
        pool = nchunks * r_chunk
        cand_d = jnp.moveaxis(tds, 0, 1).reshape(b, pool)
        cand_i = jnp.moveaxis(lis, 0, 1).reshape(b, pool)
        if do_rescore:
            safe = jnp.clip(cand_i, 0, n_loc - 1)
            cand = jnp.take(rs_l, safe, axis=0)
            ed = rescore_distances(cand, q, metric)
            cand_d = jnp.where(jnp.isinf(cand_d), jnp.inf, ed)
        neg, pos = jax.lax.top_k(-cand_d, k)
        d_top = -neg
        i_top = jnp.take_along_axis(cand_i, pos, axis=1)
        i_loc = jnp.where(jnp.isinf(d_top), -1, i_top)
        return _merge_local(d_top, i_loc, s2d_l, my, n_loc, k, fused)

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS), P(SHARD_AXIS), P(),
            P(SHARD_AXIS), P(), P(SHARD_AXIS, None), P(), P(),
            P(SHARD_AXIS, None),
        ),
        out_specs=P(),
    )(codes, recon_norms, tombs, n_per_shard, allow_words, codebook,
      rescore_store, queries, rot, s2d)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "use_allow", "top_p", "exact", "gp",
                     "fused", "mesh"),
)
def mesh_search_ivf_step(
    store, tombs, n_per_shard, allow_words, centroids, buckets, queries,
    s2d, k, metric, use_allow, top_p, exact, gp, fused, mesh,
):
    """Partition-pruned kNN over the sharded dense store: the mesh twin of
    ops/ivf.search_ivf_dense. Centroids are replicated (every chip probes
    the SAME nlist partitions — the KScaNN-style balanced assignment is
    done at build time per device), but buckets are per-device: buckets
    [n_dev, nlist, cap_p] int32 sharded over dim 0 holds LOCAL slab slot
    ids (-1 padding), so each chip gathers only the probed candidates that
    physically live in its own HBM slab. Per-shard candidate scoring and
    local top-k mirror the single-chip grouped scan exactly (shared
    _probe/_candidate_slots/_slot_valid/_grouped_topk helpers); the
    cross-chip merge is the same fused/legacy epilogue as every other mesh
    search kernel. No PCA prefilter tier here: the probed per-device pool
    is already 1/n_dev of the single-chip pool, below where the prefilter
    pays for its extra gather."""
    from weaviate_tpu.ops import ivf as ivf_ops

    n_dev = mesh.devices.size
    n_loc = store.shape[0] // n_dev

    def shard_fn(store_l, tombs_l, n_all, allow_l, cent, bkt_l, q, s2d_l):
        my = jax.lax.axis_index(SHARD_AXIS)
        n_mine = n_all[my]
        qf = q.astype(jnp.float32)
        parts = ivf_ops._probe(qf, cent, top_p, metric)
        slots_g = ivf_ops._candidate_slots(parts, bkt_l[0], gp)
        valid_g = ivf_ops._slot_valid(slots_g, n_mine, tombs_l,
                                      allow_l if use_allow else None)

        def score_full(sl):
            rows = jnp.take(store_l, jnp.clip(sl, 0, n_loc - 1), axis=0)
            return rescore_distances(rows, qf, metric)

        d_top, i_top = ivf_ops._grouped_topk(slots_g, valid_g, score_full,
                                             k, exact)
        i_loc = jnp.where(jnp.isinf(d_top), -1, i_top)
        return _merge_local(d_top, i_loc, s2d_l, my, n_loc, k, fused)

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS), P(), P(SHARD_AXIS), P(),
            P(SHARD_AXIS, None, None), P(), P(SHARD_AXIS, None),
        ),
        out_specs=P(),
    )(store, tombs, n_per_shard, allow_words, centroids, buckets, queries,
      s2d)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "use_allow", "rg4", "rc", "exact",
                     "fused", "mesh"),
)
def mesh_search_pq4_step(
    codes4, codes8, recon_norms4, recon_norms8, tombs, n_per_shard,
    allow_words, codebook4, flat_cb8, rescore_store, queries, rot, s2d,
    k, metric, use_allow, rg4, rc, exact, fused, mesh,
):
    """The 4-bit Quick-ADC funnel, mesh-sharded: each chip runs the SAME
    three-stage funnel the single-chip index uses (ops/pq4.pq4_funnel_topk
    — byte-LUT nibble scan -> exact 8-bit ADC of the top rg4*G survivors
    -> exact rescore of the top rc against the chip's own store slab, the
    per-chip stage-3 source) over its own packed uint8 slab, and the
    cross-chip merge all_gathers k (exact dist, global-row) pairs over ICI
    and reselects, exactly like the other mesh search kernels. Stage-3
    distances are exact f32, so the merge is exact.

    codes4:       [n_dev * n_loc, M/2] uint8 sharded — packed nibble pairs
    codes8:       [n_dev * n_loc, M] uint8 sharded — the 8-bit ladder rung
    recon_norms4/8: [n_dev * n_loc] f32 sharded (per-quantizer ||recon||^2)
    codebook4:    [M, 16, ds] f32 replicated
    flat_cb8:     [M * C, ds] bf16 replicated (pq_gmin.cached_cb_constants)
    rescore_store:[n_dev * n_loc, D] sharded — the resident bf16 store
    rot:          [D, D] f32 replicated OPQ rotation (or None)
    rg4/rc are PER-SHARD budgets (each chip funnels its own slab).
    The in-graph traceable stage 1 is used on every chip — the Pallas
    nibble kernel has no shard_map story yet, and the byte LUT is already
    one gather per packed byte."""
    from weaviate_tpu.ops import pq4 as pq4_ops

    n_dev = mesh.devices.size
    n_loc = codes4.shape[0] // n_dev

    def shard_fn(c4_l, c8_l, n4_l, n8_l, tombs_l, n_all, allow_l, cb4, fcb8,
                 rs_l, q, r, s2d_l):
        my = jax.lax.axis_index(SHARD_AXIS)
        n_mine = n_all[my]
        d_top, i_top = pq4_ops.pq4_funnel_topk(
            c4_l, c8_l, n4_l, n8_l, tombs_l, n_mine, q, None, cb4, fcb8,
            rs_l, allow_l, use_allow, k, metric, rg4, rc,
            use_pallas=False, interpret=False, exact=exact, rot=r)
        return _merge_local(d_top, i_top, s2d_l, my, n_loc, k, fused)

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS, None), P(SHARD_AXIS),
            P(SHARD_AXIS), P(SHARD_AXIS), P(), P(SHARD_AXIS), P(), P(),
            P(SHARD_AXIS, None), P(), P(), P(SHARD_AXIS, None),
        ),
        out_specs=P(),
    )(codes4, codes8, recon_norms4, recon_norms8, tombs, n_per_shard,
      allow_words, codebook4, flat_cb8, rescore_store, queries, rot, s2d)


# NOTE on donation: the write kernels below deliberately do NOT donate
# their input slabs. Published MeshSnapshot objects pin the previous
# arrays for in-flight lock-free readers (docs/concurrency.md, snapshot
# plane); donating would hand XLA permission to overwrite buffers a
# concurrent dispatch is still scanning. The copy cost is the price of
# the snapshot contract — identical to the single-chip index's
# non-donating _write_rows/_set_tombstones kernels.
@functools.partial(jax.jit, static_argnames=("mesh",))
def mesh_write_rows_step(arr2d, arr1d, chunks2d, vals1d, offsets, takes, mesh):
    """Generic whole-mesh append for an arbitrary-dtype sharded matrix plus
    a per-row f32 vector (codes + recon_norms on the PQ path): each chip
    with takes[my] > 0 lands its chunk at its own offset."""

    def shard_fn(a2_l, a1_l, ch_l, v1_l, offs, tks):
        my = jax.lax.axis_index(SHARD_AXIS)
        off = offs[my]
        active = tks[my] > 0
        written2 = jax.lax.dynamic_update_slice(
            a2_l, ch_l[0].astype(a2_l.dtype), (off, 0))
        written1 = jax.lax.dynamic_update_slice(a1_l, v1_l[0], (off,))
        return (jnp.where(active, written2, a2_l),
                jnp.where(active, written1, a1_l))

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS), P(SHARD_AXIS, None, None),
            P(SHARD_AXIS, None), P(), P(),
        ),
        out_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS)),
    )(arr2d, arr1d, chunks2d, vals1d, offsets, takes)


@functools.partial(jax.jit, static_argnames=("use_norms", "mesh"))
def mesh_insert_step(store, sq_norms, chunks, offsets, takes, use_norms, mesh):
    """One whole-mesh append: chunks [n_dev, C, D] sharded over dim 0 (each
    chip receives only its own [C, D] block), offsets/takes [n_dev]
    replicated. Every chip with work (takes[my] > 0) writes its chunk into
    its slab at its own offset and derives the l2 square-norms on device — a
    full import lands in one SPMD program regardless of shard count.

    Chips with takes[my] == 0 keep their slab bit-identical: the masked
    select below matters because a full slab's offset would clamp inside
    dynamic_update_slice and silently zero live rows."""

    def shard_fn(store_l, norms_l, chunk_l, offs, tks):
        my = jax.lax.axis_index(SHARD_AXIS)
        off = offs[my]
        active = tks[my] > 0
        ch = chunk_l[0]  # [C, D]
        written = jax.lax.dynamic_update_slice(
            store_l, ch.astype(store_l.dtype), (off, 0)
        )
        new_store = jnp.where(active, written, store_l)
        if use_norms:
            nch = jnp.sum(ch.astype(jnp.float32) ** 2, axis=1)
            new_norms = jnp.where(
                active, jax.lax.dynamic_update_slice(norms_l, nch, (off,)), norms_l
            )
        else:
            new_norms = norms_l
        return new_store, new_norms

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS), P(SHARD_AXIS, None, None), P(), P(),
        ),
        out_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS)),
    )(store, sq_norms, chunks, offsets, takes)


@functools.partial(jax.jit, static_argnames=("mesh",))
def mesh_delete_step(tombs, rows, mesh):
    """Tombstone scatter: rows [P] int32 global rows, padded with -1. Each
    chip claims the rows inside its slab; out-of-slab rows map to the
    out-of-range sentinel and are dropped by the scatter."""
    n_loc = tombs.shape[0] // mesh.devices.size

    def shard_fn(tombs_l, rows_r):
        my = jax.lax.axis_index(SHARD_AXIS)
        lo = my * n_loc
        mine = jnp.logical_and(rows_r >= lo, rows_r < lo + n_loc)
        local = jnp.where(mine, rows_r - lo, n_loc)
        return tombs_l.at[local].set(True, mode="drop")

    return shard_map_compat(
        shard_fn, mesh=mesh, in_specs=(P(SHARD_AXIS), P()),
        out_specs=P(SHARD_AXIS),
    )(tombs, rows)


@functools.partial(jax.jit, static_argnames=("mesh",))
def mesh_write_pairs_step(s2d, pairs, offsets, takes, mesh):
    """Whole-mesh append for the sharded slot->doc word table: pairs
    [n_dev, C, 2] uint32 sharded over dim 0 (each chip lands only its own
    [C, 2] block of (id_lo, id_hi) words), offsets/takes [n_dev]
    replicated. Same masked-select discipline as mesh_insert_step, and
    same non-donation contract — published snapshots pin the old table."""

    def shard_fn(s2d_l, pairs_l, offs, tks):
        my = jax.lax.axis_index(SHARD_AXIS)
        off = offs[my]
        active = tks[my] > 0
        written = jax.lax.dynamic_update_slice(s2d_l, pairs_l[0], (off, 0))
        return jnp.where(active, written, s2d_l)

    return shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS, None), P(SHARD_AXIS, None, None), P(), P(),
        ),
        out_specs=P(SHARD_AXIS, None),
    )(s2d, pairs, offsets, takes)


@functools.partial(jax.jit, static_argnames=("new_loc", "fill", "mesh"))
def mesh_grow_pairs(arr, new_loc, fill, mesh):
    """mesh_grow_2d for the slot->doc word table: the growth padding is
    the unwritten-slot sentinel (index/tpu.py _S2D_FILL), not zero — a
    zero pad would read as doc id 0. Slab-local offsets are preserved, so
    each chip's prefix stays valid after the grow."""

    def shard_fn(arr_l):
        out = jnp.full((new_loc, arr_l.shape[1]), fill, arr_l.dtype)
        return jax.lax.dynamic_update_slice(out, arr_l, (0, 0))

    return shard_map_compat(
        shard_fn, mesh=mesh, in_specs=(P(SHARD_AXIS, None),),
        out_specs=P(SHARD_AXIS, None),
    )(arr)


@functools.partial(jax.jit, static_argnames=("new_loc", "mesh"))
def mesh_grow_2d(store, new_loc, mesh):
    """Geometric slab growth (maintainance.go:31 parity) without leaving the
    device: every chip pads its own slab to [new_loc, D]."""

    def shard_fn(store_l):
        out = jnp.zeros((new_loc, store_l.shape[1]), store_l.dtype)
        return jax.lax.dynamic_update_slice(out, store_l, (0, 0))

    return shard_map_compat(
        shard_fn, mesh=mesh, in_specs=(P(SHARD_AXIS, None),),
        out_specs=P(SHARD_AXIS, None),
    )(store)


@functools.partial(jax.jit, static_argnames=("new_loc", "mesh"))
def mesh_grow_1d(arr, new_loc, mesh):
    def shard_fn(arr_l):
        out = jnp.zeros((new_loc,), arr_l.dtype)
        return jax.lax.dynamic_update_slice(out, arr_l, (0,))

    return shard_map_compat(
        shard_fn, mesh=mesh, in_specs=(P(SHARD_AXIS),),
        out_specs=P(SHARD_AXIS),
    )(arr)


class MeshSearchPlan:
    """Thin compatibility facade over the mesh index (weaviate_tpu/index/mesh.py)
    for standalone use (the driver dry run, notebooks): round-robin placement,
    no durability."""

    def __init__(self, mesh: Mesh, dim: int, capacity_per_shard: int = 16384,
                 metric: str = "l2-squared", dtype=jnp.float32):
        from weaviate_tpu.entities import vectorindex as vi
        from weaviate_tpu.index.mesh import MeshVectorIndex

        cfg = vi.HnswUserConfig(index_type="hnsw_tpu_mesh", distance=metric)
        if dtype == jnp.bfloat16:
            cfg.store_dtype = "bfloat16"
        self.index = MeshVectorIndex(
            cfg, shard_path="", persist=False, mesh=mesh,
            initial_capacity_per_shard=capacity_per_shard, dim_hint=dim,
        )
        self.mesh = mesh
        self.dim = dim

    def add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        self.index.add_batch(np.asarray(doc_ids), np.asarray(vectors, np.float32))

    def search(self, queries: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
        ids, d = self.index.search_by_vectors(np.asarray(queries, np.float32), k)
        # uint64 sentinel (max) -> -1 for the standalone API
        return ids.view(np.int64), d
