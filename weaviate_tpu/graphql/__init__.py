"""GraphQL engine: query parser + executor for Get / Aggregate / Explore.

Reference: adapters/handlers/graphql — the reference builds a graphql-go
schema dynamically from the data schema (local/get/class_builder_fields.go)
and lets the library execute. graphql-core is not available in this image, so
this package implements the query-language subset Weaviate's GraphQL surface
actually uses: operations, arguments (including enum/object/list literals),
variables, aliases, and inline fragments (for cross-references); executed
directly against the traverser/aggregator.
"""

from weaviate_tpu.graphql.executor import GraphQLExecutor
from weaviate_tpu.graphql.parser import GraphQLParseError, parse_query

__all__ = ["GraphQLExecutor", "parse_query", "GraphQLParseError"]
