"""GraphQL query parser (lexer + recursive descent -> light AST).

Covers the GraphQL-spec query subset the Weaviate API surface uses:
operation (query/anonymous, with variable definitions), selection sets,
field arguments with Int/Float/String/Boolean/Enum/List/Object/Variable
values, aliases, inline fragments (`... on Class`), and named fragments.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


class GraphQLParseError(ValueError):
    pass


@dataclass
class EnumValue:
    """Distinguishes `Equal` (enum token) from `"Equal"` (string)."""

    name: str

    def __str__(self) -> str:
        return self.name


@dataclass
class Variable:
    name: str


# sentinel: variable declared without a default — must be provided at execute
_REQUIRED = object()


@dataclass
class Field:
    name: str
    alias: Optional[str] = None
    args: dict[str, Any] = field(default_factory=dict)
    selections: list = field(default_factory=list)

    @property
    def out_name(self) -> str:
        return self.alias or self.name


@dataclass
class InlineFragment:
    type_name: str
    selections: list = field(default_factory=list)


@dataclass
class FragmentSpread:
    name: str


@dataclass
class Operation:
    kind: str = "query"
    name: Optional[str] = None
    variable_defaults: dict[str, Any] = field(default_factory=dict)
    selections: list = field(default_factory=list)


@dataclass
class Document:
    operation: Operation
    fragments: dict[str, InlineFragment] = field(default_factory=dict)


# -- lexer -------------------------------------------------------------------

_PUNCT = set("{}()[]:,=!$@")


def _tokenize(src: str) -> list[tuple[str, Any]]:
    toks: list[tuple[str, Any]] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n,":
            i += 1
            continue
        if c == "#":
            while i < n and src[i] != "\n":
                i += 1
            continue
        if src.startswith("...", i):
            toks.append(("ellipsis", "..."))
            i += 3
            continue
        if c in _PUNCT:
            toks.append(("punct", c))
            i += 1
            continue
        if c == '"':
            if src.startswith('"""', i):
                end = src.find('"""', i + 3)
                if end < 0:
                    raise GraphQLParseError("unterminated block string")
                toks.append(("string", src[i + 3 : end]))
                i = end + 3
                continue
            j = i + 1
            buf = []
            while j < n:
                if src[j] == "\\":
                    if j + 1 >= n:
                        raise GraphQLParseError("unterminated string escape")
                    esc = src[j + 1]
                    mapping = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\", "/": "/"}
                    if esc == "u":
                        if j + 6 > n:
                            raise GraphQLParseError("unterminated unicode escape")
                        try:
                            buf.append(chr(int(src[j + 2 : j + 6], 16)))
                        except ValueError:
                            raise GraphQLParseError(
                                f"invalid unicode escape {src[j : j + 6]!r}"
                            ) from None
                        j += 6
                        continue
                    buf.append(mapping.get(esc, esc))
                    j += 2
                    continue
                if src[j] == '"':
                    break
                buf.append(src[j])
                j += 1
            if j >= n:
                raise GraphQLParseError("unterminated string")
            toks.append(("string", "".join(buf)))
            i = j + 1
            continue
        if c.isdigit() or (c == "-" and i + 1 < n and (src[i + 1].isdigit() or src[i + 1] == ".")):
            j = i + 1
            while j < n and (src[j].isdigit() or src[j] in ".eE+-"):
                # stop if +/- not after e/E
                if src[j] in "+-" and src[j - 1] not in "eE":
                    break
                j += 1
            text = src[i:j]
            try:
                if any(ch in text for ch in ".eE"):
                    toks.append(("float", float(text)))
                else:
                    toks.append(("int", int(text)))
            except ValueError:
                raise GraphQLParseError(f"malformed number literal {text!r}") from None
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i + 1
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            toks.append(("name", src[i:j]))
            i = j
            continue
        raise GraphQLParseError(f"unexpected character {c!r} at offset {i}")
    toks.append(("eof", None))
    return toks


# -- parser ------------------------------------------------------------------


class _Parser:
    def __init__(self, toks: list[tuple[str, Any]], variables: dict[str, Any]):
        self.toks = toks
        self.pos = 0
        self.variables = variables

    def peek(self):
        return self.toks[self.pos]

    def next(self):
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def expect_punct(self, ch: str):
        kind, val = self.next()
        if kind != "punct" or val != ch:
            raise GraphQLParseError(f"expected {ch!r}, got {val!r}")

    def expect_name(self) -> str:
        kind, val = self.next()
        if kind != "name":
            raise GraphQLParseError(f"expected name, got {val!r}")
        return val

    def parse_document(self) -> Document:
        op: Optional[Operation] = None
        fragments: dict[str, InlineFragment] = {}
        while self.peek()[0] != "eof":
            kind, val = self.peek()
            if kind == "punct" and val == "{":
                if op is not None:
                    raise GraphQLParseError("multiple anonymous operations")
                op = Operation(selections=self.parse_selection_set())
            elif kind == "name" and val in ("query",):
                self.next()
                o = Operation()
                if self.peek()[0] == "name":
                    o.name = self.next()[1]
                if self.peek() == ("punct", "("):
                    self._parse_variable_defs(o)
                o.selections = self.parse_selection_set()
                if op is not None:
                    raise GraphQLParseError("multiple operations not supported")
                op = o
            elif kind == "name" and val in ("mutation", "subscription"):
                raise GraphQLParseError(f"{val} operations are not supported")
            elif kind == "name" and val == "fragment":
                self.next()
                fname = self.expect_name()
                on = self.expect_name()
                if on != "on":
                    raise GraphQLParseError("expected 'on' in fragment definition")
                tname = self.expect_name()
                fragments[fname] = InlineFragment(tname, self.parse_selection_set())
            else:
                raise GraphQLParseError(f"unexpected token {val!r}")
        if op is None:
            raise GraphQLParseError("no operation in document")
        return Document(op, fragments)

    def _parse_variable_defs(self, op: Operation):
        self.expect_punct("(")
        while self.peek() != ("punct", ")"):
            self.expect_punct("$")
            vname = self.expect_name()
            self.expect_punct(":")
            self._skip_type()
            default = _REQUIRED
            if self.peek() == ("punct", "="):
                self.next()
                default = self.parse_value()
            op.variable_defaults[vname] = default
        self.next()  # )

    def _skip_type(self):
        kind, val = self.next()
        if kind == "punct" and val == "[":
            self._skip_type()
            self.expect_punct("]")
        elif kind != "name":
            raise GraphQLParseError(f"expected type, got {val!r}")
        if self.peek() == ("punct", "!"):
            self.next()

    def parse_selection_set(self) -> list:
        self.expect_punct("{")
        out = []
        while self.peek() != ("punct", "}"):
            kind, val = self.peek()
            if kind == "ellipsis":
                self.next()
                k2, v2 = self.peek()
                if k2 == "name" and v2 == "on":
                    self.next()
                    tname = self.expect_name()
                    out.append(InlineFragment(tname, self.parse_selection_set()))
                else:
                    out.append(FragmentSpread(self.expect_name()))
                continue
            if kind != "name":
                raise GraphQLParseError(f"expected field name, got {val!r}")
            name = self.next()[1]
            f = Field(name=name)
            if self.peek() == ("punct", ":"):
                self.next()
                f.alias, f.name = name, self.expect_name()
            if self.peek() == ("punct", "("):
                f.args = self.parse_arguments()
            # skip directives
            while self.peek() == ("punct", "@"):
                self.next()
                self.expect_name()
                if self.peek() == ("punct", "("):
                    self.parse_arguments()
            if self.peek() == ("punct", "{"):
                f.selections = self.parse_selection_set()
            out.append(f)
        self.next()  # }
        return out

    def parse_arguments(self) -> dict[str, Any]:
        self.expect_punct("(")
        args = {}
        while self.peek() != ("punct", ")"):
            name = self.expect_name()
            self.expect_punct(":")
            args[name] = self.parse_value()
        self.next()  # )
        return args

    def parse_value(self) -> Any:
        kind, val = self.next()
        if kind in ("int", "float", "string"):
            return val
        if kind == "punct" and val == "$":
            vname = self.expect_name()
            return Variable(vname)
        if kind == "punct" and val == "[":
            out = []
            while self.peek() != ("punct", "]"):
                out.append(self.parse_value())
            self.next()
            return out
        if kind == "punct" and val == "{":
            obj = {}
            while self.peek() != ("punct", "}"):
                k = self.expect_name()
                self.expect_punct(":")
                obj[k] = self.parse_value()
            self.next()
            return obj
        if kind == "name":
            if val == "true":
                return True
            if val == "false":
                return False
            if val == "null":
                return None
            return EnumValue(val)
        raise GraphQLParseError(f"unexpected value token {val!r}")


def _resolve(value: Any, variables: dict[str, Any], defaults: dict[str, Any]) -> Any:
    if isinstance(value, Variable):
        if value.name in variables:
            return variables[value.name]
        if value.name in defaults and defaults[value.name] is not _REQUIRED:
            return defaults[value.name]
        raise GraphQLParseError(f"variable ${value.name} not provided")
    if isinstance(value, list):
        return [_resolve(v, variables, defaults) for v in value]
    if isinstance(value, dict):
        return {k: _resolve(v, variables, defaults) for k, v in value.items()}
    return value


def _resolve_selections(sels: list, variables, defaults, fragments) -> list:
    out = []
    for s in sels:
        if isinstance(s, FragmentSpread):
            frag = fragments.get(s.name)
            if frag is None:
                raise GraphQLParseError(f"unknown fragment {s.name!r}")
            out.append(
                InlineFragment(
                    frag.type_name,
                    _resolve_selections(frag.selections, variables, defaults, fragments),
                )
            )
        elif isinstance(s, InlineFragment):
            out.append(
                InlineFragment(
                    s.type_name,
                    _resolve_selections(s.selections, variables, defaults, fragments),
                )
            )
        else:
            out.append(
                Field(
                    name=s.name,
                    alias=s.alias,
                    args={k: _resolve(v, variables, defaults) for k, v in s.args.items()},
                    selections=_resolve_selections(s.selections, variables, defaults, fragments),
                )
            )
    return out


def parse_query(src: str, variables: Optional[dict[str, Any]] = None) -> Operation:
    """Parse + resolve variables/fragments -> a plain Operation whose arg
    values are Python literals (EnumValue for enum tokens)."""
    doc = _Parser(_tokenize(src), variables or {}).parse_document()
    op = doc.operation
    op.selections = _resolve_selections(
        op.selections, variables or {}, op.variable_defaults, doc.fragments
    )
    return op
