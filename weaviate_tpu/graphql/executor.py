"""GraphQL executor: Get / Aggregate / Explore roots over the traverser.

Reference: adapters/handlers/graphql/local — get (class_builder_fields.go:229
makeResolveGetClass), aggregate, explore; the `where` grammar of
local/common_filters, `_additional` props (class_builder_fields.go:526-620),
and result->map conversion (usecases/traverser/explorer.go:338).
"""

from __future__ import annotations

from typing import Any, Optional


from weaviate_tpu.entities.filters import LocalFilter
from weaviate_tpu.graphql.parser import (
    EnumValue,
    Field,
    GraphQLParseError,
    InlineFragment,
    parse_query,
)
from weaviate_tpu.monitoring import tracing
from weaviate_tpu.serving import robustness
from weaviate_tpu.usecases.aggregator import AggregateParams
from weaviate_tpu.usecases.traverser import GetParams


def _plain(v: Any) -> Any:
    """EnumValue -> str, recursively (args arrive enum-typed from the parser)."""
    if isinstance(v, EnumValue):
        return v.name
    if isinstance(v, list):
        return [_plain(x) for x in v]
    if isinstance(v, dict):
        return {k: _plain(x) for k, x in v.items()}
    return v


class GraphQLExecutor:
    def __init__(self, traverser, aggregator, schema_manager, db):
        self.traverser = traverser
        self.aggregator = aggregator
        self.schema = schema_manager
        self.db = db

    # -- entry ---------------------------------------------------------------

    def execute(self, query: str, variables: Optional[dict] = None) -> dict:
        try:
            op = parse_query(query, variables)
        except GraphQLParseError as e:
            return {"errors": [{"message": str(e)}]}
        data: dict[str, Any] = {}
        errors: list[dict] = []
        for sel in op.selections:
            if not isinstance(sel, Field):
                errors.append({"message": "fragments not allowed at root"})
                continue
            try:
                if sel.name == "Get":
                    data[sel.out_name] = self._exec_get(sel)
                elif sel.name == "Aggregate":
                    data[sel.out_name] = self._exec_aggregate(sel)
                elif sel.name == "Explore":
                    data[sel.out_name] = self._exec_explore(sel)
                elif sel.name == "__schema":
                    from weaviate_tpu.graphql.introspection import (
                        build_introspection,
                        project_tree,
                    )

                    data[sel.out_name] = project_tree(
                        build_introspection(self.schema), sel.selections
                    )
                elif sel.name == "__type":
                    from weaviate_tpu.graphql.introspection import (
                        find_type,
                        project_tree,
                    )

                    name = str(sel.args.get("name", ""))
                    data[sel.out_name] = project_tree(
                        find_type(self.schema, name), sel.selections
                    )
                else:
                    errors.append({"message": f"unknown root field {sel.name!r}"})
            except (robustness.DeadlineExceededError,
                    robustness.OverloadedError):
                # request-level lifecycle conditions, not per-field errors:
                # propagate so the REST layer maps them to 504 / 429 (+
                # Retry-After) instead of burying them in a 200 envelope
                raise
            except Exception as e:
                errors.append({"message": str(e), "path": [sel.name]})
        out: dict[str, Any] = {"data": data}
        if errors:
            out["errors"] = errors
        return out

    # -- Get -----------------------------------------------------------------

    # Get args the executor itself understands; module near-args are added
    # per enabled provider (class_builder_fields.go:210-233 arg surface)
    _GET_ARGS = frozenset({
        "where", "nearVector", "nearObject", "bm25", "hybrid", "group",
        "groupBy", "sort", "limit", "offset", "after", "ask",
        "consistencyLevel",
    })
    _BUILTIN_ADDITIONAL = frozenset({
        "id", "vector", "certainty", "distance", "score", "explainScore",
        "creationTimeUnix", "lastUpdateTimeUnix", "classification",
        "isConsistent", "group",
    })

    def _validate_get_class(self, class_field: Field) -> None:
        """Schema validation the reference gets from its generated GraphQL
        schema (class_builder_fields.go): unknown args, unknown properties,
        and unknown _additional props are errors, not silent nulls."""
        resolved = self.schema.resolve_class_name(class_field.name)
        cd = self.schema.get_class(resolved) if resolved else None
        if cd is None:
            raise GraphQLParseError(f"class {class_field.name!r} not found")
        provider = self._module_provider()
        args_ok = set(self._GET_ARGS)
        add_ok = set(self._BUILTIN_ADDITIONAL)
        if provider is not None:
            args_ok.update(provider.graphql_arguments())
            add_ok.update(provider.additional_properties())
        for a in class_field.args:
            if a not in args_ok:
                raise GraphQLParseError(
                    f"unknown argument {a!r} on Get.{class_field.name}")
        props = {p.name for p in cd.properties}
        for s in class_field.selections:
            if not isinstance(s, Field):
                continue
            if s.name == "_additional":
                for sub in s.selections:
                    if isinstance(sub, Field) and sub.name not in add_ok:
                        raise GraphQLParseError(
                            f"unknown _additional prop {sub.name!r}")
            elif s.name not in props:
                raise GraphQLParseError(
                    f"class {class_field.name!r} has no property {s.name!r}")

    def _exec_get(self, root: Field) -> dict:
        out = {}
        for class_field in root.selections:
            if not isinstance(class_field, Field):
                raise GraphQLParseError("expected class field under Get")
            # one span per Get class: a multi-class query's trace shows
            # which class the time went to, not one opaque "graphql" blob
            # the tenant rides the same contextvar plumbing as the
            # deadline; tagging the span here keeps multi-class queries'
            # per-class time attributable per tenant in the slow-query log
            with tracing.span(
                    "graphql.get", class_name=class_field.name,
                    tenant=robustness.effective_tenant(class_field.name)):
                self._validate_get_class(class_field)
                params = self._get_params(class_field)
                results = self.traverser.get_class(params)
                self._resolve_module_additionals(class_field, params, results)
                self._resolve_is_consistent(class_field, params, results)
                # per-query ref cache (refcache/ role): N results pointing
                # at the same referenced object hit storage once, not N
                # times
                ref_cache: dict[str, object] = {}
                out[class_field.out_name] = [
                    self._project(r, class_field.selections, params,
                                  ref_cache)
                    for r in results
                ]
        return out

    def _module_provider(self):
        return getattr(getattr(self.traverser, "explorer", None), "modules", None)

    def _resolve_is_consistent(self, class_field: Field, params: GetParams,
                               results) -> None:
        """Batch isConsistent resolution (finder.go CheckConsistency):
        resolve the class once and fan the per-row digest probes out in
        parallel — the per-row sequential form costs N_results x N_replicas
        network roundtrips."""
        wanted = any(
            isinstance(sel, Field) and sel.name == "_additional"
            and any(isinstance(x, Field) and x.name == "isConsistent"
                    for x in sel.selections)
            for sel in class_field.selections
        )
        if not wanted or not results:
            return
        resolved = self.schema.resolve_class_name(params.class_name)
        cidx = self.db.get_index(resolved) if resolved else None
        if cidx is None or cidx.finder is None:
            return  # _additional defaults isConsistent to True
        verdicts = cidx.are_consistent(
            [(r.obj.uuid, r.obj.last_update_time_unix) for r in results])
        for r, v in zip(results, verdicts):
            r.additional["isConsistent"] = v

    def _resolve_module_additionals(self, class_field: Field, params: GetParams,
                                    results) -> None:
        """Batch-resolve module-provided _additional props (answer, generate,
        summary, tokens, spellCheck, ...) once per query and attach the
        per-result payloads (modulecapabilities/additional.go dispatch)."""
        provider = self._module_provider()
        if provider is None or not results:
            return
        module_props = set(provider.additional_properties())
        if not module_props:
            return
        class_def = self.schema.get_class(params.class_name)
        for sel in class_field.selections:
            if not (isinstance(sel, Field) and sel.name == "_additional"):
                continue
            for sub in sel.selections:
                if not isinstance(sub, Field) or sub.name not in module_props:
                    continue
                if sub.name == "answer":
                    prop_params = _plain(params.ask) if params.ask else {}
                elif sub.name == "semanticPath":
                    # sempath/builder.go: the path starts at the nearText
                    # query concepts, so the resolver needs them
                    prop_params = {k: _plain(v) for k, v in sub.args.items()}
                    prop_params["near_text"] = _plain(params.near_text) if params.near_text else None
                elif sub.name == "spellCheck":
                    concepts = (params.near_text or {}).get("concepts") or []
                    if isinstance(concepts, str):
                        concepts = [concepts]
                    prop_params = {"text": " ".join(str(c) for c in concepts)}
                else:
                    prop_params = {k: _plain(v) for k, v in sub.args.items()}
                values = provider.resolve_additional(
                    sub.name, results, prop_params, class_def=class_def)
                for r, v in zip(results, values):
                    r.additional[sub.name] = v

    def _get_params(self, f: Field) -> GetParams:
        a = {k: _plain(v) for k, v in f.args.items()}
        where = a.get("where")
        needs_vector = self._selection_wants_vector(f.selections)
        params = GetParams(
            class_name=f.name,
            filters=LocalFilter.from_dict(self._convert_where(where)) if where else None,
            near_vector=a.get("nearVector"),
            near_object=a.get("nearObject"),
            near_text=a.get("nearText"),
            near_image=a.get("nearImage"),
            ask=a.get("ask"),
            keyword_ranking=a.get("bm25"),
            hybrid=a.get("hybrid"),
            sort=self._as_list(a.get("sort")),
            group=a.get("group"),
            group_by=a.get("groupBy"),
            limit=int(a.get("limit", 0) or 0),  # 0 => traverser's query_limit
            offset=int(a.get("offset", 0) or 0),
            after=a.get("after"),
            include_vector=needs_vector,
            consistency_level=(a.get("consistencyLevel") or None),
        )
        if params.keyword_ranking is not None:
            params.keyword_ranking = dict(params.keyword_ranking)
            params.keyword_ranking.setdefault("query", "")
        return params

    @staticmethod
    def _as_list(v):
        if v is None:
            return []
        return v if isinstance(v, list) else [v]

    def _convert_where(self, w: dict) -> dict:
        """GraphQL where arg -> entities.filters dict (same keys; nested
        operands recursed; enum operator already plain)."""
        out = dict(w)
        if "operands" in out and out["operands"]:
            out["operands"] = [self._convert_where(o) for o in out["operands"]]
        return out

    # _additional props whose module resolvers need the result vectors
    # (explain.py: neighbors/path/interpretation/projection all score
    # against the object embedding)
    _VECTOR_HUNGRY_PROPS = frozenset(
        {"vector", "featureProjection", "nearestNeighbors", "semanticPath",
         "interpretation"})

    def _selection_wants_vector(self, sels: list) -> bool:
        for s in sels:
            if isinstance(s, Field) and s.name == "_additional":
                for sub in s.selections:
                    if isinstance(sub, Field) and sub.name in self._VECTOR_HUNGRY_PROPS:
                        return True
        return False

    # -- result projection ---------------------------------------------------

    def _project(self, r, sels: list, params: GetParams,
                 ref_cache: Optional[dict] = None) -> dict:
        obj = r.obj
        row: dict[str, Any] = {}
        for s in sels:
            if isinstance(s, InlineFragment):
                continue
            if s.name == "_additional":
                row[s.out_name] = self._additional(r, s.selections, params)
                continue
            value = obj.properties.get(s.name)
            if s.selections and isinstance(value, list):
                # cross-reference: resolve beacons via inline fragments
                row[s.out_name] = self._resolve_refs(value, s.selections, ref_cache)
            elif s.selections and isinstance(value, dict):
                row[s.out_name] = {
                    sub.out_name: value.get(sub.name)
                    for sub in s.selections
                    if isinstance(sub, Field)
                }
            else:
                row[s.out_name] = value
        return row

    def _resolve_refs(self, beacons: list, sels: list,
                      ref_cache: Optional[dict] = None) -> list:
        out = []
        frags = [s for s in sels if isinstance(s, InlineFragment)]
        for b in beacons:
            beacon = b.get("beacon") if isinstance(b, dict) else None
            if beacon is None:
                continue
            if ref_cache is not None and beacon in ref_cache:
                obj = ref_cache[beacon]
                if obj is None:
                    continue
                self._project_ref(obj, frags, out)
                continue
            parts = beacon.split("weaviate://")[-1].split("/")
            # host/Class/uuid or host/uuid (legacy)
            target_class = parts[1] if len(parts) >= 3 else None
            target_uuid = parts[-1]
            obj, idx = (None, None)
            if target_class:
                tidx = self.db.get_index(target_class)
                if tidx is not None:
                    obj = tidx.object_by_uuid(target_uuid, include_vector=False)
            else:
                obj, idx = self.db.object_by_uuid_any_class(target_uuid, False)
            if ref_cache is not None:
                ref_cache[beacon] = obj
            if obj is None:
                continue
            self._project_ref(obj, frags, out)
        return out

    @staticmethod
    def _project_ref(obj, frags, out: list) -> None:
        for frag in frags:
            if frag.type_name == obj.class_name:
                row = {
                    sub.out_name: obj.properties.get(sub.name)
                    for sub in frag.selections
                    if isinstance(sub, Field) and sub.name != "_additional"
                }
                for sub in frag.selections:
                    if isinstance(sub, Field) and sub.name == "_additional":
                        row[sub.out_name] = {"id": obj.uuid}
                out.append(row)

    def _additional(self, r, sels: list, params: GetParams) -> dict:
        obj = r.obj
        add: dict[str, Any] = {}
        for s in sels:
            if not isinstance(s, Field):
                continue
            n = s.name
            if n == "id":
                add[s.out_name] = obj.uuid
            elif n == "vector":
                add[s.out_name] = (
                    [float(x) for x in obj.vector] if obj.vector is not None else None
                )
            elif n == "distance":
                add[s.out_name] = r.distance
            elif n == "certainty":
                add[s.out_name] = (
                    r.certainty
                    if r.certainty is not None
                    else (
                        max(0.0, 1.0 - r.distance / 2.0)
                        if r.distance is not None and self._is_cosine(params.class_name)
                        else None
                    )
                )
            elif n == "score":
                add[s.out_name] = None if r.score is None else str(r.score)
            elif n == "explainScore":
                add[s.out_name] = r.explain_score
            elif n == "creationTimeUnix":
                add[s.out_name] = str(obj.creation_time_unix)
            elif n == "lastUpdateTimeUnix":
                add[s.out_name] = str(obj.last_update_time_unix)
            elif n == "group":
                add[s.out_name] = r.additional.get("group")
            elif n == "classification":
                # stamped at classification time (usecases/classification.py
                # _class_meta; entities/additional/classification.go shape),
                # projected to the selected subfields with aliases honored
                payload = (obj.meta or {}).get("classification")
                subs = [x for x in s.selections if isinstance(x, Field)]
                if payload is not None and subs:
                    payload = {x.out_name: payload.get(x.name) for x in subs}
                add[s.out_name] = payload
            elif n == "isConsistent":
                # batch-resolved once per query (_resolve_is_consistent)
                add[s.out_name] = r.additional.get("isConsistent", True)
            else:
                add[s.out_name] = r.additional.get(n)
        return add

    def _is_cosine(self, class_name: str) -> bool:
        resolved = self.schema.resolve_class_name(class_name)
        idx = self.db.get_index(resolved) if resolved else None
        return idx is not None and idx.vector_config.distance == "cosine"

    # -- Aggregate -----------------------------------------------------------

    _AGGREGATE_ARGS = frozenset({
        "where", "nearVector", "nearObject", "objectLimit", "groupBy", "limit",
    })
    # module near-args AggregateParams can actually execute; intersected
    # with the provider's contributed args so Get/Aggregate share one
    # source of truth without claiming support Aggregate lacks
    _AGGREGATE_MODULE_ARGS = frozenset({"nearText"})

    def _exec_aggregate(self, root: Field) -> dict:
        out = {}
        for class_field in root.selections:
            if not isinstance(class_field, Field):
                continue
            resolved_name = self.schema.resolve_class_name(class_field.name)
            cd = self.schema.get_class(resolved_name) if resolved_name else None
            if cd is None:
                raise GraphQLParseError(f"class {class_field.name!r} not found")
            props_ok = {p.name for p in cd.properties} | {"meta", "groupedBy"}
            args_ok = set(self._AGGREGATE_ARGS)
            provider = self._module_provider()
            if provider is not None:
                args_ok.update(
                    set(provider.graphql_arguments()) & self._AGGREGATE_MODULE_ARGS)
            for arg in class_field.args:
                if arg not in args_ok:
                    raise GraphQLParseError(
                        f"unknown argument {arg!r} on Aggregate.{class_field.name}")
            for s in class_field.selections:
                if isinstance(s, Field) and s.name not in props_ok:
                    raise GraphQLParseError(
                        f"class {class_field.name!r} has no property {s.name!r}")
            a = {k: _plain(v) for k, v in class_field.args.items()}
            prop_aggs: dict[str, list[str]] = {}
            include_meta = False
            group_by_sel = False
            for s in class_field.selections:
                if not isinstance(s, Field):
                    continue
                if s.name == "meta":
                    include_meta = True
                elif s.name == "groupedBy":
                    group_by_sel = True
                else:
                    prop_aggs[s.name] = [
                        sub.name for sub in s.selections if isinstance(sub, Field)
                    ]
            gb = a.get("groupBy")
            params = AggregateParams(
                class_name=class_field.name,
                filters=(
                    LocalFilter.from_dict(self._convert_where(a["where"]))
                    if a.get("where")
                    else None
                ),
                near_vector=a.get("nearVector"),
                near_object=a.get("nearObject"),
                near_text=a.get("nearText"),
                object_limit=a.get("objectLimit"),
                group_by=self._as_list(gb) if gb else None,
                properties=prop_aggs,
                include_meta_count=include_meta,
                limit=a.get("limit"),
            )
            groups = self.aggregator.aggregate(params)
            rows = []
            for g in groups:
                row = dict(g)
                if not group_by_sel:
                    row.pop("groupedBy", None)
                rows.append(row)
            out[class_field.out_name] = rows
        return out

    # -- Explore -------------------------------------------------------------

    def _exec_explore(self, root: Field) -> list[dict]:
        a = {k: _plain(v) for k, v in root.args.items()}
        hits = self.traverser.explorer.explore(
            near_vector=a.get("nearVector"),
            near_object=a.get("nearObject"),
            near_text=a.get("nearText"),
            limit=int(a.get("limit", 25) or 25),
        )
        wanted = [s.name for s in root.selections if isinstance(s, Field)]
        return [{k: h.get(k) for k in wanted} for h in hits]
