"""GraphQL introspection: a type system generated from the data schema.

Reference: adapters/handlers/graphql/ rebuilds the GraphQL schema from the
data schema on every schema change (makeUpdateSchemaCall); clients rely on
`__schema` / `__type` introspection for autocompletion and codegen. Here the
introspection document is generated on demand from the live SchemaManager —
always current, no rebuild bookkeeping.

Shape: Query { Get: GetObjectsObj, Aggregate: AggregateObjectsObj,
Explore: [ExploreObj] }, one object type per class with a field per property
(scalars mapped per entities/schema data types, cross-references as lists of
the target type) plus the _additional object.
"""

from __future__ import annotations

from typing import Optional

_SCALAR_MAP = {
    "text": "String",
    "string": "String",
    "int": "Int",
    "number": "Float",
    "boolean": "Boolean",
    "date": "String",
    "uuid": "String",
    "blob": "String",
    "phoneNumber": "String",
    "geoCoordinates": "GeoCoordinates",
}


def _t(name: str, kind: str = "OBJECT") -> dict:
    return {"kind": kind, "name": name, "ofType": None}


def _list_of(inner: dict) -> dict:
    return {"kind": "LIST", "name": None, "ofType": inner}


def _field(name: str, ftype: dict, description: str = "") -> dict:
    return {
        "name": name,
        "description": description,
        "args": [],
        "type": ftype,
        "isDeprecated": False,
        "deprecationReason": None,
    }


def _obj_type(name: str, fields: list[dict], description: str = "") -> dict:
    return {
        "kind": "OBJECT",
        "name": name,
        "description": description,
        "fields": fields,
        "inputFields": None,
        "interfaces": [],
        "enumValues": None,
        "possibleTypes": None,
    }


def _scalar(name: str) -> dict:
    return {
        "kind": "SCALAR",
        "name": name,
        "description": "",
        "fields": None,
        "inputFields": None,
        "interfaces": [],
        "enumValues": None,
        "possibleTypes": None,
    }


def _prop_type(prop) -> dict:
    dt = prop.data_type[0] if prop.data_type else "text"
    if dt.endswith("[]"):
        base = _SCALAR_MAP.get(dt[:-2], "String")
        return _list_of(_t(base, "SCALAR"))
    if dt in _SCALAR_MAP:
        base = _SCALAR_MAP[dt]
        return _t(base, "SCALAR" if base != "GeoCoordinates" else "OBJECT")
    # cross-reference: list of the target class type
    return _list_of(_t(dt, "OBJECT"))


def build_introspection(schema) -> dict:
    """-> the __schema payload for the current data schema."""
    classes = sorted(schema.get_schema().classes.values(), key=lambda c: c.name)

    additional_fields = [
        _field("id", _t("String", "SCALAR")),
        _field("vector", _list_of(_t("Float", "SCALAR"))),
        _field("certainty", _t("Float", "SCALAR")),
        _field("distance", _t("Float", "SCALAR")),
        _field("score", _t("Float", "SCALAR")),
        _field("explainScore", _t("String", "SCALAR")),
        _field("creationTimeUnix", _t("String", "SCALAR")),
        _field("lastUpdateTimeUnix", _t("String", "SCALAR")),
        # module-provided explanation props (class_builder_fields.go:590-620)
        _field("featureProjection", _t("FeatureProjection")),
        _field("nearestNeighbors", _t("NearestNeighbors")),
        _field("semanticPath", _t("SemanticPath")),
        _field("interpretation", _t("Interpretation")),
    ]

    types: list[dict] = [
        _scalar("String"), _scalar("Int"), _scalar("Float"), _scalar("Boolean"),
        _obj_type("GeoCoordinates", [
            _field("latitude", _t("Float", "SCALAR")),
            _field("longitude", _t("Float", "SCALAR")),
        ]),
        _obj_type("AdditionalProps", additional_fields,
                  "_additional result metadata"),
        _obj_type("FeatureProjection", [
            _field("vector", _list_of(_t("Float", "SCALAR"))),
        ]),
        _obj_type("NearestNeighbors", [
            _field("neighbors", _list_of(_t("NearestNeighbor"))),
        ]),
        _obj_type("NearestNeighbor", [
            _field("concept", _t("String", "SCALAR")),
            _field("distance", _t("Float", "SCALAR")),
            _field("vector", _list_of(_t("Float", "SCALAR"))),
        ]),
        _obj_type("SemanticPath", [
            _field("path", _list_of(_t("SemanticPathElement"))),
        ]),
        _obj_type("SemanticPathElement", [
            _field("concept", _t("String", "SCALAR")),
            _field("distanceToNext", _t("Float", "SCALAR")),
            _field("distanceToPrevious", _t("Float", "SCALAR")),
            _field("distanceToQuery", _t("Float", "SCALAR")),
            _field("distanceToResult", _t("Float", "SCALAR")),
        ]),
        _obj_type("Interpretation", [
            _field("source", _list_of(_t("InterpretationSource"))),
        ]),
        _obj_type("InterpretationSource", [
            _field("concept", _t("String", "SCALAR")),
            _field("occurrence", _t("Int", "SCALAR")),
            _field("weight", _t("Float", "SCALAR")),
        ]),
    ]

    get_fields, agg_fields = [], []
    for cd in classes:
        fields = [
            _field(p.name, _prop_type(p), p.description or "")
            for p in cd.properties
        ]
        fields.append(_field("_additional", _t("AdditionalProps")))
        types.append(_obj_type(cd.name, fields, cd.description or ""))
        get_fields.append(_field(cd.name, _list_of(_t(cd.name))))
        agg_fields.append(_field(cd.name, _list_of(_t(f"Aggregate{cd.name}Obj"))))
        types.append(_obj_type(
            f"Aggregate{cd.name}Obj",
            [_field("meta", _t("AggregateMetaObj")),
             _field("groupedBy", _t("AggregateGroupedByObj"))],
        ))

    types.append(_obj_type("AggregateMetaObj", [_field("count", _t("Int", "SCALAR"))]))
    types.append(_obj_type("AggregateGroupedByObj", [
        _field("path", _list_of(_t("String", "SCALAR"))),
        _field("value", _t("String", "SCALAR")),
    ]))
    types.append(_obj_type("ExploreObj", [
        _field("className", _t("String", "SCALAR")),
        _field("beacon", _t("String", "SCALAR")),
        _field("certainty", _t("Float", "SCALAR")),
        _field("distance", _t("Float", "SCALAR")),
    ]))
    types.append(_obj_type(
        "GetObjectsObj", get_fields or [_field("_empty", _t("String", "SCALAR"))]
    ))
    types.append(_obj_type(
        "AggregateObjectsObj", agg_fields or [_field("_empty", _t("String", "SCALAR"))]
    ))
    types.append(_obj_type("WeaviateQuery", [
        _field("Get", _t("GetObjectsObj"), "Get objects"),
        _field("Aggregate", _t("AggregateObjectsObj"), "Aggregate objects"),
        _field("Explore", _list_of(_t("ExploreObj")), "Cross-class vector search"),
    ]))

    return {
        "queryType": {"name": "WeaviateQuery"},
        "mutationType": None,
        "subscriptionType": None,
        "types": types,
        "directives": [],
    }


def find_type(schema, name: str) -> Optional[dict]:
    """__type(name:) resolution."""
    for t in build_introspection(schema)["types"]:
        if t["name"] == name:
            return t
    return None


def project_tree(node, selections) -> object:
    """Project an introspection data tree through the query's selection set
    (generic: the data is plain dicts/lists; unknown fields resolve null)."""
    from weaviate_tpu.graphql.parser import Field

    if node is None:
        return None
    if isinstance(node, list):
        return [project_tree(n, selections) for n in node]
    if not selections:
        return node
    if not isinstance(node, dict):
        return node
    out = {}
    for sel in selections:
        if not isinstance(sel, Field):
            continue
        out[sel.out_name] = project_tree(node.get(sel.name), sel.selections)
    return out
