from weaviate_tpu.config.config import (
    AuthConfig,
    AuthzConfig,
    Config,
    ConfigError,
    ControllerConfig,
    load_config,
)

__all__ = ["Config", "AuthConfig", "AuthzConfig", "ConfigError",
           "ControllerConfig", "load_config"]
