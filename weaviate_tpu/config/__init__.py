from weaviate_tpu.config.config import (
    AuthConfig,
    AuthzConfig,
    Config,
    ConfigError,
    ControllerConfig,
    IvfConfig,
    load_config,
)

__all__ = ["Config", "AuthConfig", "AuthzConfig", "ConfigError",
           "ControllerConfig", "IvfConfig", "load_config"]
