from weaviate_tpu.config.config import (
    AuthConfig,
    AuthzConfig,
    Config,
    ConfigError,
    load_config,
)

__all__ = ["Config", "AuthConfig", "AuthzConfig", "ConfigError", "load_config"]
