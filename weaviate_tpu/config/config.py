"""Environment-driven server configuration.

Reference: usecases/config/environment.go (env parsing) +
config_handler.go:73-99 (the Config struct) — the full env surface is listed
in SURVEY.md Appendix A. Same variable names, same defaults; TPU extensions
(device mesh shape, store dtype) are additive.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Mapping, Optional


class ConfigError(ValueError):
    pass


# The ONE table of PQ fast-scan candidate-depth buckets. Two consumers
# import it and may never drift apart (the fused-dispatch satellite):
#   - serving/controller.py's recall-guarded budget controller steps the
#     rescore_r cap DOWN this ladder (and snaps operator overrides to it);
#   - index/tpu.py's `_rescore_r` / codes-tier pool sizing treat the top
#     bucket as the static maximum and clamp against the controller cap.
# Because every cap value is a bucket and the index's own static choices
# are {max(4k, 32)} ∪ buckets, a controller cut can never mint a jit
# shape the static path wouldn't also compile.
RESCORE_R_BUCKETS = (32, 48, 64, 96, 128)

# The ONE table of IVF probe-count buckets (ROADMAP item 3). Same
# discipline as RESCORE_R_BUCKETS, same two consumers:
#   - serving/controller.py's recall-guarded budget controller steps the
#     ivf_top_p cap DOWN this ladder (the second recall-guarded knob);
#   - index/tpu.py snaps every effective probe count to a bucket (or to
#     nlist exactly when the request covers all partitions), so top_p —
#     a jit static argument — can only take bounded values and a
#     controller cut can never mint a jit shape the static path
#     wouldn't also compile.
# ~1.5x steps up to the 4096 auto-nlist ceiling: the budget controller's
# one-bucket-per-hold-period gradualism must hold for large layouts too
# (a ladder topping out at 128 would make the first cut on a 256-probe
# layout a 2.7x jump)
IVF_TOP_P_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128,
                     192, 256, 384, 512, 768, 1024, 1536, 2048, 3072, 4096)

# The ONE table of 4-bit funnel stage-C buckets (the pq.bits=4 three-stage
# re-ranking funnel's FIRST budget: how many 4-bit ADC scan survivors reach
# the 8-bit reconstruction rescore). Same discipline and the same two
# consumers as RESCORE_R_BUCKETS:
#   - serving/controller.py's recall-guarded budget controller steps the
#     funnel_c cap DOWN this ladder (the third recall-guarded knob);
#   - index/tpu.py's funnel planner snaps C to a bucket (clamped to the
#     candidate-set size), and the fused kernel keeps C/G whole groups, so
#     a controller cut can never mint a jit shape the static path wouldn't
#     also compile. Values are multiples of the group width G=16.
PQ4_FUNNEL_C_BUCKETS = (256, 384, 512, 768, 1024, 1536, 2048, 3072, 4096)

# The ONE table of 4-bit funnel stage-c buckets (the funnel's SECOND
# budget: how many 8-bit rescore survivors reach the final bf16/exact
# rescore). Mirrors RESCORE_R_BUCKETS — the two knobs are the same kind of
# recall-budget, one per funnel hand-off.
PQ4_FUNNEL_RESCORE_BUCKETS = (32, 48, 64, 96, 128, 192, 256)


def _bool(env: Mapping[str, str], key: str, default: bool = False) -> bool:
    v = env.get(key)
    if v is None:
        return default
    return v.strip().lower() in ("true", "enabled", "on", "1")


def _int(env: Mapping[str, str], key: str, default: int) -> int:
    v = env.get(key)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        raise ConfigError(f"invalid {key}: {v!r} (want int)") from None


def _float(env: Mapping[str, str], key: str, default: float) -> float:
    v = env.get(key)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        raise ConfigError(f"invalid {key}: {v!r} (want float)") from None


def _list(env: Mapping[str, str], key: str) -> list[str]:
    v = env.get(key, "")
    return [s.strip() for s in v.split(",") if s.strip()]


@dataclass
class AnonymousAccess:
    enabled: bool = True  # environment.go default: anonymous on unless auth set


@dataclass
class APIKeyAuth:
    enabled: bool = False
    allowed_keys: list[str] = field(default_factory=list)
    users: list[str] = field(default_factory=list)  # positional key->user map


@dataclass
class OIDCAuth:
    enabled: bool = False
    issuer: str = ""
    client_id: str = ""
    username_claim: str = "sub"
    groups_claim: str = ""
    skip_client_id_check: bool = False


@dataclass
class AuthConfig:
    anonymous: AnonymousAccess = field(default_factory=AnonymousAccess)
    apikey: APIKeyAuth = field(default_factory=APIKeyAuth)
    oidc: OIDCAuth = field(default_factory=OIDCAuth)

    def validate(self) -> None:
        if self.apikey.enabled:
            if not self.apikey.allowed_keys:
                raise ConfigError(
                    "AUTHENTICATION_APIKEY_ENABLED requires AUTHENTICATION_APIKEY_ALLOWED_KEYS")
            if not self.apikey.users:
                raise ConfigError(
                    "AUTHENTICATION_APIKEY_ENABLED requires AUTHENTICATION_APIKEY_USERS")
            if len(self.apikey.users) not in (1, len(self.apikey.allowed_keys)):
                raise ConfigError(
                    "AUTHENTICATION_APIKEY_USERS must have one user or one per key")


@dataclass
class AuthzConfig:
    admin_list_enabled: bool = False
    admin_users: list[str] = field(default_factory=list)
    readonly_users: list[str] = field(default_factory=list)


@dataclass
class ClusterConfig:
    hostname: str = ""
    gossip: bool = False  # UDP gossip membership (seed nodes set this too)
    gossip_bind_port: int = 7946
    data_bind_port: int = 7947
    join: list[str] = field(default_factory=list)
    ignore_schema_sync: bool = False


@dataclass
class PersistenceConfig:
    data_path: str = "./data"
    memtables_max_size_mb: int = 200
    memtables_min_active_seconds: int = 10
    memtables_max_active_seconds: int = 300
    flush_idle_memtables_after: int = 60


@dataclass
class MonitoringConfig:
    enabled: bool = False
    port: int = 2112
    group_classes: bool = False


@dataclass
class DiskUseConfig:
    warning_percentage: int = 80
    readonly_percentage: int = 90


@dataclass
class MemUseConfig:
    warning_percentage: int = 80
    readonly_percentage: int = 0  # 0 = disabled (environment.go default)


@dataclass
class CoalescerConfig:
    """Cross-request query coalescing (serving/coalescer.py). TPU extension:
    concurrent single-query kNN requests admission-queue per
    (shard, k, metric, filter-signature) lane and flush as one padded
    device dispatch on bucket-fill or deadline. Disabled => the serving
    path is byte-for-byte the direct dispatch (zero queue hops)."""

    enabled: bool = False
    window_ms: float = 1.5        # deadline flush window per lane
    max_batch: int = 256          # rows that force an immediate flush
    max_request_rows: int = 16    # wider requests bypass to the direct path
    # admission control (serving/robustness.py): the queue bound is
    # cost-aware — queued ROWS, not requests — and overflow sheds with
    # 429/RESOURCE_EXHAUSTED + Retry-After instead of silently stalling
    max_queued_rows: int = 4096
    # liveness bound on a queued request's wait for its coalesced result:
    # even with no deadline set, a wedged flush thread can only cost a
    # client this long before the request falls back to the direct path
    wait_timeout_s: float = 30.0
    # lanes in flight between async enqueue and finalize. With the
    # snapshot-isolated read path (PR 4) finalize no longer contends with
    # the next lane's enqueue on an index lock, but on a CPU backend two
    # in-flight scans still contend for host cores — depth 1 (the flusher's
    # stall IS the backpressure that fills lanes) remains the measured
    # default; a real TPU backend is the case for 2.
    pipeline_depth: int = 1


@dataclass
class TracingConfig:
    """End-to-end request tracing (monitoring/tracing.py). TPU extension:
    per-request span trees with device-time attribution across coalesced
    dispatches, a /debug/traces ring buffer, and a slow-query JSON log.
    Disabled => no tracer object anywhere on the serving path (the module
    global stays None; every tracing entry point is a one-comparison
    no-op)."""

    enabled: bool = False
    sample_rate: float = 1.0      # fraction of requests traced (0..1)
    ring_size: int = 256          # completed traces kept for /debug/traces
    slow_query_threshold_ms: float = 1000.0  # <=0 disables the slow log
    # rolling window of the perf-attribution plane (monitoring/perf.py):
    # /debug/perf summaries, duty cycle, and the roofline gauges aggregate
    # over this many trailing seconds. Rides TRACING_ENABLED.
    perf_window_s: float = 60.0


@dataclass
class RobustnessConfig:
    """Request-lifecycle robustness (serving/robustness.py). TPU extension:
    end-to-end deadlines, a device circuit breaker with a host fallback
    plane, and the fault-injection harness gate (testing/faults.py)."""

    # default per-request deadline when the caller sends none
    # (X-Request-Timeout-Ms / gRPC deadline override it). 0 = unbounded.
    query_timeout_ms: float = 0.0
    breaker_enabled: bool = True
    breaker_failure_threshold: int = 5   # consecutive device errors to trip
    breaker_reset_ms: float = 2000.0     # OPEN cooldown before half-open
    breaker_half_open_probes: int = 1    # concurrent probe dispatches
    # fault-injection spec (testing/faults.py from_spec); "" = harness off
    # (the module global stays None; every injection point is a
    # one-comparison no-op)
    fault_injection: str = ""
    fault_injection_seed: int = 0


@dataclass
class QualityConfig:
    """Online quality observability (monitoring/quality.py). TPU
    extension: a shadow recall auditor re-executes a sampled fraction of
    completed live searches against the exact host plane (snapshot-
    generation-pinned) and reports recall@k / rank-biased overlap /
    distance error into ``GET /debug/quality`` and bounded-label gauges.
    Disabled (sample rate 0, the default) => no auditor object anywhere
    on the serving path (the module global stays None; every capture
    point is a one-comparison no-op)."""

    # fraction of completed live searches shadow-audited (0..1); 0 = off
    audit_sample_rate: float = 0.0
    # background audit worker threads (hard concurrency budget); the
    # pending queue is bounded to the same number — overflow DROPS the
    # sample (counted), never queues behind live load
    audit_concurrency: int = 1
    # query rows audited per sampled dispatch (a wide coalesced batch
    # audits a uniform row subset)
    audit_max_rows: int = 64
    # per-audit budget for the host-plane scan; the scan streams row
    # chunks and abandons the audit when over (counted). <= 0 = unbounded
    audit_deadline_ms: float = 1000.0
    # rolling QualityWindow horizon for /debug/quality and the gauges
    window_s: float = 300.0
    # per-tier EWMA recall below this fires the degradation alert
    alert_threshold: float = 0.95
    # audited dispatches of a tier before its EWMA may alert (a cold
    # EWMA over two samples is noise, not a regression)
    alert_min_samples: int = 20


@dataclass
class MemoryLedgerConfig:
    """Memory & capacity observability (monitoring/memory.py). TPU
    extension: an always-on device/host/disk byte ledger stamped
    analytically at every index-snapshot publish (zero device syncs),
    write-path lifecycle instrumentation, and a time-to-exhaustion
    forecast with fire-once headroom alerts at ``GET /debug/memory``.
    Disabled => no ledger object anywhere on the write path (the module
    global stays None; every stamping entry point is a one-comparison
    no-op)."""

    ledger_enabled: bool = True
    # rolling window for write-phase percentiles / COW peaks / forecast
    window_s: float = 300.0
    # headroom percentage below which a scope fires its exhaustion alert
    headroom_alert_pct: float = 10.0
    # per-device HBM budget override; 0 = autodetect from the backend's
    # memory_stats()['bytes_limit'] (0 when the backend reports none)
    device_budget_bytes: int = 0
    # host RAM budget override; 0 = autodetect from /proc/meminfo MemTotal
    host_budget_bytes: int = 0


@dataclass
class IncidentsConfig:
    """Incident flight recorder + SLO burn-rate engine (monitoring/
    incidents.py). TPU extension: a bounded ops-event journal fed by
    every plane's state transitions, config-declared availability/
    latency SLOs evaluated into 5m/1h burn rates, and trigger-driven
    post-mortem bundles (perf/quality/memory/trace/journal state) dumped
    to ``INCIDENT_DIR``. Disabled => no journal/engine/recorder object
    anywhere on the serving path (the module globals stay None; every
    entry point is a one-comparison no-op)."""

    enabled: bool = True
    # ops-journal ring size (events retained for /debug/incidents and
    # bundle tails; burst kinds coalesce so a storm is one entry)
    journal_size: int = 512
    # bundle directory; "" = <data_path>/incidents
    dir: str = ""
    # disk budget for the bundle directory: oldest bundles pruned past
    # this (accounted in the memory ledger's disk scope). 0 = unbounded.
    dir_max_bytes: int = 64 * 1024 * 1024
    # min seconds between bundles of one incident class (teardown/manual
    # dumps are forced and exempt)
    rate_limit_s: float = 300.0
    # availability SLO: the fraction of serving requests that must not
    # shed/expire/error (bad fraction / (1-target) = burn rate)
    slo_availability_target: float = 0.999
    # latency SLO: p99 target in ms over completed requests; 0 disables
    # the latency objective (there is no universally right target)
    slo_latency_p99_ms: float = 0.0
    # burn-rate alert thresholds for the 5m (fast) and 1h (slow) windows
    # (14.4/3.0: the SRE-workbook pairing — a cliff vs a smolder)
    slo_fast_burn: float = 14.4
    slo_slow_burn: float = 3.0
    # requests a window must hold before its burn rate may alert (a cold
    # window over two requests is noise, not an incident)
    slo_min_events: int = 20
    # "tenantA=0.999,tenantB=0.99" — per-tenant availability overrides;
    # each adds ONE bounded SLO series (config-sized, never traffic-sized)
    slo_tenant_targets: dict = field(default_factory=dict)


@dataclass
class IvfConfig:
    """Partition-pruned search: the clustered IVF scan plane with a
    low-dim PCA prefilter (index/tpu.py + ops/ivf.py, ROADMAP item 3).
    TPU extension: a k-means partition layout trained on the write path
    (assignments ride the staged-generation snapshot handshake, stored
    as padded partition buckets so jit shapes stay cached across
    inserts); at query time a cheap centroid scan probes the top-P
    partitions and only their buckets are scored, making per-dispatch
    scan cost sublinear in N. Disabled (the default) => a true zero-hop
    no-op: no centroids/buckets/PCA slabs exist anywhere, the write path
    never trains, and every dispatch-path gate is one comparison."""

    enabled: bool = False     # IVF_ENABLED
    # partitions; 0 = auto: ~256 rows per partition, ceil-pow2-snapped,
    # clamped 16..4096 (the host k-means budget — index/tpu.py
    # _ivf_nlist; fill-targeted sizing measured 2-4x better than
    # sqrt(n) in both probe recall and probed_fraction)
    nlist: int = 0            # IVF_NLIST
    # partitions probed per query; 0 = auto (nlist/16, min 1). Snapped to
    # IVF_TOP_P_BUCKETS; the controller's recall-guarded budget may cut
    # it further down the same ladder, never raise it.
    top_p: int = 0            # IVF_TOP_P
    # rows before the first k-means training pass (an IVF layout over a
    # few thousand rows costs more in probe overhead than it prunes)
    min_n: int = 20000        # IVF_MIN_N
    # PCA prefilter subspace dims; 0 = prefilter off
    pca_dim: int = 0          # IVF_PCA_DIM
    # candidates surviving the PCA prefilter per query; 0 = auto
    # (max(8k, probed/8), pow2-snapped). Only meaningful with pca_dim>0.
    prefilter_c: int = 0      # IVF_PREFILTER_C
    # k-means training sample / iterations (bounded — training must stay
    # a write-path pause, not an offline job)
    train_sample: int = 65536  # IVF_TRAIN_SAMPLE
    train_iters: int = 6       # IVF_TRAIN_ITERS
    # recluster (full retrain) once n outgrows the trained layout by
    # this fraction; between retrains new rows are assigned to the
    # existing centroids incrementally
    retrain_growth: float = 0.5  # IVF_RETRAIN_GROWTH


@dataclass
class ControllerConfig:
    """Self-tuning degradation control plane (serving/controller.py).
    TPU extension: four clamped sense->decide->actuate->journal
    controllers on one supervised tick thread — burn-rate brownout
    (SLO burn -> a staged degradation ladder), a recall-guarded PQ
    candidate budget (the shadow auditor's recall EWMA -> the fast-scan
    ``rescore_r`` cap), coalescer window/pipeline-depth steering (the
    perf window's duty-cycle/queue-wait split), and per-tenant
    token-bucket rate quotas. Disabled (the default) => no plane object
    anywhere (the module global stays None; every knob reader on the
    serving path is a one-comparison no-op returning its configured
    default)."""

    enabled: bool = False           # CONTROL_PLANE_ENABLED
    # seconds between control ticks; knob leases expire at ~8 ticks, so
    # a stalled thread fail-statics in bounded time
    tick_s: float = 1.0
    # consecutive qualifying ticks before a held actuation applies (and
    # before the brownout ladder steps DOWN) — the hysteresis that keeps
    # a square-wave signal from flapping the knobs
    hold_ticks: int = 3
    # per-controller kill switches (the whole plane gates on `enabled`)
    brownout_enabled: bool = True   # CONTROLLER_BROWNOUT_ENABLED
    budget_enabled: bool = True    # CONTROLLER_BUDGET_ENABLED
    lanes_enabled: bool = True     # CONTROLLER_LANES_ENABLED
    # brownout: burn thresholds the ladder reacts to (defaults mirror
    # the SLO engine's alert pair) and the per-stage knob values
    fast_burn_threshold: float = 14.4
    slow_burn_threshold: float = 3.0
    brownout_margin: float = 2.0       # stage 1: admission-estimate x
    brownout_cap_scale: float = 0.5    # stage 2: tenant row cap x
    brownout_retry_scale: float = 2.0  # stage 2: Retry-After hints x
    brownout_rate_scale: float = 0.5   # stage 2: rate-quota refill x
    # recall-guarded budget: the EWMA floor the bench/acceptance pins,
    # the slack that must exist before a cut, the margin that forces an
    # immediate back-off, and the per-tier sample count before acting
    recall_floor: float = 0.98
    recall_slack: float = 0.015
    recall_backoff_margin: float = 0.005
    recall_min_samples: int = 8
    # lane steering: the clamp band for the coalescer flush window, the
    # pipeline-depth ceiling, and the duty-cycle hysteresis bands
    window_min_ms: float = 0.5
    window_max_ms: float = 6.0
    depth_max: int = 2
    duty_hi: float = 0.85
    duty_lo: float = 0.3
    # per-tenant token-bucket rate quotas: base QPS (x the tenant's DRR
    # weight); 0 = quota off. Enforced at coalescer admission while the
    # control plane is enabled, shedding `tenant_rate` with
    # Retry-After = time-to-next-token.
    tenant_rate_qps: float = 0.0   # TENANT_RATE_QPS
    tenant_rate_burst_s: float = 2.0  # TENANT_RATE_BURST_S


def _tenant_targets(env: Mapping[str, str], key: str) -> dict:
    """Parse "a=0.999,b=0.99" into {tenant: float target in (0,1)};
    reject malformed entries at startup, not at the first request."""
    out: dict = {}
    for item in _list(env, key):
        if "=" not in item:
            raise ConfigError(
                f"invalid {key} entry {item!r} (want tenant=target)")
        name, t = item.split("=", 1)
        name = name.strip()
        try:
            target = float(t)
        except ValueError:
            raise ConfigError(
                f"invalid {key} target for {name!r}: {t!r}") from None
        if not name or not (0.0 < target < 1.0):
            raise ConfigError(
                f"invalid {key} entry {item!r} (want nonempty tenant, "
                "target in (0, 1))")
        out[name] = target
    return out


@dataclass
class TenancyConfig:
    """Multi-tenant fairness (serving/coalescer.py weighted-fair
    admission + monitoring/metrics.py bounded tenant labels). TPU
    extension: tenant identity defaults to the queried class name and is
    overridable per request via REST ``X-Tenant-Id`` / gRPC
    ``x-tenant-id`` metadata."""

    # "tenantA=4,tenantB=2" — DRR weights; unlisted tenants weigh 1
    weights: dict = field(default_factory=dict)
    # the fraction of QUERY_COALESCER_MAX_QUEUED_ROWS one tenant may
    # occupy while OTHER tenants have rows waiting (alone it may use the
    # whole queue); overflow sheds that tenant with `tenant_budget`
    max_queued_rows_fraction: float = 0.5
    # per-tenant metric labels: the top-K tenants by traffic get their
    # own label value, the rest aggregate under "other" (bounded
    # prometheus cardinality no matter how many tenant ids exist)
    metrics_top_k: int = 10
    # front-door bound on ONE tenant's concurrent in-server requests
    # (explicit X-Tenant-Id traffic): excess sheds with 429/
    # RESOURCE_EXHAUSTED before any per-request work. 0 = disabled.
    max_concurrent_requests: int = 0


def _tenant_weights(env: Mapping[str, str], key: str) -> dict:
    """Parse "a=4,b=2" into {tenant: float}; reject non-positive or
    malformed entries at startup, not at the first admission."""
    out: dict = {}
    for item in _list(env, key):
        if "=" not in item:
            raise ConfigError(
                f"invalid {key} entry {item!r} (want tenant=weight)")
        name, w = item.split("=", 1)
        name = name.strip()
        try:
            weight = float(w)
        except ValueError:
            raise ConfigError(
                f"invalid {key} weight for {name!r}: {w!r}") from None
        if not name or weight <= 0:
            raise ConfigError(
                f"invalid {key} entry {item!r} (want nonempty tenant, "
                "weight > 0)")
        out[name] = weight
    return out


@dataclass
class AutoSchemaConfig:
    enabled: bool = True
    default_string: str = "text"
    default_number: str = "number"
    default_date: str = "date"


@dataclass
class Config:
    """config_handler.go:73-99 twin."""

    persistence: PersistenceConfig = field(default_factory=PersistenceConfig)
    auth: AuthConfig = field(default_factory=AuthConfig)
    authz: AuthzConfig = field(default_factory=AuthzConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    monitoring: MonitoringConfig = field(default_factory=MonitoringConfig)
    disk_use: DiskUseConfig = field(default_factory=DiskUseConfig)
    mem_use: MemUseConfig = field(default_factory=MemUseConfig)
    auto_schema: AutoSchemaConfig = field(default_factory=AutoSchemaConfig)

    origin: str = ""
    enable_modules: list[str] = field(default_factory=list)
    default_vectorizer_module: str = "none"
    default_vector_distance_metric: str = ""
    query_defaults_limit: int = 25
    query_maximum_results: int = 10000
    max_import_goroutines_factor: float = 1.5
    maximum_concurrent_get_requests: int = 0  # 0 = unlimited
    track_vector_dimensions: bool = False
    reindex_vector_dimensions_at_startup: bool = False
    index_missing_text_filterable_at_startup: bool = False
    grpc_port: int = 50051
    contextionary_url: str = ""
    backup_filesystem_path: str = ""

    # TPU extensions
    device_mesh_shards: int = 0  # 0 = one shard per local device
    store_dtype: str = "float32"
    # fully fused device dispatch (index/tpu.py): final top-k ->
    # tombstone/allowList masking -> slot->doc translation run in ONE XLA
    # program, so a search's single packed fetch carries final doc ids
    # and finalize() does zero host translation. Off = the legacy host
    # slot_to_doc path (the bench's --fused A/B lever)
    fused_dispatch_enabled: bool = True
    ivf: IvfConfig = field(default_factory=IvfConfig)
    coalescer: CoalescerConfig = field(default_factory=CoalescerConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    robustness: RobustnessConfig = field(default_factory=RobustnessConfig)
    tenancy: TenancyConfig = field(default_factory=TenancyConfig)
    quality: QualityConfig = field(default_factory=QualityConfig)
    memory: MemoryLedgerConfig = field(default_factory=MemoryLedgerConfig)
    incidents: IncidentsConfig = field(default_factory=IncidentsConfig)
    controller: ControllerConfig = field(default_factory=ControllerConfig)

    def validate(self) -> None:
        self.auth.validate()
        if self.query_defaults_limit < 1:
            raise ConfigError("QUERY_DEFAULTS_LIMIT must be >= 1")
        if self.query_maximum_results < 1:
            raise ConfigError("QUERY_MAXIMUM_RESULTS must be >= 1")
        if not (0 <= self.disk_use.warning_percentage <= 100):
            raise ConfigError("DISK_USE_WARNING_PERCENTAGE must be 0..100")
        if not (0 <= self.disk_use.readonly_percentage <= 100):
            raise ConfigError("DISK_USE_READONLY_PERCENTAGE must be 0..100")
        if self.store_dtype not in ("float32", "bfloat16"):
            raise ConfigError("STORE_DTYPE must be float32|bfloat16")
        ivf = self.ivf
        if ivf.nlist < 0:
            raise ConfigError("IVF_NLIST must be >= 0 (0 = auto)")
        if ivf.top_p < 0:
            raise ConfigError("IVF_TOP_P must be >= 0 (0 = auto)")
        if ivf.min_n < 1:
            raise ConfigError("IVF_MIN_N must be >= 1")
        if ivf.pca_dim < 0:
            raise ConfigError("IVF_PCA_DIM must be >= 0 (0 = prefilter off)")
        if ivf.prefilter_c < 0:
            raise ConfigError("IVF_PREFILTER_C must be >= 0 (0 = auto)")
        if ivf.train_sample < 256:
            raise ConfigError("IVF_TRAIN_SAMPLE must be >= 256")
        if ivf.train_iters < 1:
            raise ConfigError("IVF_TRAIN_ITERS must be >= 1")
        if ivf.retrain_growth <= 0:
            raise ConfigError("IVF_RETRAIN_GROWTH must be > 0")
        if self.coalescer.window_ms < 0:
            raise ConfigError("QUERY_COALESCER_WINDOW_MS must be >= 0")
        if self.coalescer.max_batch < 2:
            raise ConfigError("QUERY_COALESCER_MAX_BATCH must be >= 2")
        if not (1 <= self.coalescer.max_request_rows
                <= self.coalescer.max_batch):
            raise ConfigError(
                "QUERY_COALESCER_MAX_REQUEST_ROWS must be in "
                "[1, QUERY_COALESCER_MAX_BATCH]")
        if self.coalescer.pipeline_depth < 1:
            raise ConfigError("QUERY_COALESCER_PIPELINE_DEPTH must be >= 1")
        if self.coalescer.max_queued_rows < 1:
            raise ConfigError("QUERY_COALESCER_MAX_QUEUED_ROWS must be >= 1")
        if self.coalescer.wait_timeout_s <= 0:
            raise ConfigError("QUERY_COALESCER_WAIT_TIMEOUT_S must be > 0")
        if self.robustness.query_timeout_ms < 0:
            raise ConfigError("QUERY_TIMEOUT_MS must be >= 0")
        if self.robustness.breaker_failure_threshold < 1:
            raise ConfigError("BREAKER_FAILURE_THRESHOLD must be >= 1")
        if self.robustness.breaker_reset_ms < 0:
            raise ConfigError("BREAKER_RESET_TIMEOUT_MS must be >= 0")
        if self.robustness.breaker_half_open_probes < 1:
            raise ConfigError("BREAKER_HALF_OPEN_PROBES must be >= 1")
        if self.robustness.fault_injection:
            # fail at startup, not at the first injection-point firing
            from weaviate_tpu.testing import faults

            try:
                faults.from_spec(self.robustness.fault_injection)
            except ValueError as e:
                raise ConfigError(f"invalid FAULT_INJECTION: {e}") from None
        if not (0.0 <= self.tracing.sample_rate <= 1.0):
            raise ConfigError("TRACING_SAMPLE_RATE must be in [0, 1]")
        if self.tracing.ring_size < 1:
            raise ConfigError("TRACING_RING_SIZE must be >= 1")
        if self.tracing.perf_window_s <= 0:
            raise ConfigError("PERF_WINDOW_S must be > 0")
        if not (0.0 < self.tenancy.max_queued_rows_fraction <= 1.0):
            raise ConfigError(
                "TENANT_MAX_QUEUED_ROWS_FRACTION must be in (0, 1]")
        if self.tenancy.metrics_top_k < 1:
            raise ConfigError("TENANT_METRICS_TOP_K must be >= 1")
        if self.tenancy.max_concurrent_requests < 0:
            raise ConfigError(
                "TENANT_MAX_CONCURRENT_REQUESTS must be >= 0 (0 disables)")
        for t, w in self.tenancy.weights.items():
            if not t or w <= 0:
                raise ConfigError(
                    f"TENANT_WEIGHTS entry {t!r}={w!r} must have a "
                    "nonempty tenant and weight > 0")
        if not (0.0 <= self.quality.audit_sample_rate <= 1.0):
            raise ConfigError("RECALL_AUDIT_SAMPLE_RATE must be in [0, 1]")
        if self.quality.audit_concurrency < 1:
            raise ConfigError("RECALL_AUDIT_CONCURRENCY must be >= 1")
        if self.quality.audit_max_rows < 1:
            raise ConfigError("RECALL_AUDIT_MAX_ROWS must be >= 1")
        if self.quality.window_s <= 0:
            raise ConfigError("QUALITY_WINDOW_S must be > 0")
        if not (0.0 <= self.quality.alert_threshold <= 1.0):
            raise ConfigError("RECALL_ALERT_THRESHOLD must be in [0, 1]")
        if self.quality.alert_min_samples < 1:
            raise ConfigError("RECALL_ALERT_MIN_SAMPLES must be >= 1")
        if self.memory.window_s <= 0:
            raise ConfigError("MEMORY_LEDGER_WINDOW_S must be > 0")
        if not (0.0 <= self.memory.headroom_alert_pct <= 100.0):
            raise ConfigError("MEMORY_HEADROOM_ALERT_PCT must be 0..100")
        if self.memory.device_budget_bytes < 0:
            raise ConfigError("MEMORY_DEVICE_BUDGET_BYTES must be >= 0")
        if self.memory.host_budget_bytes < 0:
            raise ConfigError("MEMORY_HOST_BUDGET_BYTES must be >= 0")
        if self.incidents.journal_size < 1:
            raise ConfigError("INCIDENT_JOURNAL_SIZE must be >= 1")
        if self.incidents.dir_max_bytes < 0:
            raise ConfigError(
                "INCIDENT_DIR_MAX_BYTES must be >= 0 (0 = unbounded)")
        if self.incidents.rate_limit_s < 0:
            raise ConfigError("INCIDENT_RATE_LIMIT_S must be >= 0")
        if not (0.0 < self.incidents.slo_availability_target < 1.0):
            raise ConfigError("SLO_AVAILABILITY_TARGET must be in (0, 1)")
        if self.incidents.slo_latency_p99_ms < 0:
            raise ConfigError(
                "SLO_LATENCY_P99_MS must be >= 0 (0 disables)")
        if self.incidents.slo_fast_burn <= 0 \
                or self.incidents.slo_slow_burn <= 0:
            raise ConfigError(
                "SLO_FAST_BURN_THRESHOLD and SLO_SLOW_BURN_THRESHOLD "
                "must be > 0")
        if self.incidents.slo_min_events < 1:
            raise ConfigError("SLO_MIN_EVENTS must be >= 1")
        if len(self.incidents.slo_tenant_targets) > 64:
            raise ConfigError(
                "SLO_TENANT_AVAILABILITY_TARGETS: at most 64 per-tenant "
                "overrides (each mints a bounded metric series)")
        for t, tv in self.incidents.slo_tenant_targets.items():
            if not t or not (0.0 < tv < 1.0):
                raise ConfigError(
                    f"SLO_TENANT_AVAILABILITY_TARGETS entry {t!r}={tv!r} "
                    "must have a nonempty tenant and target in (0, 1)")
        ctl = self.controller
        if ctl.tick_s <= 0:
            raise ConfigError("CONTROLLER_TICK_S must be > 0")
        if ctl.hold_ticks < 1:
            raise ConfigError("CONTROLLER_HOLD_TICKS must be >= 1")
        if ctl.fast_burn_threshold <= 0 or ctl.slow_burn_threshold <= 0:
            raise ConfigError(
                "CONTROLLER_FAST_BURN and CONTROLLER_SLOW_BURN must be > 0")
        if ctl.brownout_margin < 1.0:
            raise ConfigError(
                "CONTROLLER_BROWNOUT_MARGIN must be >= 1 (1 = no "
                "tightening)")
        if not (0.0 < ctl.brownout_cap_scale <= 1.0) \
                or not (0.0 < ctl.brownout_rate_scale <= 1.0):
            raise ConfigError(
                "CONTROLLER_BROWNOUT_CAP_SCALE and "
                "CONTROLLER_BROWNOUT_RATE_SCALE must be in (0, 1]")
        if ctl.brownout_retry_scale < 1.0:
            raise ConfigError(
                "CONTROLLER_BROWNOUT_RETRY_SCALE must be >= 1")
        if not (0.0 < ctl.recall_floor < 1.0):
            raise ConfigError("CONTROLLER_RECALL_FLOOR must be in (0, 1)")
        if ctl.recall_slack <= 0 or ctl.recall_backoff_margin < 0:
            raise ConfigError(
                "CONTROLLER_RECALL_SLACK must be > 0 and "
                "CONTROLLER_RECALL_BACKOFF_MARGIN >= 0")
        if ctl.recall_min_samples < 1:
            raise ConfigError("CONTROLLER_RECALL_MIN_SAMPLES must be >= 1")
        if not (0.0 < ctl.window_min_ms <= ctl.window_max_ms):
            raise ConfigError(
                "CONTROLLER_WINDOW_MIN_MS must be in (0, "
                "CONTROLLER_WINDOW_MAX_MS]")
        if ctl.depth_max < 1:
            raise ConfigError("CONTROLLER_DEPTH_MAX must be >= 1")
        if not (0.0 < ctl.duty_lo < ctl.duty_hi <= 1.0):
            raise ConfigError(
                "CONTROLLER_DUTY_LO/HI must satisfy 0 < lo < hi <= 1")
        if ctl.tenant_rate_qps < 0:
            raise ConfigError("TENANT_RATE_QPS must be >= 0 (0 disables)")
        if ctl.tenant_rate_burst_s <= 0:
            raise ConfigError("TENANT_RATE_BURST_S must be > 0")


def ivf_from_env(env: Optional[Mapping[str, str]] = None) -> IvfConfig:
    """Parse the IVF knob surface. Shared by load_config AND the index
    layer's bare-library fallback (index/tpu.py ivf_settings) — one knob
    must never read differently with vs without an App (the
    FUSED_DISPATCH_ENABLED discipline)."""
    e = dict(os.environ) if env is None else env
    return IvfConfig(
        enabled=_bool(e, "IVF_ENABLED"),
        nlist=_int(e, "IVF_NLIST", 0),
        top_p=_int(e, "IVF_TOP_P", 0),
        min_n=_int(e, "IVF_MIN_N", 20000),
        pca_dim=_int(e, "IVF_PCA_DIM", 0),
        prefilter_c=_int(e, "IVF_PREFILTER_C", 0),
        train_sample=_int(e, "IVF_TRAIN_SAMPLE", 65536),
        train_iters=_int(e, "IVF_TRAIN_ITERS", 6),
        retrain_growth=_float(e, "IVF_RETRAIN_GROWTH", 0.5),
    )


def load_config(env: Optional[Mapping[str, str]] = None) -> Config:
    """LoadConfig twin (environment.go): parse the env surface, validate."""
    e = dict(os.environ) if env is None else dict(env)
    cfg = Config()

    cfg.persistence.data_path = e.get("PERSISTENCE_DATA_PATH", "./data")
    cfg.persistence.memtables_max_size_mb = _int(e, "PERSISTENCE_MEMTABLES_MAX_SIZE_MB", 200)
    cfg.persistence.memtables_min_active_seconds = _int(
        e, "PERSISTENCE_MEMTABLES_MIN_ACTIVE_DURATION_SECONDS", 10)
    cfg.persistence.memtables_max_active_seconds = _int(
        e, "PERSISTENCE_MEMTABLES_MAX_ACTIVE_DURATION_SECONDS", 300)
    cfg.persistence.flush_idle_memtables_after = _int(
        e, "PERSISTENCE_FLUSH_IDLE_MEMTABLES_AFTER", 60)

    apikey_enabled = _bool(e, "AUTHENTICATION_APIKEY_ENABLED")
    oidc_enabled = _bool(e, "AUTHENTICATION_OIDC_ENABLED")
    anon_default = not (apikey_enabled or oidc_enabled)
    cfg.auth.anonymous.enabled = _bool(
        e, "AUTHENTICATION_ANONYMOUS_ACCESS_ENABLED", anon_default)
    cfg.auth.apikey.enabled = apikey_enabled
    cfg.auth.apikey.allowed_keys = _list(e, "AUTHENTICATION_APIKEY_ALLOWED_KEYS")
    cfg.auth.apikey.users = _list(e, "AUTHENTICATION_APIKEY_USERS")
    cfg.auth.oidc.enabled = oidc_enabled
    cfg.auth.oidc.issuer = e.get("AUTHENTICATION_OIDC_ISSUER", "")
    cfg.auth.oidc.client_id = e.get("AUTHENTICATION_OIDC_CLIENT_ID", "")
    cfg.auth.oidc.username_claim = e.get("AUTHENTICATION_OIDC_USERNAME_CLAIM", "sub")
    cfg.auth.oidc.groups_claim = e.get("AUTHENTICATION_OIDC_GROUPS_CLAIM", "")
    cfg.auth.oidc.skip_client_id_check = _bool(e, "AUTHENTICATION_OIDC_SKIP_CLIENT_ID_CHECK")

    cfg.authz.admin_list_enabled = _bool(e, "AUTHORIZATION_ADMINLIST_ENABLED")
    cfg.authz.admin_users = _list(e, "AUTHORIZATION_ADMINLIST_USERS")
    cfg.authz.readonly_users = _list(e, "AUTHORIZATION_ADMINLIST_READONLY_USERS")

    cfg.cluster.hostname = e.get("CLUSTER_HOSTNAME", "")
    cfg.cluster.gossip = _bool(e, "CLUSTER_GOSSIP")
    cfg.cluster.gossip_bind_port = _int(e, "CLUSTER_GOSSIP_BIND_PORT", 7946)
    cfg.cluster.data_bind_port = _int(e, "CLUSTER_DATA_BIND_PORT", 7947)
    cfg.cluster.join = _list(e, "CLUSTER_JOIN")
    cfg.cluster.ignore_schema_sync = _bool(e, "CLUSTER_IGNORE_SCHEMA_SYNC")

    cfg.monitoring.enabled = _bool(e, "PROMETHEUS_MONITORING_ENABLED")
    cfg.monitoring.port = _int(e, "PROMETHEUS_MONITORING_PORT", 2112)
    cfg.monitoring.group_classes = _bool(e, "PROMETHEUS_MONITORING_GROUP_CLASSES")

    cfg.disk_use.warning_percentage = _int(e, "DISK_USE_WARNING_PERCENTAGE", 80)
    cfg.disk_use.readonly_percentage = _int(e, "DISK_USE_READONLY_PERCENTAGE", 90)
    cfg.mem_use.warning_percentage = _int(e, "MEMORY_WARNING_PERCENTAGE", 80)
    cfg.mem_use.readonly_percentage = _int(e, "MEMORY_READONLY_PERCENTAGE", 0)

    cfg.auto_schema.enabled = _bool(e, "AUTOSCHEMA_ENABLED", True)
    cfg.auto_schema.default_string = e.get("AUTOSCHEMA_DEFAULT_STRING", "text")
    cfg.auto_schema.default_number = e.get("AUTOSCHEMA_DEFAULT_NUMBER", "number")
    cfg.auto_schema.default_date = e.get("AUTOSCHEMA_DEFAULT_DATE", "date")

    cfg.origin = e.get("ORIGIN", "")
    cfg.enable_modules = _list(e, "ENABLE_MODULES")
    cfg.default_vectorizer_module = e.get("DEFAULT_VECTORIZER_MODULE", "none")
    cfg.default_vector_distance_metric = e.get("DEFAULT_VECTOR_DISTANCE_METRIC", "")
    cfg.query_defaults_limit = _int(e, "QUERY_DEFAULTS_LIMIT", 25)
    cfg.query_maximum_results = _int(e, "QUERY_MAXIMUM_RESULTS", 10000)
    cfg.max_import_goroutines_factor = _float(e, "MAX_IMPORT_GOROUTINES_FACTOR", 1.5)
    cfg.maximum_concurrent_get_requests = _int(e, "MAXIMUM_CONCURRENT_GET_REQUESTS", 0)
    cfg.track_vector_dimensions = _bool(e, "TRACK_VECTOR_DIMENSIONS")
    cfg.reindex_vector_dimensions_at_startup = _bool(
        e, "REINDEX_VECTOR_DIMENSIONS_AT_STARTUP")
    cfg.index_missing_text_filterable_at_startup = _bool(
        e, "INDEX_MISSING_TEXT_FILTERABLE_AT_STARTUP")
    cfg.grpc_port = _int(e, "GRPC_PORT", 50051)
    cfg.contextionary_url = e.get("CONTEXTIONARY_URL", "")
    cfg.backup_filesystem_path = e.get("BACKUP_FILESYSTEM_PATH", "")

    cfg.device_mesh_shards = _int(e, "TPU_DEVICE_MESH_SHARDS", 0)
    cfg.store_dtype = e.get("TPU_STORE_DTYPE", "float32")
    cfg.fused_dispatch_enabled = _bool(e, "FUSED_DISPATCH_ENABLED", True)

    cfg.ivf = ivf_from_env(e)

    cfg.coalescer.enabled = _bool(e, "QUERY_COALESCER_ENABLED")
    cfg.coalescer.window_ms = _float(e, "QUERY_COALESCER_WINDOW_MS", 1.5)
    cfg.coalescer.max_batch = _int(e, "QUERY_COALESCER_MAX_BATCH", 256)
    cfg.coalescer.max_request_rows = _int(
        e, "QUERY_COALESCER_MAX_REQUEST_ROWS", 16)
    cfg.coalescer.pipeline_depth = _int(
        e, "QUERY_COALESCER_PIPELINE_DEPTH", 1)
    cfg.coalescer.max_queued_rows = _int(
        e, "QUERY_COALESCER_MAX_QUEUED_ROWS", 4096)
    cfg.coalescer.wait_timeout_s = _float(
        e, "QUERY_COALESCER_WAIT_TIMEOUT_S", 30.0)

    cfg.robustness.query_timeout_ms = _float(e, "QUERY_TIMEOUT_MS", 0.0)
    cfg.robustness.breaker_enabled = _bool(e, "BREAKER_ENABLED", True)
    cfg.robustness.breaker_failure_threshold = _int(
        e, "BREAKER_FAILURE_THRESHOLD", 5)
    cfg.robustness.breaker_reset_ms = _float(
        e, "BREAKER_RESET_TIMEOUT_MS", 2000.0)
    cfg.robustness.breaker_half_open_probes = _int(
        e, "BREAKER_HALF_OPEN_PROBES", 1)
    cfg.robustness.fault_injection = e.get("FAULT_INJECTION", "")
    cfg.robustness.fault_injection_seed = _int(e, "FAULT_INJECTION_SEED", 0)

    cfg.tenancy.weights = _tenant_weights(e, "TENANT_WEIGHTS")
    cfg.tenancy.max_queued_rows_fraction = _float(
        e, "TENANT_MAX_QUEUED_ROWS_FRACTION", 0.5)
    cfg.tenancy.metrics_top_k = _int(e, "TENANT_METRICS_TOP_K", 10)
    cfg.tenancy.max_concurrent_requests = _int(
        e, "TENANT_MAX_CONCURRENT_REQUESTS", 0)

    cfg.quality.audit_sample_rate = _float(e, "RECALL_AUDIT_SAMPLE_RATE", 0.0)
    cfg.quality.audit_concurrency = _int(e, "RECALL_AUDIT_CONCURRENCY", 1)
    cfg.quality.audit_max_rows = _int(e, "RECALL_AUDIT_MAX_ROWS", 64)
    cfg.quality.audit_deadline_ms = _float(
        e, "RECALL_AUDIT_DEADLINE_MS", 1000.0)
    cfg.quality.window_s = _float(e, "QUALITY_WINDOW_S", 300.0)
    cfg.quality.alert_threshold = _float(e, "RECALL_ALERT_THRESHOLD", 0.95)
    cfg.quality.alert_min_samples = _int(e, "RECALL_ALERT_MIN_SAMPLES", 20)

    cfg.memory.ledger_enabled = _bool(e, "MEMORY_LEDGER_ENABLED", True)
    cfg.memory.window_s = _float(e, "MEMORY_LEDGER_WINDOW_S", 300.0)
    cfg.memory.headroom_alert_pct = _float(
        e, "MEMORY_HEADROOM_ALERT_PCT", 10.0)
    cfg.memory.device_budget_bytes = _int(
        e, "MEMORY_DEVICE_BUDGET_BYTES", 0)
    cfg.memory.host_budget_bytes = _int(e, "MEMORY_HOST_BUDGET_BYTES", 0)

    cfg.incidents.enabled = _bool(e, "INCIDENTS_ENABLED", True)
    cfg.incidents.journal_size = _int(e, "INCIDENT_JOURNAL_SIZE", 512)
    cfg.incidents.dir = e.get("INCIDENT_DIR", "")
    cfg.incidents.dir_max_bytes = _int(
        e, "INCIDENT_DIR_MAX_BYTES", 64 * 1024 * 1024)
    cfg.incidents.rate_limit_s = _float(e, "INCIDENT_RATE_LIMIT_S", 300.0)
    cfg.incidents.slo_availability_target = _float(
        e, "SLO_AVAILABILITY_TARGET", 0.999)
    cfg.incidents.slo_latency_p99_ms = _float(e, "SLO_LATENCY_P99_MS", 0.0)
    cfg.incidents.slo_fast_burn = _float(e, "SLO_FAST_BURN_THRESHOLD", 14.4)
    cfg.incidents.slo_slow_burn = _float(e, "SLO_SLOW_BURN_THRESHOLD", 3.0)
    cfg.incidents.slo_min_events = _int(e, "SLO_MIN_EVENTS", 20)
    cfg.incidents.slo_tenant_targets = _tenant_targets(
        e, "SLO_TENANT_AVAILABILITY_TARGETS")

    cfg.controller.enabled = _bool(e, "CONTROL_PLANE_ENABLED")
    cfg.controller.tick_s = _float(e, "CONTROLLER_TICK_S", 1.0)
    cfg.controller.hold_ticks = _int(e, "CONTROLLER_HOLD_TICKS", 3)
    cfg.controller.brownout_enabled = _bool(
        e, "CONTROLLER_BROWNOUT_ENABLED", True)
    cfg.controller.budget_enabled = _bool(
        e, "CONTROLLER_BUDGET_ENABLED", True)
    cfg.controller.lanes_enabled = _bool(
        e, "CONTROLLER_LANES_ENABLED", True)
    cfg.controller.fast_burn_threshold = _float(
        e, "CONTROLLER_FAST_BURN", 14.4)
    cfg.controller.slow_burn_threshold = _float(
        e, "CONTROLLER_SLOW_BURN", 3.0)
    cfg.controller.brownout_margin = _float(
        e, "CONTROLLER_BROWNOUT_MARGIN", 2.0)
    cfg.controller.brownout_cap_scale = _float(
        e, "CONTROLLER_BROWNOUT_CAP_SCALE", 0.5)
    cfg.controller.brownout_retry_scale = _float(
        e, "CONTROLLER_BROWNOUT_RETRY_SCALE", 2.0)
    cfg.controller.brownout_rate_scale = _float(
        e, "CONTROLLER_BROWNOUT_RATE_SCALE", 0.5)
    cfg.controller.recall_floor = _float(
        e, "CONTROLLER_RECALL_FLOOR", 0.98)
    cfg.controller.recall_slack = _float(
        e, "CONTROLLER_RECALL_SLACK", 0.015)
    cfg.controller.recall_backoff_margin = _float(
        e, "CONTROLLER_RECALL_BACKOFF_MARGIN", 0.005)
    cfg.controller.recall_min_samples = _int(
        e, "CONTROLLER_RECALL_MIN_SAMPLES", 8)
    cfg.controller.window_min_ms = _float(
        e, "CONTROLLER_WINDOW_MIN_MS", 0.5)
    cfg.controller.window_max_ms = _float(
        e, "CONTROLLER_WINDOW_MAX_MS", 6.0)
    cfg.controller.depth_max = _int(e, "CONTROLLER_DEPTH_MAX", 2)
    cfg.controller.duty_hi = _float(e, "CONTROLLER_DUTY_HI", 0.85)
    cfg.controller.duty_lo = _float(e, "CONTROLLER_DUTY_LO", 0.3)
    cfg.controller.tenant_rate_qps = _float(e, "TENANT_RATE_QPS", 0.0)
    cfg.controller.tenant_rate_burst_s = _float(
        e, "TENANT_RATE_BURST_S", 2.0)

    cfg.tracing.enabled = _bool(e, "TRACING_ENABLED")
    cfg.tracing.sample_rate = _float(e, "TRACING_SAMPLE_RATE", 1.0)
    cfg.tracing.ring_size = _int(e, "TRACING_RING_SIZE", 256)
    cfg.tracing.slow_query_threshold_ms = _float(
        e, "SLOW_QUERY_THRESHOLD_MS", 1000.0)
    cfg.tracing.perf_window_s = _float(e, "PERF_WINDOW_S", 60.0)

    cfg.validate()
    return cfg
