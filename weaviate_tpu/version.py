"""Version of the weaviate_tpu framework.

Mirrors the reference version surface (openapi-specs/schema.json:1637 —
"1.19.0-beta.1") with our own build identity.
"""

__version__ = "1.19.0-tpu.1"
