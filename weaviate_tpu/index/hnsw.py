"""The "hnsw" index type: native C++ graph engine behind the VectorIndex seam.

This is the CPU parity index mirroring the reference's Go HNSW
(adapters/repos/db/vector/hnsw/) — graph semantics live in native/hnsw.cpp;
this wrapper adds:
- dynamic ef (autoEfFromK, search.go:46: ef = k*factor clamped to [min,max])
- cosine = normalize-then-dot (cosine_dist.go, search.go:64)
- flat-search cutoff: allowLists smaller than flatSearchCutoff are brute
  forced over the allowList only (search.go:73-77 → flat_search.go)
- durability: snapshot (hnsw_save) + VectorLog delta replay — the analog of
  commit-log condensing (condensor.go): flush() persists a snapshot and
  truncates the delta log; restore = load snapshot, replay the delta.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Optional, Sequence

import numpy as np

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.interface import AllowList, VectorIndex
from weaviate_tpu.index.tpu import VectorLog

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libhnsw.so")
_SRC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "hnsw.cpp",
)

_lib = None
_lib_lock = threading.Lock()


def _load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH):
            if not os.path.exists(_SRC_PATH):
                raise ImportError(f"native hnsw source not found at {_SRC_PATH}")
            os.makedirs(_NATIVE_DIR, exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-march=native", "-std=c++17", "-fopenmp", "-shared", "-fPIC",
                 "-o", _SO_PATH, _SRC_PATH],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO_PATH)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.hnsw_new.restype = ctypes.c_void_p
        lib.hnsw_new.argtypes = [ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                                 ctypes.c_int32, ctypes.c_uint64]
        lib.hnsw_free.argtypes = [ctypes.c_void_p]
        lib.hnsw_add.argtypes = [ctypes.c_void_p, ctypes.c_uint64, f32p]
        lib.hnsw_add_batch.argtypes = [ctypes.c_void_p, ctypes.c_int64, u64p, f32p]
        lib.hnsw_delete.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.hnsw_delete.restype = ctypes.c_int32
        lib.hnsw_contains.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.hnsw_contains.restype = ctypes.c_int32
        lib.hnsw_size.argtypes = [ctypes.c_void_p]
        lib.hnsw_size.restype = ctypes.c_int64
        lib.hnsw_search.argtypes = [ctypes.c_void_p, f32p, ctypes.c_int32, ctypes.c_int32,
                                    u64p, ctypes.c_int64, u64p, f32p]
        lib.hnsw_search.restype = ctypes.c_int32
        lib.hnsw_search_batch.argtypes = [ctypes.c_void_p, f32p, ctypes.c_int32,
                                          ctypes.c_int32, ctypes.c_int32, u64p,
                                          ctypes.c_int64, u64p, f32p, i32p]
        lib.hnsw_flat_search.argtypes = [ctypes.c_void_p, f32p, ctypes.c_int32, u64p,
                                         ctypes.c_int64, u64p, f32p]
        lib.hnsw_flat_search.restype = ctypes.c_int32
        lib.hnsw_cleanup.argtypes = [ctypes.c_void_p]
        lib.hnsw_cleanup.restype = ctypes.c_int64
        lib.hnsw_node_count.argtypes = [ctypes.c_void_p]
        lib.hnsw_node_count.restype = ctypes.c_int64
        lib.hnsw_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.hnsw_save.restype = ctypes.c_int32
        lib.hnsw_load.argtypes = [ctypes.c_char_p]
        lib.hnsw_load.restype = ctypes.c_void_p
        _lib = lib
        return _lib


_METRIC_L2 = 0
_METRIC_DOT = 1


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u64p(a: Optional[np.ndarray]):
    if a is None or a.size == 0:
        return None
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


class HnswIndex(VectorIndex):
    def __init__(
        self,
        config: vi.HnswUserConfig,
        shard_path: str,
        shard_name: str = "",
        metrics=None,
        persist: bool = True,
        class_name: str = "",
    ):
        self.config = config
        self.metric = config.distance
        if self.metric in (vi.DISTANCE_MANHATTAN, vi.DISTANCE_HAMMING):
            raise vi.ConfigValidationError(
                f"hnsw native engine supports l2-squared/dot/cosine, not {self.metric}"
            )
        self.shard_path = shard_path
        self.shard_name = shard_name
        self.class_name = class_name  # before _restore (metric labels)
        self.metrics = metrics
        self._lib = _load_lib()
        self._lock = threading.RLock()
        self.dim: Optional[int] = None
        self._h = None
        self._cleanup_running = threading.Semaphore(1)  # one cycle at a time
        self._snapshot_path = os.path.join(shard_path, "hnsw.snapshot")
        self._log = VectorLog(os.path.join(shard_path, "hnsw.log")) if persist else None
        if persist:
            self._restore()

    # -- internals -----------------------------------------------------------

    def _native_metric(self) -> int:
        return _METRIC_L2 if self.metric == vi.DISTANCE_L2 else _METRIC_DOT

    def _ensure_handle(self, dim: int) -> None:
        if self._h is None:
            self.dim = dim
            self._h = self._lib.hnsw_new(
                dim,
                self._native_metric(),
                self.config.max_connections,
                self.config.ef_construction,
                0x5EED,
            )

    def _prep(self, v: np.ndarray) -> np.ndarray:
        v = np.ascontiguousarray(v, dtype=np.float32)
        if self.metric == vi.DISTANCE_COSINE:
            n = float(np.linalg.norm(v))
            if n > 0:
                v = v / n
        return v

    def _restore(self) -> None:
        if os.path.exists(self._snapshot_path):
            h = self._lib.hnsw_load(self._snapshot_path.encode())
            if h:
                self._h = h
                # dim is embedded in the snapshot; probe via a search no-op is
                # overkill — store alongside
                dim_file = self._snapshot_path + ".dim"
                if os.path.exists(dim_file):
                    self.dim = int(open(dim_file).read().strip())
        if self._log is not None:
            replay_stats: dict = {}
            for op, doc_id, vec in VectorLog.replay(self._log.path, stats=replay_stats):
                if op == "add":
                    v = np.asarray(vec, dtype=np.float32)  # already normalized at log time
                    self._ensure_handle(v.shape[0])
                    self._lib.hnsw_add(self._h, doc_id, _f32p(np.ascontiguousarray(v)))
                elif self._h is not None:
                    self._lib.hnsw_delete(self._h, doc_id)
            VectorLog.report_replay_stats(self._log.path, replay_stats)
            self.last_replay_stats = replay_stats

    def _ef(self, k: int) -> int:
        ef = self.config.ef
        if ef != -1:
            return max(ef, k)
        # autoEfFromK (search.go:46)
        ef = k * self.config.dynamic_ef_factor
        ef = min(max(ef, self.config.dynamic_ef_min), self.config.dynamic_ef_max)
        return max(ef, k)

    # -- VectorIndex ---------------------------------------------------------

    def add(self, doc_id: int, vector: np.ndarray) -> None:
        v = self._prep(vector)
        with self._lock:
            if self.dim is not None and v.shape[0] != self.dim:
                raise ValueError(f"dim mismatch: index has {self.dim}, got {v.shape[0]}")
            self._ensure_handle(v.shape[0])
            if self._log is not None:
                self._log.append_add(int(doc_id), v)
            self._lib.hnsw_add(self._h, int(doc_id), _f32p(v))
            self._maybe_cleanup()  # re-adds tombstone the old node

    def add_batch(self, doc_ids: Sequence[int], vectors: np.ndarray) -> None:
        vectors = np.ascontiguousarray(vectors, dtype=np.float32)
        if self.metric == vi.DISTANCE_COSINE:
            norms = np.linalg.norm(vectors, axis=1, keepdims=True)
            norms[norms == 0] = 1.0
            vectors = np.ascontiguousarray(vectors / norms)
        ids = np.ascontiguousarray(np.asarray(doc_ids, dtype=np.uint64))
        with self._lock:
            if self.dim is not None and vectors.shape[1] != self.dim:
                raise ValueError(f"dim mismatch: index has {self.dim}, got {vectors.shape[1]}")
            self._ensure_handle(int(vectors.shape[1]))
            if self._log is not None:
                self._log.append_add_batch(ids, vectors)
            t0 = time.perf_counter()
            self._lib.hnsw_add_batch(self._h, len(ids), _u64p(ids), _f32p(vectors))
            self._obs_index("add", "graph_insert", t0, ops=len(ids))
            self._maybe_cleanup()  # re-adds tombstone the old nodes

    # tombstone pressure that triggers CleanUpTombstonedNodes inline (the
    # reference runs it on a cyclemanager timer, delete.go:177 — here the
    # write path that crosses the threshold pays for the cycle). Counted
    # natively (physical nodes - live), so re-add tombstones and tombstones
    # replayed from the log all count.
    _CLEANUP_MIN_TOMBS = 1024

    def _maybe_cleanup(self) -> None:
        """Kick the cleanup cycle off-thread when tombstone pressure crosses
        the threshold: the triggering write returns immediately instead of
        eating the O(n) repair inline (the reference's cyclemanager role).
        Searches still serialize with the cycle on the index lock — the
        native engine is single-writer by design — but no single caller is
        singled out to pay for it."""
        phys = int(self._lib.hnsw_node_count(self._h))
        live = int(self._lib.hnsw_size(self._h))
        if phys - live < max(self._CLEANUP_MIN_TOMBS, live):
            return
        if self._cleanup_running.acquire(blocking=False):
            def run():
                try:
                    # through cleanup_tombstones so background cycles land
                    # in the same metrics as explicit ones
                    self.cleanup_tombstones()
                finally:
                    self._cleanup_running.release()

            threading.Thread(target=run, daemon=True, name="hnsw-cleanup").start()

    def delete(self, *doc_ids: int) -> None:
        with self._lock:
            if self._h is None:
                return
            t0 = time.perf_counter()
            for d in doc_ids:
                if self._log is not None:
                    self._log.append_delete(int(d))
                self._lib.hnsw_delete(self._h, int(d))
            self._obs_index("delete", "tombstone", t0, ops=len(doc_ids))
            self._set_tombstone_gauge()
            self._maybe_cleanup()

    def cleanup_tombstones(self) -> int:
        """Reassign neighbors of deleted nodes, move the entrypoint, and
        physically remove them (delete.go:177-422). -> nodes removed."""
        with self._lock:
            if self._h is None:
                return 0
            t0 = time.perf_counter()
            removed = int(self._lib.hnsw_cleanup(self._h))
            self._obs_index("cleanup", "tombstone_cycle", t0)
            m = self.metrics
            if m is not None:
                cls, shard = self._metric_labels()
                m.vector_index_tombstone_cleanups.labels(cls, shard).inc()
            self._set_tombstone_gauge()
            return removed

    def _set_tombstone_gauge(self) -> None:
        """Gauge tracks live tombstone pressure: updated when tombstones are
        CREATED (delete) and after cleanup removes them — not only
        post-cleanup, where it would always read ~0."""
        m = self.metrics
        if m is None:
            return
        cls, shard = self._metric_labels()
        m.vector_index_tombstones.labels(cls, shard).set(
            max(0, self.node_count_locked() - len(self)))

    def node_count_locked(self) -> int:
        return int(self._lib.hnsw_node_count(self._h)) if self._h else 0

    def compact(self) -> None:
        """Uniform compaction surface with the TPU index: cleanup +
        condense the delta log into a fresh snapshot."""
        self.cleanup_tombstones()
        self.flush()

    def node_count(self) -> int:
        """Physical node count incl. tombstones (test/metrics surface)."""
        with self._lock:
            return int(self._lib.hnsw_node_count(self._h)) if self._h else 0

    def contains(self, doc_id: int) -> bool:
        with self._lock:
            return bool(self._h and self._lib.hnsw_contains(self._h, int(doc_id)))

    def __len__(self) -> int:
        with self._lock:
            return int(self._lib.hnsw_size(self._h)) if self._h else 0

    def distancer_name(self) -> str:
        return self.metric

    def search_by_vector(
        self, vector: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        q = self._prep(vector)
        with self._lock:
            if self._h is None:
                return np.zeros(0, np.uint64), np.zeros(0, np.float32)
            out_ids = np.zeros(k, dtype=np.uint64)
            out_d = np.zeros(k, dtype=np.float32)
            if allow_list is not None:
                allow = np.ascontiguousarray(allow_list.to_array(), dtype=np.uint64)
                if allow.size < self.config.flat_search_cutoff:
                    n = self._lib.hnsw_flat_search(
                        self._h, _f32p(q), k, _u64p(allow), allow.size, _u64p(out_ids), _f32p(out_d)
                    )
                else:
                    n = self._lib.hnsw_search(
                        self._h, _f32p(q), k, self._ef(k), _u64p(allow), allow.size,
                        _u64p(out_ids), _f32p(out_d),
                    )
            else:
                n = self._lib.hnsw_search(
                    self._h, _f32p(q), k, self._ef(k), None, 0, _u64p(out_ids), _f32p(out_d)
                )
            return out_ids[:n], out_d[:n]

    def search_by_vectors(
        self, vectors: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        vectors = np.ascontiguousarray(vectors, dtype=np.float32)
        if self.metric == vi.DISTANCE_COSINE:
            norms = np.linalg.norm(vectors, axis=1, keepdims=True)
            norms[norms == 0] = 1.0
            vectors = np.ascontiguousarray(vectors / norms)
        b = vectors.shape[0]
        with self._lock:
            if self._h is None:
                return np.zeros((b, 0), np.uint64), np.zeros((b, 0), np.float32)
            if allow_list is not None and len(allow_list) < self.config.flat_search_cutoff:
                return super().search_by_vectors(vectors, k, allow_list)
            allow = None
            a_n = 0
            if allow_list is not None:
                allow = np.ascontiguousarray(allow_list.to_array(), dtype=np.uint64)
                a_n = allow.size
            out_ids = np.zeros((b, k), dtype=np.uint64)
            out_d = np.full((b, k), np.inf, dtype=np.float32)
            counts = np.zeros(b, dtype=np.int32)
            self._lib.hnsw_search_batch(
                self._h, _f32p(vectors), b, k, self._ef(k), _u64p(allow), a_n,
                _u64p(out_ids), _f32p(out_d),
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            # mask out unfilled tails
            for i in range(b):
                if counts[i] < k:
                    out_d[i, counts[i]:] = np.inf
                    out_ids[i, counts[i]:] = np.iinfo(np.uint64).max
            return out_ids, out_d

    def search_by_vector_distance(
        self,
        vector: np.ndarray,
        target_distance: float,
        max_limit: int,
        allow_list: Optional[AllowList] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Iteratively double the limit (search.go:90-157)."""
        limit = 64
        while True:
            ids, dists = self.search_by_vector(vector, min(limit, max_limit), allow_list)
            if len(ids) == 0:
                return ids, dists
            if (dists > target_distance).any() or limit >= max_limit or len(ids) >= len(self):
                keep = dists <= target_distance
                return ids[keep][:max_limit], dists[keep][:max_limit]
            limit *= 2

    def update_user_config(self, updated: vi.HnswUserConfig) -> None:
        with self._lock:
            vi.validate_config_update(self.config, updated)
            self.config = updated

    def flush(self) -> None:
        """Snapshot + truncate the delta log (commit-log condense analog)."""
        with self._lock:
            if self._h is None:
                return
            if self._log is not None:
                tmp = self._snapshot_path + ".tmp"
                if self._lib.hnsw_save(self._h, tmp.encode()):
                    os.replace(tmp, self._snapshot_path)
                    with open(self._snapshot_path + ".dim", "w") as f:
                        f.write(str(self.dim))
                    self._log.rewrite([])
                self._log.flush()

    def drop(self) -> None:
        with self._lock:
            if self._h is not None:
                self._lib.hnsw_free(self._h)
                self._h = None
            self.dim = None
            if self._log is not None:
                self._log.close()
                for p in (self._log.path, self._snapshot_path, self._snapshot_path + ".dim"):
                    try:
                        os.remove(p)
                    except FileNotFoundError:
                        pass
                self._log = None

    def shutdown(self) -> None:
        with self._lock:
            self.flush()
            if self._log is not None:
                self._log.close()
            if self._h is not None:
                self._lib.hnsw_free(self._h)
                self._h = None

    def list_files(self) -> list[str]:
        out = []
        if self._log is not None:
            out.append(self._log.path)
        if os.path.exists(self._snapshot_path):
            out.extend([self._snapshot_path, self._snapshot_path + ".dim"])
        return out
