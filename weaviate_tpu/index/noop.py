"""Null vector index for classes with skip=true (reference: vector/noop)."""

from __future__ import annotations


from weaviate_tpu.index.interface import VectorIndex


class NoopIndex(VectorIndex):
    def __init__(self, config=None):
        self.config = config

    def add(self, doc_id, vector):
        pass

    def delete(self, *doc_ids):
        pass

    def search_by_vector(self, vector, k, allow_list=None):
        raise ValueError(
            "class is configured with skip=true: vector search is not possible"
        )

    def search_by_vector_distance(self, vector, target_distance, max_limit, allow_list=None):
        raise ValueError(
            "class is configured with skip=true: vector search is not possible"
        )

    def update_user_config(self, updated):
        self.config = updated

    def flush(self):
        pass

    def drop(self):
        pass

    def shutdown(self):
        pass
