"""The mesh-sharded TPU vector index ("hnsw_tpu_mesh").

The multi-chip twin of index/tpu.py: one logical shard's vectors are spread
over every chip of a jax.sharding.Mesh as per-chip HBM slabs, and every
operation is a whole-mesh SPMD program (kernels in
weaviate_tpu/parallel/mesh_search.py):

- insert: staged host-side, flushed as ONE sharded [n_dev, C, D] write —
  each chip lands its own chunk at its own offset (no per-shard dispatch
  loop);
- search: chunked masked scan per slab + local top-k, cross-chip merge over
  ICI (all_gather + reselect) inside the same jit;
- delete: tombstone scatter where each chip claims the global rows in its
  slab;
- filters: the allowList becomes a packed uint32 bitmap sharded over the
  mesh, ANDed into the validity mask on device (helpers/allow_list.go
  semantics; no host-side row gathering);
- growth: geometric slab doubling fully on device (maintainance.go:31).

Durability reuses the single-chip index's VectorLog (add/delete records,
torn-tail-tolerant replay) — the log format is placement-independent, so a
shard can restart onto a different mesh size and the replay re-balances.

This replaces the reference's scatter-gather over goroutines+HTTP
(adapters/repos/db/index.go:967-1046) for the intra-node multi-chip case:
the collective rides ICI instead of the network.

PQ (compress.go parity, mesh-shaped): codes and ||recon||^2 shard like the
store; each chip runs the reconstruction-matmul scan over its own code
slab, rescores its local candidates against its local row slab at exact
f32, and the k best per chip merge over ICI. Compression downcasts an f32
store to bf16 (the memory move the single-chip index makes by dropping its
float cache); post-compress appends encode on write.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.interface import AllowList, VectorIndex
from weaviate_tpu.index.tpu import VectorLog, _bucket_b, _bucket_rows
# memory ledger (monitoring/memory.py): per-device slab components are
# stamped analytically at every buffer mutation; unconfigured => one
# comparison, nothing constructed
from weaviate_tpu.monitoring import memory
from weaviate_tpu.testing import sanitizers
from weaviate_tpu.monitoring.metrics import record_device_fallback
from weaviate_tpu.parallel.mesh_search import (
    _MESH_SCAN_CHUNK,
    make_mesh,
    mesh_delete_step,
    mesh_grow_1d,
    mesh_grow_2d,
    mesh_insert_step,
    mesh_search_pq_step,
    mesh_search_step,
    mesh_write_rows_step,
    shard_spec,
)

_MIN_LOC = 1024       # minimum slab rows per chip (power of two, mult of 32)
_FLUSH_CHUNK = 8192   # staged rows that trigger a flush
_MAX_WRITE_C = 8192   # max rows per chip per insert step


def _pow2_at_least(n: int, floor: int) -> int:
    c = floor
    while c < n:
        c *= 2
    return c


@jax.jit
def _downcast_bf16(store):
    """One cached compilation for the compress-time store downcast; the
    output keeps the input's mesh sharding."""
    return store.astype(jnp.bfloat16)


class MeshVectorIndex(VectorIndex):
    def __init__(
        self,
        config: vi.HnswUserConfig,
        shard_path: str,
        shard_name: str = "",
        metrics=None,
        mesh=None,
        persist: bool = True,
        initial_capacity_per_shard: Optional[int] = None,
        dim_hint: Optional[int] = None,
        class_name: str = "",
    ):
        self.config = config
        self.metric = config.distance
        self.shard_path = shard_path
        self.shard_name = shard_name
        self.class_name = class_name
        self.metrics = metrics
        self.mesh = mesh if mesh is not None else make_mesh(
            getattr(config, "mesh_devices", 0) or None
        )
        self.n_dev = self.mesh.devices.size
        self.dtype = (
            jnp.bfloat16
            if getattr(config, "store_dtype", "float32") == "bfloat16"
            else jnp.float32
        )
        self._lock = sanitizers.register_lock(
            threading.RLock(), "index.mesh")
        self._init_loc = _pow2_at_least(
            initial_capacity_per_shard or _MIN_LOC, 32
        )
        self.dim: Optional[int] = None
        self.n_loc = 0               # slab rows per chip
        self.live = 0
        self._store = None           # sharded [n_dev * n_loc, D]
        self._sq_norms = None        # sharded [n_dev * n_loc] f32
        self._tombs = None           # sharded [n_dev * n_loc] bool
        self._zero_words = None      # sharded [n_dev * n_loc / 32] u32 (no-filter)
        self._counts = np.zeros(self.n_dev, dtype=np.int64)
        self._slot_to_doc = np.zeros(0, dtype=np.int64)  # global row -> doc
        self._doc_to_row: dict[int, int] = {}
        self._pending: dict[int, np.ndarray] = {}
        self._pending_tombs: list[int] = []
        # PQ state (mesh twin of index/tpu.py compression): codes and
        # ||recon||^2 are sharded like the store; the (possibly bf16)
        # store itself stays resident as the per-chip rescore source
        self.compressed = False
        self._pq = None
        self._codes = None          # sharded [n_dev * n_loc, M]
        self._recon_norms = None    # sharded [n_dev * n_loc] f32
        self._host_vecs = None      # np [cap, D] f32 (compressed mode only)
        self._pq_path = os.path.join(shard_path, "pq.npz") if shard_path else ""
        self._restoring = False
        self._gmin_broken = False  # fused mesh kernel failed: use the scan
        # identity token for the per-allowList packed-words cache
        self._allow_token = object()
        # separate failure domain + codebook cache for the PQ codes kernel
        from weaviate_tpu.ops.gmin_scan import KernelState

        self._pqg_state = KernelState()
        self._pqg_cb = None
        self._gmin_validated: set = set()     # shapes that served correctly
        self._gmin_shape_broken: set = set()  # shapes Mosaic rejected
        # host-memory provider (monitoring/memory.py): slot map, PQ host
        # rows, and staged rows become /debug/memory host components
        memory.register_host_provider(self, memory.index_host_components)
        self._log = (
            VectorLog(os.path.join(shard_path, "vector.log")) if persist else None
        )
        if dim_hint is not None:
            self._init_device(int(dim_hint))
        if self._log is not None:
            self._restore()

    # -- lifecycle -----------------------------------------------------------

    def _restore(self) -> None:
        """Replay the vector log (startup.go:56 analog). Placement is
        recomputed at replay time, so the same log restores onto any mesh."""
        self._restoring = True
        try:
            replay_stats: dict = {}
            for op, ids, vecs in VectorLog.replay_batches(self._log.path, stats=replay_stats):
                if op == "add":
                    self._bulk_stage_add(ids, vecs)
                else:
                    self._stage_delete(int(ids), log=False)
            VectorLog.report_replay_stats(self._log.path, replay_stats)
            self.last_replay_stats = replay_stats
            if self._pq_path and os.path.exists(self._pq_path):
                from weaviate_tpu.compress.pq import ProductQuantizer

                self._flush_pending()
                if self.live > 0:
                    self._enable_pq(
                        ProductQuantizer.load(self._pq_path),
                        np.asarray(self._store, dtype=np.float32),
                        save=False,
                    )
        finally:
            self._restoring = False

    def post_startup(self) -> None:
        self._flush_pending()

    # -- memory ledger stamping (monitoring/memory.py) -----------------------

    def _memory_components(self) -> dict:
        """Analytic byte sizes of the mesh slab buffers (global totals of
        the sharded arrays; the ledger divides by ``ndev`` for per-chip
        headroom). Zero syncs; equals the arrays' ``nbytes`` exactly."""
        comps: dict = {}
        for name, arr in (("store", self._store),
                          ("sq_norms", self._sq_norms),
                          ("tombs", self._tombs),
                          ("pq_codes", self._codes),
                          ("recon_norms", self._recon_norms),
                          ("allow_words", self._zero_words)):
            b = memory.array_bytes(arr)
            if b:
                comps[name] = b
        return comps

    def _stamp_memory(self) -> None:
        """The JGL012-registered stamping hook: every method that binds a
        device buffer to a slab field flows through here."""
        led = memory.get_ledger()
        if led is not None:
            led.stamp_device(self, self._memory_components(),
                             ndev=self.n_dev)

    # -- device plumbing -----------------------------------------------------

    def _init_device(self, dim: int) -> None:
        self.dim = dim
        self.n_loc = self._init_loc
        cap = self.n_dev * self.n_loc
        sh2 = shard_spec(self.mesh, None)
        sh1 = shard_spec(self.mesh)
        self._store = jax.device_put(jnp.zeros((cap, dim), self.dtype), sh2)
        self._sq_norms = jax.device_put(jnp.zeros((cap,), jnp.float32), sh1)
        self._tombs = jax.device_put(jnp.zeros((cap,), jnp.bool_), sh1)
        self._zero_words = jax.device_put(jnp.zeros((cap // 32,), jnp.uint32), sh1)
        self._slot_to_doc = np.full(cap, -1, dtype=np.int64)
        if self.compressed and self._pq is not None:
            # a device reset in compressed mode (compact) re-creates the
            # code slabs too; _write_balanced re-encodes rows as they land
            self._codes = jax.device_put(
                jnp.zeros((cap, self._pq.segments), self._pq.code_dtype), sh2)
            self._recon_norms = jax.device_put(jnp.zeros((cap,), jnp.float32), sh1)
            self._host_vecs = np.zeros((cap, dim), np.float32)
        self._stamp_memory()

    def _grow(self, needed_per_shard: int) -> None:
        new_loc = self.n_loc
        while new_loc < needed_per_shard:
            new_loc *= 2
        if new_loc == self.n_loc:
            return
        old_loc = self.n_loc
        self._store = mesh_grow_2d(self._store, new_loc, self.mesh)
        self._sq_norms = mesh_grow_1d(self._sq_norms, new_loc, self.mesh)
        self._tombs = mesh_grow_1d(self._tombs, new_loc, self.mesh)
        if self.compressed:
            self._codes = mesh_grow_2d(self._codes, new_loc, self.mesh)
            self._recon_norms = mesh_grow_1d(self._recon_norms, new_loc, self.mesh)
            hv = np.zeros((self.n_dev * new_loc, self.dim), np.float32)
            for s in range(self.n_dev):
                hv[s * new_loc : s * new_loc + old_loc] = self._host_vecs[
                    s * old_loc : (s + 1) * old_loc
                ]
            self._host_vecs = hv
        cap = self.n_dev * new_loc
        self._zero_words = jax.device_put(
            jnp.zeros((cap // 32,), jnp.uint32), shard_spec(self.mesh)
        )
        # remap global rows: slab-local offsets are preserved
        s2d = np.full(cap, -1, dtype=np.int64)
        for s in range(self.n_dev):
            c = int(self._counts[s])
            s2d[s * new_loc : s * new_loc + c] = self._slot_to_doc[
                s * old_loc : s * old_loc + c
            ]
        self._slot_to_doc = s2d
        rows = np.nonzero(s2d >= 0)[0]
        self._doc_to_row = dict(zip(s2d[rows].tolist(), rows.tolist()))
        # staged-but-unflushed tombstone rows move with their slab
        self._pending_tombs = [
            (r // old_loc) * new_loc + (r % old_loc) for r in self._pending_tombs
        ]
        self.n_loc = new_loc
        led = memory.get_ledger()
        if led is not None:
            led.note_write_shape(
                ("mesh_grow", self.n_dev, new_loc, self.dim or 0,
                 self.compressed))
        self._stamp_memory()

    # -- staging -------------------------------------------------------------

    def _stage_add(self, doc_id: int, vector: np.ndarray, log: bool = True) -> None:
        vector = np.asarray(vector, dtype=np.float32)
        if self.metric == vi.DISTANCE_COSINE:
            nrm = float(np.linalg.norm(vector))
            if nrm > 0:
                vector = vector / nrm
        if self.dim is None:
            self._init_device(int(vector.shape[0]))
        elif vector.shape[0] != self.dim:
            raise ValueError(f"dim mismatch: index has {self.dim}, got {vector.shape[0]}")
        old = self._doc_to_row.pop(doc_id, None)
        if old is not None:
            self._pending_tombs.append(old)
            self._slot_to_doc[old] = -1  # dead row must not resurrect via _grow
            self.live -= 1
        if doc_id in self._pending:
            self.live -= 1
        self._pending[doc_id] = vector
        self.live += 1
        if log and self._log is not None:
            self._log.append_add(doc_id, vector)
        if len(self._pending) >= _FLUSH_CHUNK:
            self._flush_pending()

    def _bulk_stage_add(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        """Restore-path bulk staging (single-chip twin in tpu.py): a run of
        add records feeds the staging buffer in one dict update with
        _stage_add's exact semantics; small/fragmented runs and docs the
        index already knows take the per-record path."""
        if len(ids) < 256:
            for d, v in zip(ids.tolist(), vecs):
                self._stage_add(int(d), v, log=False)
            return
        if self.dim is None:
            self._init_device(int(np.asarray(vecs).shape[1]))
        elif np.asarray(vecs).shape[1] != self.dim:
            raise ValueError(
                f"dim mismatch: index has {self.dim}, got {np.asarray(vecs).shape[1]}")
        from weaviate_tpu.index.tpu import _prep_bulk_run

        d2r = self._doc_to_row
        ids64, vecs, known = _prep_bulk_run(
            ids, vecs, self.metric,
            lambda d: d in d2r or d in self._pending)
        if known:
            for i in known:
                self._stage_add(int(ids64[i]), vecs[i], log=False)
            keep = np.ones(len(ids64), bool)
            keep[known] = False
            ids64, vecs = ids64[keep], vecs[keep]
            if len(ids64) == 0:
                return
        self._pending.update(zip(ids64.tolist(), vecs))
        self.live += len(ids64)
        if len(self._pending) >= _FLUSH_CHUNK:
            self._flush_pending()

    def _stage_delete(self, doc_id: int, log: bool = True) -> None:
        row = self._doc_to_row.pop(doc_id, None)
        if row is None:
            if doc_id in self._pending:
                del self._pending[doc_id]
                self.live -= 1
                if log and self._log is not None:
                    self._log.append_delete(doc_id)
            return
        self._pending_tombs.append(row)
        self._slot_to_doc[row] = -1  # dead row must not resurrect via _grow
        self.live -= 1
        if log and self._log is not None:
            self._log.append_delete(doc_id)

    def _assign_balanced(self, count: int) -> list[np.ndarray]:
        """Split `count` new rows over shards so slab fills equalize
        (the chip-level analog of the virtual-shard ring's even spread,
        usecases/sharding/state.go:261)."""
        counts = self._counts.copy()
        takes = np.zeros(self.n_dev, dtype=np.int64)
        remaining = count
        # level-fill: repeatedly top up the emptiest shards
        while remaining > 0:
            order = np.argsort(counts + takes)
            lo = order[0]
            if self.n_dev > 1:
                second = counts[order[1]] + takes[order[1]]
                gap = int(second - (counts[lo] + takes[lo]))
                step = max(1, min(remaining, gap if gap > 0 else remaining // self.n_dev + 1))
            else:
                step = remaining
            takes[lo] += step
            remaining -= step
        out, off = [], 0
        for s in range(self.n_dev):
            out.append(np.arange(off, off + int(takes[s])))
            off += int(takes[s])
        return out

    def _flush_pending(self) -> None:
        led = memory.get_ledger()
        if self._pending:
            t0 = time.perf_counter()
            rows = np.stack(list(self._pending.values()))
            docs = np.array(list(self._pending.keys()), dtype=np.int64)
            self._write_balanced(docs, rows)
            self._pending.clear()
            if led is not None:
                led.note_write(
                    "add", "flush", (time.perf_counter() - t0) * 1000.0,
                    rows=rows.shape[0],
                    bytes_moved=rows.shape[0] * (self.dim or 0) * 4)
        if self._pending_tombs:
            t0 = time.perf_counter()
            idx = np.array(self._pending_tombs, dtype=np.int32)
            pad = _bucket_rows(len(idx))
            padded = np.full(pad, -1, dtype=np.int32)
            padded[: len(idx)] = idx
            self._tombs = mesh_delete_step(self._tombs, jnp.asarray(padded), self.mesh)
            if led is not None:
                led.note_write(
                    "delete", "apply_tombstones",
                    (time.perf_counter() - t0) * 1000.0,
                    rows=len(self._pending_tombs))
            self._pending_tombs.clear()
            self._stamp_memory()
        # declarative pq.enabled compresses once enough data exists to fit
        # codebooks (same trigger as the single-chip index)
        if (
            self.config.pq.enabled
            and not self.compressed
            and not self._restoring
            and self.live >= max(256, self.config.pq.centroids)
        ):
            try:
                self._compress_locked()
            except vi.ConfigValidationError as e:
                # a pq config that only turns out invalid once dims are
                # known (declared before the first import) must not turn
                # every later add/search into an error: auto-disable with a
                # warning and keep serving uncompressed
                import logging

                self.config.pq.enabled = False
                logging.getLogger(__name__).warning(
                    "declared pq config is invalid (%s); auto-disabling "
                    "compression for this index", e)

    def _write_balanced(self, docs: np.ndarray, rows: np.ndarray) -> None:
        """Land [count, D] rows across slabs in whole-mesh insert steps."""
        assign = self._assign_balanced(rows.shape[0])
        needed = max(
            int(self._counts[s]) + len(assign[s]) for s in range(self.n_dev)
        )
        self._grow(needed)
        queues = [list(a) for a in assign]
        while any(queues):
            max_rem = max(len(q) for q in queues)
            max_off = max(
                int(self._counts[s]) for s in range(self.n_dev) if queues[s]
            )
            c = min(_bucket_rows(max_rem), _MAX_WRITE_C, self.n_loc - max_off)
            c = max(c, 1)
            chunks = np.zeros((self.n_dev, c, self.dim), np.float32)
            offsets = self._counts.astype(np.int32)
            takes = np.zeros(self.n_dev, dtype=np.int32)
            taken: list[np.ndarray] = []
            for s in range(self.n_dev):
                take = min(c, len(queues[s]))
                sel = np.array(queues[s][:take], dtype=np.int64)
                queues[s] = queues[s][take:]
                if take:
                    chunks[s, :take] = rows[sel]
                takes[s] = take
                taken.append(sel)
            chunks_dev = jax.device_put(
                jnp.asarray(chunks), shard_spec(self.mesh, None, None)
            )
            self._store, self._sq_norms = mesh_insert_step(
                self._store,
                self._sq_norms,
                chunks_dev,
                jnp.asarray(offsets),
                jnp.asarray(takes),
                self.metric == vi.DISTANCE_L2,
                self.mesh,
            )
            if self.compressed:
                # post-compress appends also land codes + recon norms (the
                # single-chip index's encode-on-write parity)
                code_chunks = self._pq.encode(
                    chunks.reshape(-1, self.dim)
                ).reshape(self.n_dev, c, self._pq.segments)
                norm_chunks = self._pq.recon_sq_norms(
                    code_chunks.reshape(-1, self._pq.segments)
                ).reshape(self.n_dev, c).astype(np.float32)
                self._codes, self._recon_norms = mesh_write_rows_step(
                    self._codes,
                    self._recon_norms,
                    jax.device_put(jnp.asarray(code_chunks),
                                   shard_spec(self.mesh, None, None)),
                    jax.device_put(jnp.asarray(norm_chunks),
                                   shard_spec(self.mesh, None)),
                    jnp.asarray(offsets),
                    jnp.asarray(takes),
                    self.mesh,
                )
            for s in range(self.n_dev):
                take = len(taken[s])
                if not take:
                    continue
                base = s * self.n_loc + int(self._counts[s])
                grows = np.arange(base, base + take)
                d = docs[taken[s]]
                self._slot_to_doc[grows] = d
                self._doc_to_row.update(zip(d.tolist(), grows.tolist()))
                if self.compressed:
                    self._host_vecs[grows] = rows[taken[s]]
                self._counts[s] += take
        self._stamp_memory()

    # -- product quantization (mesh twin of index/tpu.py compression) --------

    def compress(self) -> None:
        with self._lock:
            self._flush_pending()
            self._compress_locked()

    def _compress_locked(self) -> None:
        from weaviate_tpu.compress.pq import ProductQuantizer

        if self.compressed:
            return
        if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
            # the mesh PQ kernel is the reconstruction matmul; the LUT path
            # the single-chip index keeps for manhattan/hamming has no mesh
            # twin, and silently-wrong distances are worse than an error
            raise vi.ConfigValidationError(
                f"pq on hnsw_tpu_mesh supports l2-squared/dot/cosine, "
                f"not {self.metric}")
        if self.live == 0:
            raise RuntimeError("compress requires imported vectors to fit on")
        host = np.asarray(self._store, dtype=np.float32)  # [cap, D] gather
        occupied = self._slot_to_doc >= 0
        pq = ProductQuantizer(
            dim=self.dim,
            segments=self.config.pq.segments,
            centroids=self.config.pq.centroids,
            metric=self.metric,
            encoder=self.config.pq.encoder.type,
            distribution=self.config.pq.encoder.distribution,
            rotation=self.config.pq.rotation,
        )
        pq.fit(host[occupied])
        self._enable_pq(pq, host, save=True)

    def _enable_pq(self, pq, host: np.ndarray, save: bool) -> None:
        """Shard codes + ||recon||^2 over the mesh. Dead/padding rows encode
        garbage but are masked by tombs/high-water in the kernel. The store
        itself stays resident as the per-chip rescore source, downcast to
        bf16 when it was f32 (the single-chip index's drop-the-float-cache
        memory move, mesh-shaped); the full-precision rows move to host RAM
        so compact()'s log rewrite never re-persists bf16-rounded data
        (tpu.py _host_vecs parity)."""
        t0 = time.perf_counter()
        codes = pq.encode(host)                       # [cap, M]
        norms = pq.recon_sq_norms(codes).astype(np.float32)
        self._pq = pq
        self._codes = jax.device_put(jnp.asarray(codes), shard_spec(self.mesh, None))
        self._recon_norms = jax.device_put(jnp.asarray(norms), shard_spec(self.mesh))
        self._host_vecs = np.array(host, dtype=np.float32)
        if self.dtype == jnp.float32:
            self.dtype = jnp.bfloat16
            # module-level jitted downcast (sharding propagates from the
            # input); re-jitting a lambda here would compile per call
            self._store = jax.device_put(
                _downcast_bf16(self._store), shard_spec(self.mesh, None))
        self.compressed = True
        if save and self._pq_path:
            pq.save(self._pq_path)
        led = memory.get_ledger()
        if led is not None:
            led.note_write(
                "compress", "compress", (time.perf_counter() - t0) * 1000.0,
                rows=self.live, bytes_moved=memory.array_bytes(self._codes))
        self._stamp_memory()

    # -- VectorIndex ---------------------------------------------------------

    def add(self, doc_id: int, vector: np.ndarray) -> None:
        with self._lock:
            self._stage_add(int(doc_id), vector)

    def add_batch(self, doc_ids: Sequence[int], vectors: np.ndarray) -> None:
        """Bulk import: fresh unique doc_ids take the fully-vectorized
        balanced-write path; collisions fall back to per-row staging."""
        doc_arr = np.asarray(doc_ids, dtype=np.int64)
        vectors = np.asarray(vectors, dtype=np.float32)
        with self._lock:
            collides = any(int(d) in self._doc_to_row for d in doc_arr) or bool(
                self._pending
            )
            fresh = (
                not collides
                and vectors.ndim == 2
                and np.unique(doc_arr).size == doc_arr.size
            )
            if not fresh:
                for d, v in zip(doc_arr, vectors):
                    self._stage_add(int(d), v)
                return
            if self.metric == vi.DISTANCE_COSINE:
                norms = np.linalg.norm(vectors, axis=1, keepdims=True)
                norms[norms == 0] = 1.0
                vectors = vectors / norms
            if self.dim is None:
                self._init_device(int(vectors.shape[1]))
            elif vectors.shape[1] != self.dim:
                raise ValueError(
                    f"dim mismatch: index has {self.dim}, got {vectors.shape[1]}"
                )
            if self._log is not None and not self._restoring:
                self._log.append_add_batch(doc_arr, vectors)
            self._write_balanced(doc_arr, vectors)
            self.live += doc_arr.size

    def delete(self, *doc_ids: int) -> None:
        with self._lock:
            for d in doc_ids:
                self._stage_delete(int(d))

    def contains(self, doc_id: int) -> bool:
        with self._lock:
            return doc_id in self._doc_to_row or doc_id in self._pending

    def __len__(self) -> int:
        return self.live

    def distancer_name(self) -> str:
        return self.metric

    def _prep_queries(self, vectors: np.ndarray) -> tuple[np.ndarray, int]:
        q = np.asarray(vectors, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        b = q.shape[0]
        if self.metric == vi.DISTANCE_COSINE:
            norms = np.linalg.norm(q, axis=1, keepdims=True)
            norms[norms == 0] = 1.0
            q = q / norms
        bb = _bucket_b(b)
        if bb != b:
            q = np.concatenate([q, np.zeros((bb - b, q.shape[1]), np.float32)])
        return q, b

    def _allow_words(self, allow_list: AllowList) -> jax.Array:
        """Sharded packed filter words, cached ON the (immutable) allowList
        per index state — same contract as the single-chip twin
        (index/tpu.py _allow_words)."""
        from weaviate_tpu.storage.bitmap import (
            Bitmap, allowed_mask, pack_allow_words)

        cap = self.n_dev * self.n_loc
        key = (self._allow_token, int(self._counts.sum()), cap)
        cached = getattr(allow_list, "_words_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        mask = np.zeros(cap, dtype=bool)
        occupied = self._slot_to_doc >= 0
        if occupied.any():
            docs = self._slot_to_doc[occupied]
            if isinstance(allow_list, Bitmap):
                mask[occupied] = allowed_mask(allow_list, docs)
            else:
                mask[occupied] = allow_list.contains_array(docs.astype(np.uint64))
        out = jax.device_put(
            jnp.asarray(pack_allow_words(mask, cap)), shard_spec(self.mesh))
        try:
            allow_list._words_cache = (key, out)
        except AttributeError:
            pass
        return out

    def search_by_vectors(
        self, vectors: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        with self._lock:
            self._flush_pending()
            if self.live == 0 or self.dim is None:
                b = 1 if np.asarray(vectors).ndim == 1 else len(vectors)
                return (
                    np.zeros((b, 0), dtype=np.uint64),
                    np.zeros((b, 0), dtype=np.float32),
                )
            q, b = self._prep_queries(vectors)
            chunk = min(self.n_loc, _MESH_SCAN_CHUNK)
            kk = max(1, min(k, self.live, chunk))
            use_allow = allow_list is not None
            words = self._allow_words(allow_list) if use_allow else self._zero_words
            from weaviate_tpu.ops.topk import unpack_topk

            if self.compressed:
                if not self.config.pq.rescore:
                    # codes-only tier: try the fused per-shard ADC kernel
                    # (mesh twin of the single-chip pq_gmin dispatch)
                    packed = self._pq_gmin_step_or_none(q, kk, words, use_allow)
                    if packed is not None:
                        top, rows = unpack_topk(np.asarray(packed))
                        top, rows = top[:b], rows[:b]
                        ids = np.where(
                            rows >= 0,
                            self._slot_to_doc[np.clip(rows, 0, None)], -1)
                        return ids.astype(np.uint64), top.astype(np.float32)
                nchunks_eff = max(1, self.n_loc // chunk)
                pool_target = self.config.pq.rescore_limit or 1024
                r_chunk = min(
                    max(2 * kk, -(-pool_target // nchunks_eff), 64), 256, chunk)
                # the concatenated per-chip pool must cover k (tpu.py:1080)
                r_chunk = max(r_chunk, min(-(-kk // nchunks_eff), chunk))
                packed = np.asarray(
                    mesh_search_pq_step(
                        self._codes,
                        self._recon_norms,
                        self._tombs,
                        jnp.asarray(self._counts.astype(np.int32)),
                        words,
                        self._pq._dev_codebook(),
                        self._store,
                        jnp.asarray(q),
                        self._pq.rotation_dev(),
                        kk,
                        r_chunk,
                        self.metric,
                        use_allow,
                        getattr(self.config, "exact_topk", False),
                        self.config.pq.rescore,
                        self.mesh,
                    )
                )
                top, rows = unpack_topk(packed)
                top, rows = top[:b], rows[:b]
                ids = np.where(rows >= 0, self._slot_to_doc[np.clip(rows, 0, None)], -1)
                return ids.astype(np.uint64), top.astype(np.float32)

            packed = self._gmin_step_or_none(q, kk, words, use_allow)
            if packed is None:
                packed = np.asarray(
                    mesh_search_step(
                        self._store,
                        self._sq_norms,
                        self._tombs,
                        jnp.asarray(self._counts.astype(np.int32)),
                        words,
                        jnp.asarray(q),
                        kk,
                        self.metric,
                        use_allow,
                        self.metric == vi.DISTANCE_L2,
                        getattr(self.config, "exact_topk", False),
                        self.mesh,
                    )
                )
            top, rows = unpack_topk(packed)
            top, rows = top[:b], rows[:b]
            ids = np.where(rows >= 0, self._slot_to_doc[np.clip(rows, 0, None)], -1)
            return ids.astype(np.uint64), top.astype(np.float32)

    def _gmin_plan(self, b: int, kk: int):
        """-> (rg, active_g) when the fused mesh kernel is eligible for this
        shape (metric, slab size, VMEM budget), else None. Pure gate — no
        kernel execution — so tests can assert eligibility directly."""
        from weaviate_tpu.ops import gmin_scan

        if getattr(self.config, "exact_topk", False):
            return None  # config opt-out, not degradation
        if self._gmin_broken:
            record_device_fallback("index.mesh.gmin", "degraded", log=False)
            return None
        if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
            return None
        if self.n_loc < 16384 or b < 8:
            return None
        ncols_l = self.n_loc // gmin_scan.G
        rg = min(max(32, 2 * kk), 128, ncols_l)
        if rg < kk:
            return None
        active_g = max(1, -(-int(self._counts.max()) // ncols_l))
        if not gmin_scan.fits_vmem(b, self.dim, ncols_l, active_g,
                                   self._store.dtype.itemsize):
            return None
        return rg, active_g

    def _pq_gmin_step_or_none(self, q: np.ndarray, kk: int, words, use_allow):
        """Run the fused per-shard PQ codes kernel, or None for the legacy
        reconstruction scan — separate failure domain (self._pqg_state);
        gating and codebook constants are the shared helpers in
        ops/pq_gmin.py (one copy with the single-chip dispatch)."""
        from weaviate_tpu.parallel.mesh_search import mesh_search_pq_gmin_step

        from weaviate_tpu.ops import gmin_scan, pq_gmin

        ncols_l = self.n_loc // gmin_scan.G
        active_g = max(1, -(-int(self._counts.max()) // ncols_l)) if ncols_l else 1
        rg = pq_gmin.eligible_rg(
            self._pqg_state, getattr(self.config, "exact_topk", False),
            self.metric, self._pq, q.shape[0], ncols_l, kk, self.dim, active_g,
            component="index.mesh.pq_gmin")
        if rg is None:
            return None
        m, c = self._pq.segments, self._pq.centroids
        interpret = jax.default_backend() not in ("tpu", "axon")
        cb_chunks, flat_cb = pq_gmin.cached_cb_constants(self)
        key = ("pq", q.shape[0], kk, rg, active_g, self.n_loc, m, c, use_allow)
        packed = gmin_scan.guarded_kernel_call(
            self._pqg_state, key,
            lambda: mesh_search_pq_gmin_step(
                self._codes,
                self._recon_norms,
                self._tombs,
                jnp.asarray(self._counts.astype(np.int32)),
                words,
                cb_chunks,
                flat_cb,
                jnp.asarray(q),
                self._pq.rotation_dev(),
                kk,
                self.metric,
                use_allow,
                rg,
                active_g,
                interpret,
                self.mesh,
            ),
            "mesh pq codes kernel", component="index.mesh.pq_gmin")
        return None if packed is None else np.asarray(packed)

    def _gmin_step_or_none(self, q: np.ndarray, kk: int, words, use_allow):
        """Run the fused group-min mesh kernel, or None for the legacy scan.
        Validation mirrors tpu.py's _gmin_packed_or_none: per compiled
        shape — a Mosaic rejection on a NEW shape falls back for that shape
        only, a failure on a shape that already served propagates, and only
        repeated distinct-shape failures with zero successes disable the
        path."""
        from weaviate_tpu.parallel.mesh_search import mesh_search_gmin_step

        from weaviate_tpu.ops import gmin_scan

        plan = self._gmin_plan(q.shape[0], kk)
        if plan is None:
            return None
        rg, active_g = plan
        key = (q.shape[0], kk, rg, active_g, self.n_loc, use_allow)
        interpret = jax.default_backend() not in ("tpu", "axon")
        packed = gmin_scan.guarded_kernel_call(
            self, key,
            lambda: mesh_search_gmin_step(
                self._store,
                self._sq_norms,
                self._tombs,
                jnp.asarray(self._counts.astype(np.int32)),
                words,
                jnp.asarray(q),
                kk,
                self.metric,
                use_allow,
                self.metric == vi.DISTANCE_L2,
                rg,
                active_g,
                interpret,
                self.mesh,
            ),
            "mesh gmin kernel", component="index.mesh.gmin")
        return None if packed is None else np.asarray(packed)

    def search_by_vector(
        self, vector: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        ids, dists = self.search_by_vectors(np.asarray(vector)[None, :], k, allow_list)
        keep = dists[0] != np.inf
        return ids[0][keep], dists[0][keep]

    def search_by_vector_distance(
        self,
        vector: np.ndarray,
        target_distance: float,
        max_limit: int,
        allow_list: Optional[AllowList] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Doubling-limit loop (search.go:90-157 semantics)."""
        limit = 64
        while True:
            ids, dists = self.search_by_vector(vector, min(limit, max_limit), allow_list)
            if len(ids) == 0:
                return ids, dists
            beyond = dists > target_distance
            if beyond.any() or len(ids) >= min(max_limit, self.live):
                keep = dists <= target_distance
                return ids[keep][:max_limit], dists[keep][:max_limit]
            if limit >= max_limit:
                return ids[:max_limit], dists[:max_limit]
            limit *= 2

    def update_user_config(self, updated: vi.HnswUserConfig) -> None:
        with self._lock:
            vi.validate_config_update(self.config, updated)
            was_enabled = self.config.pq.enabled
            if updated.pq.enabled and not was_enabled:
                # reject what is knowable NOW instead of deferring the
                # failure into the compression trigger
                if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT,
                                       vi.DISTANCE_COSINE):
                    raise vi.ConfigValidationError(
                        f"pq on hnsw_tpu_mesh supports l2-squared/dot/"
                        f"cosine, not {self.metric}")
                if (self.dim is not None and updated.pq.segments > 0
                        and self.dim % updated.pq.segments != 0):
                    raise vi.ConfigValidationError(
                        f"pq.segments ({updated.pq.segments}) must divide "
                        f"vector dims ({self.dim})")
            prev = self.config
            self.config = updated
            # pq.enabled flipped on triggers compression (compress.go)
            if updated.pq.enabled and not was_enabled and not self.compressed:
                try:
                    self._flush_pending()
                    if self.live > 0:
                        self._compress_locked()
                except Exception:
                    # a failed pq-enable must not stick — config or runtime
                    # (an OOM'd kmeans fit): a committed-but-uncompressed
                    # config would re-run the full fit from _flush_pending's
                    # declarative trigger on every later add/search
                    self.config = prev
                    raise

    def flush(self) -> None:
        with self._lock:
            self._flush_pending()
            if self._log is not None:
                self._log.flush()

    def compact(self) -> None:
        """Condense: drop tombstoned slots, rewrite the log, rebuild balanced
        (condensor.go analog)."""
        with self._lock:
            self._flush_pending()
            if self.dim is None or not self._doc_to_row:
                return
            total = int(self._counts.sum())
            if len(self._doc_to_row) == total:
                return
            t_compact0 = time.perf_counter()
            rows = np.array(sorted(self._doc_to_row.values()), dtype=np.int64)
            docs = self._slot_to_doc[rows]
            # compressed mode rewrites the log from the f32 host copy — the
            # device store is bf16 by then and must not degrade durable data
            src = self._host_vecs if self.compressed else np.asarray(
                self._store, dtype=np.float32)
            store_host = np.asarray(src, dtype=np.float32)[rows]
            if self._log is not None:
                self._log.rewrite(zip(docs.tolist(), store_host))
            # mapping rebuild invalidates any packed-words cache keyed on it
            self._allow_token = object()
            dim = self.dim
            self.dim = None
            self.n_loc = 0
            self.live = 0
            self._counts = np.zeros(self.n_dev, dtype=np.int64)
            self._doc_to_row.clear()
            self._slot_to_doc = np.zeros(0, dtype=np.int64)
            self._store = self._sq_norms = self._tombs = None
            self._init_device(dim)
            self._restoring = True
            try:
                self.add_batch(docs, store_host)
            finally:
                self._restoring = False
            led = memory.get_ledger()
            if led is not None:
                led.note_write(
                    "compact", "compact",
                    (time.perf_counter() - t_compact0) * 1000.0,
                    rows=self.live)

    def drop(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.close()
                try:
                    os.remove(self._log.path)
                except FileNotFoundError:
                    pass
                self._log = None
            self._store = self._sq_norms = self._tombs = None
            self._zero_words = None  # sharded device words must free too
            self._codes = self._recon_norms = None
            self._host_vecs = None
            self._pq = None
            self.compressed = False
            if self._pq_path:
                try:
                    os.remove(self._pq_path)
                except FileNotFoundError:
                    pass
            self.dim = None
            self.n_loc = 0
            self.live = 0
            self._counts = np.zeros(self.n_dev, dtype=np.int64)
            self._slot_to_doc = np.zeros(0, dtype=np.int64)
            self._doc_to_row.clear()
            self._pending.clear()
            self._pending_tombs.clear()
            self._stamp_memory()  # zero this index's device components

    def shutdown(self) -> None:
        with self._lock:
            self._flush_pending()
            if self._log is not None:
                self._log.flush()
                self._log.close()

    def list_files(self) -> list[str]:
        out = [self._log.path] if self._log is not None else []
        if self._pq_path and os.path.exists(self._pq_path):
            out.append(self._pq_path)  # backups must carry the codebook
        return out
