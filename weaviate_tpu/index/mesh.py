"""The mesh-sharded TPU vector index ("hnsw_tpu_mesh").

The multi-chip twin of index/tpu.py: one logical shard's vectors are spread
over every chip of a jax.sharding.Mesh as per-chip HBM slabs, and every
operation is a whole-mesh SPMD program (kernels in
weaviate_tpu/parallel/mesh_search.py):

- insert: staged host-side, flushed as ONE sharded [n_dev, C, D] write —
  each chip lands its own chunk at its own offset (no per-shard dispatch
  loop);
- search: chunked masked scan per slab + local top-k, cross-chip merge over
  ICI (all_gather + reselect) inside the same jit, then on-device slot→doc
  translation against the sharded pair table — the fused dispatch returns
  the packed [B, 3k] buffer, so finalize is ONE fetch and dtype views
  (the single-chip one-fetch/zero-translation invariant, now across chips);
- delete: tombstone scatter where each chip claims the global rows in its
  slab;
- filters: the allowList becomes a packed uint32 bitmap sharded over the
  mesh, ANDed into the validity mask on device (helpers/allow_list.go
  semantics; no host-side row gathering);
- growth: geometric slab doubling fully on device (maintainance.go:31).

Reads are SNAPSHOT-ISOLATED with the same lock-free discipline as the
single-chip index (docs/concurrency.md, docs/mesh_serving.md): writers
publish an immutable MeshSnapshot with one atomic reference swap; readers
grab it without the index lock and run the whole two-phase dispatch
(enqueue on the snapshot, fetch outside any lock). Because the mesh write
kernels are NON-donating, a published snapshot pins the exact device slabs
it was built from — deletes, growth, compression, and compaction can never
tear an in-flight dispatch.

Durability reuses the single-chip index's VectorLog (add/delete records,
torn-tail-tolerant replay) — the log format is placement-independent, so a
shard can restart onto a different mesh size and the replay re-balances.

This replaces the reference's scatter-gather over goroutines+HTTP
(adapters/repos/db/index.go:967-1046) for the intra-node multi-chip case:
the collective rides ICI instead of the network.

PQ (compress.go parity, mesh-shaped): codes and ||recon||^2 shard like the
store; each chip runs the reconstruction-matmul scan over its own code
slab, rescores its local candidates against its local row slab at exact
f32, and the k best per chip merge over ICI. Compression downcasts an f32
store to bf16 (the memory move the single-chip index makes by dropping its
float cache); post-compress appends encode on write.

IVF (the partition-pruned tier, mesh-shaped): one k-means codebook is
trained over ALL chips' rows, then each chip gets its own KScaNN-style
balanced bucket table over its local slab (ops/ivf.py balanced_assign per
device, one shared capacity so the [n_dev, nlist, cap_p] table shards
cleanly). The probe runs per chip against replicated centroids; training
happens off-lock from a pinned snapshot with a write backlog, exactly like
the single-chip staged-clustering plane.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.interface import AllowList, VectorIndex
from weaviate_tpu.index.tpu import (
    VectorLog,
    _S2D_FILL,
    _bucket_b,
    _bucket_rows,
    _fetch_packed,
    _snap_top_p,
    fused_dispatch_enabled,
    ivf_settings,
)
# dispatch-shape recording for the perf-attribution plane: a
# costmodel.DispatchShape is built per dispatch ONLY while the tracer is
# up (tracing.get_tracer() gate — the zero-cost-when-disabled contract);
# shapes carry ndev so the roofline normalizes to per-chip work
from weaviate_tpu.monitoring import costmodel, tracing
# memory ledger (monitoring/memory.py): per-device slab components are
# stamped analytically at every buffer mutation; unconfigured => one
# comparison, nothing constructed
from weaviate_tpu.monitoring import memory
# shadow recall auditing (monitoring/quality.py): the dispatch snapshot is
# pinned in TLS ONLY while an auditor is configured, so the audit compares
# against the exact mesh state the live answer saw
from weaviate_tpu.monitoring import quality
from weaviate_tpu.monitoring.costmodel import (
    TIER_EXACT,
    TIER_PQ_ADC4,
    TIER_PQ_CODES,
    TIER_PQ_RESCORE,
    DispatchShape,
)
from weaviate_tpu.monitoring.metrics import record_device_fallback
from weaviate_tpu.ops import ivf as ivf_ops
from weaviate_tpu.ops.topk import unpack_fused, unpack_topk
# the recall-guarded probe-depth cap shares the single-chip controller;
# controller imports nothing from the index layer, so no cycle
from weaviate_tpu.serving import controller
from weaviate_tpu.testing import faults, sanitizers
from weaviate_tpu.parallel.mesh_search import (
    _MESH_SCAN_CHUNK,
    make_mesh,
    mesh_delete_step,
    mesh_grow_1d,
    mesh_grow_2d,
    mesh_grow_pairs,
    mesh_insert_step,
    mesh_search_ivf_step,
    mesh_search_pq4_step,
    mesh_search_pq_step,
    mesh_search_step,
    mesh_write_pairs_step,
    mesh_write_rows_step,
    replicated,
    shard_spec,
)
from weaviate_tpu.compress.pq import pack_codes4 as pq_pack_codes4
from weaviate_tpu.config.config import (PQ4_FUNNEL_C_BUCKETS,
                                        PQ4_FUNNEL_RESCORE_BUCKETS)

_MIN_LOC = 1024       # minimum slab rows per chip (power of two, mult of 32)
_FLUSH_CHUNK = 8192   # staged rows that trigger a flush
_MAX_WRITE_C = 8192   # max rows per chip per insert step


def _pow2_at_least(n: int, floor: int) -> int:
    c = floor
    while c < n:
        c *= 2
    return c


@jax.jit
def _downcast_bf16(store):
    """One cached compilation for the compress-time store downcast; the
    output keeps the input's mesh sharding."""
    return store.astype(jnp.bfloat16)


class MeshSnapshot:
    """An immutable view of the mesh index state, published atomically.

    Same contract as the single-chip IndexSnapshot (index/tpu.py): the
    constructor copies REFERENCES under the write lock; correctness rests
    on every referenced buffer being effectively immutable once published —
    the mesh write kernels are non-donating (every flush/delete/grow binds
    NEW device arrays to the index fields, the snapshot keeps the old
    ones), ``host_tombs`` is copy-on-write (_mark_dead), ``slot_to_doc``
    is append-only within a device generation (rows past a snapshot's
    per-device counts are never read by it), and ``counts`` is copied
    outright because the index mutates it in place."""

    __slots__ = (
        "gen", "dim", "n_dev", "n_loc", "counts", "counts_dev", "n_total",
        "live", "store", "sq_norms", "tombs", "zero_words", "slot_to_doc",
        "slot_to_doc_dev", "host_tombs", "allow_token", "compressed", "pq",
        "codes", "recon_norms", "pq4", "codes4", "recon_norms4", "opq_rot",
        "host_vecs", "ivf_centroids", "ivf_buckets", "ivf_meta",
    )

    def __init__(self, gen: int, idx: "MeshVectorIndex"):
        self.gen = gen
        self.dim = idx.dim
        self.n_dev = idx.n_dev
        self.n_loc = idx.n_loc
        self.counts = idx._counts.copy()
        # replicated i32 per-shard high-water marks for the kernels (the
        # P() in_spec broadcasts a plain committed array)
        self.counts_dev = (
            jnp.asarray(self.counts.astype(np.int32))
            if idx.dim is not None else None
        )
        self.n_total = int(self.counts.sum())
        self.live = idx.live
        self.store = idx._store
        self.sq_norms = idx._sq_norms
        self.tombs = idx._tombs
        self.zero_words = idx._zero_words
        self.slot_to_doc = idx._slot_to_doc
        self.slot_to_doc_dev = idx._s2d_dev
        self.host_tombs = idx._host_tombs
        self.allow_token = idx._allow_token
        self.compressed = idx.compressed
        self.pq = idx._pq
        self.codes = idx._codes
        self.recon_norms = idx._recon_norms
        # the 4-bit ladder rung (COW like every other slab: writes bind
        # NEW sharded arrays, this snapshot keeps the ones it was born with)
        self.pq4 = idx._pq4
        self.codes4 = idx._codes4
        self.recon_norms4 = idx._recon_norms4
        self.opq_rot = idx._opq_rot_dev
        self.host_vecs = idx._host_vecs
        self.ivf_centroids = idx._ivf_centroids
        self.ivf_buckets = idx._ivf_buckets
        self.ivf_meta = idx._ivf_meta


class MeshVectorIndex(VectorIndex):
    # serving layers key off this: filtered lanes ride the coalesced
    # two-phase dispatch instead of falling back to the sync pool
    async_supports_filters = True

    _HOST_SCAN_CHUNK = 65536  # rows per host-fallback scan block

    def __init__(
        self,
        config: vi.HnswUserConfig,
        shard_path: str,
        shard_name: str = "",
        metrics=None,
        mesh=None,
        persist: bool = True,
        initial_capacity_per_shard: Optional[int] = None,
        dim_hint: Optional[int] = None,
        class_name: str = "",
    ):
        self.config = config
        self.metric = config.distance
        self.shard_path = shard_path
        self.shard_name = shard_name
        self.class_name = class_name
        self.metrics = metrics
        self.mesh = mesh if mesh is not None else make_mesh(
            getattr(config, "mesh_devices", 0) or None
        )
        self.n_dev = self.mesh.devices.size
        self.dtype = (
            jnp.bfloat16
            if getattr(config, "store_dtype", "float32") == "bfloat16"
            else jnp.float32
        )
        self._lock = sanitizers.register_lock(
            threading.RLock(), "index.mesh")
        self._init_loc = _pow2_at_least(
            initial_capacity_per_shard or _MIN_LOC, 32
        )
        self.dim: Optional[int] = None
        self.n_loc = 0               # slab rows per chip
        self.live = 0
        self._store = None           # sharded [n_dev * n_loc, D]
        self._sq_norms = None        # sharded [n_dev * n_loc] f32
        self._tombs = None           # sharded [n_dev * n_loc] bool
        self._zero_words = None      # sharded [n_dev * n_loc / 32] u32 (no-filter)
        self._counts = np.zeros(self.n_dev, dtype=np.int64)
        self._slot_to_doc = np.zeros(0, dtype=np.int64)  # global row -> doc
        self._s2d_dev = None         # sharded [cap, 2] u32 (id_lo, id_hi)
        self._host_tombs = np.zeros(0, dtype=bool)  # COW: snapshots pin copies
        self._doc_to_row: dict[int, int] = {}
        self._pending: dict[int, np.ndarray] = {}
        self._pending_tombs: list[int] = []
        # snapshot plane (docs/mesh_serving.md): readers are lock-free on
        # the published MeshSnapshot; staged/published generations drive
        # the republish-on-read slow path
        self._snap: Optional[MeshSnapshot] = None
        self._snap_gen = 0
        self._staged_gen = 0
        self._published_gen = -1  # != staged: the first read publishes
        self._staged_t0: Optional[float] = None
        self._read_local = threading.local()
        self._inflight = 0
        self._inflight_lock = sanitizers.register_lock(
            threading.Lock(), "index.mesh.inflight")
        self._inflight_gauge = None
        self._host_rows_cache = None  # (gen, rows, sq) breaker-path cache
        # device generation: compact/drop re-create the slabs; an off-lock
        # IVF trainer must abandon results targeted at a dead epoch
        self._device_epoch = 0
        # IVF plane (mesh twin of the single-chip staged clustering):
        # stats lock is leaf-level, ordered after index.mesh
        self._ivf_lock = sanitizers.register_lock(
            threading.Lock(), "index.mesh.ivf")
        self._ivf_stats = {"dispatches": 0, "probed_rows": 0, "base_rows": 0}
        self._ivf_centroids_host = None   # np [nlist, D] f32
        self._ivf_centroids = None        # replicated device copy
        self._ivf_buckets = None          # sharded [n_dev, nlist, cap_p] i32
        self._ivf_assign = np.zeros(0, dtype=np.int32)  # per-row partition
        self._ivf_fills = None            # np [n_dev, nlist] bucket fills
        self._ivf_cap_p = 0
        self._ivf_meta = None             # (nlist, cap_p, gen)
        self._ivf_dirty = False
        self._ivf_trained_n = 0
        self._ivf_gen = 0
        self._ivf_backlog = None          # rows written during off-lock training
        # PQ state (mesh twin of index/tpu.py compression): codes and
        # ||recon||^2 are sharded like the store; the (possibly bf16)
        # store itself stays resident as the per-chip rescore source
        self.compressed = False
        self._pq = None
        self._codes = None          # sharded [n_dev * n_loc, M]
        self._recon_norms = None    # sharded [n_dev * n_loc] f32
        self._pq4 = None            # the 4-bit rung's quantizer (16 cents)
        self._codes4 = None         # sharded [n_dev * n_loc, M/2] uint8
        self._recon_norms4 = None   # sharded [n_dev * n_loc] f32
        self._opq_rot_dev = None    # replicated [D, D] f32 (shared OPQ)
        self._host_vecs = None      # np [cap, D] f32 (compressed mode only)
        self._pq_path = os.path.join(shard_path, "pq.npz") if shard_path else ""
        self._pq4_path = (os.path.join(shard_path, "pq4.npz")
                          if shard_path else "")
        self._restoring = False
        self._gmin_broken = False  # fused mesh kernel failed: use the scan
        # identity token for the per-allowList packed-words cache
        self._allow_token = object()
        # separate failure domain + codebook cache for the PQ codes kernel
        from weaviate_tpu.ops.gmin_scan import KernelState

        self._pqg_state = KernelState()
        self._pqg_cb = None
        self._gmin_validated: set = set()     # shapes that served correctly
        self._gmin_shape_broken: set = set()  # shapes Mosaic rejected
        # host-memory provider (monitoring/memory.py): slot map, PQ host
        # rows, and staged rows become /debug/memory host components
        memory.register_host_provider(self, memory.index_host_components)
        self._log = (
            VectorLog(os.path.join(shard_path, "vector.log")) if persist else None
        )
        if dim_hint is not None:
            self._init_device(int(dim_hint))
        if self._log is not None:
            self._restore()

    # -- lifecycle -----------------------------------------------------------

    def _restore(self) -> None:
        """Replay the vector log (startup.go:56 analog). Placement is
        recomputed at replay time, so the same log restores onto any mesh."""
        self._restoring = True
        try:
            replay_stats: dict = {}
            for op, ids, vecs in VectorLog.replay_batches(self._log.path, stats=replay_stats):
                if op == "add":
                    self._bulk_stage_add(ids, vecs)
                else:
                    self._stage_delete(int(ids), log=False)
            VectorLog.report_replay_stats(self._log.path, replay_stats)
            self.last_replay_stats = replay_stats
            if self._pq_path and os.path.exists(self._pq_path):
                from weaviate_tpu.compress.pq import ProductQuantizer

                self._flush_pending()
                if self.live > 0:
                    self._enable_pq(
                        ProductQuantizer.load(self._pq_path),
                        np.asarray(self._store, dtype=np.float32),
                        save=False,
                    )
        finally:
            self._restoring = False

    def post_startup(self) -> None:
        self.flush()

    # -- memory ledger stamping (monitoring/memory.py) -----------------------

    def _memory_components(self) -> dict:
        """Analytic byte sizes of the mesh slab buffers (global totals of
        the sharded arrays; the ledger divides by ``ndev`` for per-chip
        headroom). Zero syncs; equals the arrays' ``nbytes`` exactly."""
        comps: dict = {}
        for name, arr in (("store", self._store),
                          ("sq_norms", self._sq_norms),
                          ("tombs", self._tombs),
                          ("slot_to_doc", self._s2d_dev),
                          ("pq_codes", self._codes),
                          ("recon_norms", self._recon_norms),
                          ("pq4_codes", self._codes4),
                          ("pq4_norms", self._recon_norms4),
                          ("opq_rot", self._opq_rot_dev),
                          ("ivf_centroids", self._ivf_centroids),
                          ("ivf_buckets", self._ivf_buckets),
                          ("allow_words", self._zero_words)):
            b = memory.array_bytes(arr)
            if b:
                comps[name] = b
        return comps

    def _stamp_memory(self) -> None:
        """The JGL012-registered stamping hook: every method that binds a
        device buffer to a slab field flows through here."""
        led = memory.get_ledger()
        if led is not None:
            led.stamp_device(self, self._memory_components(),
                             ndev=self.n_dev)

    # -- device plumbing -----------------------------------------------------

    def _init_device(self, dim: int) -> None:
        self.dim = dim
        self.n_loc = self._init_loc
        cap = self.n_dev * self.n_loc
        sh2 = shard_spec(self.mesh, None)
        sh1 = shard_spec(self.mesh)
        self._store = jax.device_put(jnp.zeros((cap, dim), self.dtype), sh2)
        self._sq_norms = jax.device_put(jnp.zeros((cap,), jnp.float32), sh1)
        self._tombs = jax.device_put(jnp.zeros((cap,), jnp.bool_), sh1)
        self._zero_words = jax.device_put(jnp.zeros((cap // 32,), jnp.uint32), sh1)
        self._s2d_dev = jax.device_put(
            jnp.full((cap, 2), _S2D_FILL, jnp.uint32), sh2)
        self._slot_to_doc = np.full(cap, -1, dtype=np.int64)
        self._host_tombs = np.zeros(cap, dtype=bool)
        self._ivf_assign = np.full(cap, -1, dtype=np.int32)
        self._device_epoch += 1
        if self._ivf_centroids_host is not None:
            self._ivf_dirty = True
        if self.compressed and self._pq is not None:
            # a device reset in compressed mode (compact) re-creates the
            # code slabs too; _write_balanced re-encodes rows as they land
            self._codes = jax.device_put(
                jnp.zeros((cap, self._pq.segments), self._pq.code_dtype), sh2)
            self._recon_norms = jax.device_put(jnp.zeros((cap,), jnp.float32), sh1)
            if self._pq4 is not None:
                self._codes4 = jax.device_put(
                    jnp.zeros((cap, self._pq4.segments // 2), jnp.uint8), sh2)
                self._recon_norms4 = jax.device_put(
                    jnp.zeros((cap,), jnp.float32), sh1)
            self._host_vecs = np.zeros((cap, dim), np.float32)
        self._stamp_memory()

    def _grow(self, needed_per_shard: int) -> None:
        new_loc = self.n_loc
        while new_loc < needed_per_shard:
            new_loc *= 2
        if new_loc == self.n_loc:
            return
        old_loc = self.n_loc
        self._store = mesh_grow_2d(self._store, new_loc, self.mesh)
        self._sq_norms = mesh_grow_1d(self._sq_norms, new_loc, self.mesh)
        self._tombs = mesh_grow_1d(self._tombs, new_loc, self.mesh)
        self._s2d_dev = mesh_grow_pairs(
            self._s2d_dev, new_loc, _S2D_FILL, self.mesh)
        if self.compressed:
            self._codes = mesh_grow_2d(self._codes, new_loc, self.mesh)
            self._recon_norms = mesh_grow_1d(self._recon_norms, new_loc, self.mesh)
            if self._codes4 is not None:
                self._codes4 = mesh_grow_2d(self._codes4, new_loc, self.mesh)
                self._recon_norms4 = mesh_grow_1d(
                    self._recon_norms4, new_loc, self.mesh)
            hv = np.zeros((self.n_dev * new_loc, self.dim), np.float32)
            for s in range(self.n_dev):
                hv[s * new_loc : s * new_loc + old_loc] = self._host_vecs[
                    s * old_loc : (s + 1) * old_loc
                ]
            self._host_vecs = hv
        cap = self.n_dev * new_loc
        self._zero_words = jax.device_put(
            jnp.zeros((cap // 32,), jnp.uint32), shard_spec(self.mesh)
        )
        # remap global rows: slab-local offsets are preserved. Fresh host
        # arrays every grow — published snapshots keep the old ones.
        s2d = np.full(cap, -1, dtype=np.int64)
        ht = np.zeros(cap, dtype=bool)
        ia = np.full(cap, -1, dtype=np.int32)
        for s in range(self.n_dev):
            c = int(self._counts[s])
            s2d[s * new_loc : s * new_loc + c] = self._slot_to_doc[
                s * old_loc : s * old_loc + c
            ]
            ht[s * new_loc : s * new_loc + old_loc] = self._host_tombs[
                s * old_loc : (s + 1) * old_loc
            ]
            ia[s * new_loc : s * new_loc + old_loc] = self._ivf_assign[
                s * old_loc : (s + 1) * old_loc
            ]
        self._slot_to_doc = s2d
        self._host_tombs = ht
        self._ivf_assign = ia
        occ = np.nonzero((s2d >= 0) & ~ht)[0]
        self._doc_to_row = dict(zip(s2d[occ].tolist(), occ.tolist()))
        # staged-but-unflushed tombstone rows move with their slab
        self._pending_tombs = [
            (r // old_loc) * new_loc + (r % old_loc) for r in self._pending_tombs
        ]
        if self._ivf_backlog is not None:
            self._ivf_backlog = [
                ((g // old_loc) * new_loc + (g % old_loc), r)
                for g, r in self._ivf_backlog
            ]
        self.n_loc = new_loc
        led = memory.get_ledger()
        if led is not None:
            led.note_write_shape(
                ("mesh_grow", self.n_dev, new_loc, self.dim or 0,
                 self.compressed))
        self._stamp_memory()

    # -- staging -------------------------------------------------------------

    def _mark_dead(self, row: int) -> None:
        """Tombstone `row` in the host mask, copy-on-write: a published
        snapshot referencing the current mask keeps its version — torn
        reads of a half-updated liveness mask are impossible."""
        snap = self._snap
        if snap is not None and snap.host_tombs is self._host_tombs:
            self._host_tombs = self._host_tombs.copy()
        self._host_tombs[row] = True

    def _stage_add(self, doc_id: int, vector: np.ndarray, log: bool = True) -> None:
        vector = np.asarray(vector, dtype=np.float32)
        if self.metric == vi.DISTANCE_COSINE:
            nrm = float(np.linalg.norm(vector))
            if nrm > 0:
                vector = vector / nrm
        if self.dim is None:
            self._init_device(int(vector.shape[0]))
        elif vector.shape[0] != self.dim:
            raise ValueError(f"dim mismatch: index has {self.dim}, got {vector.shape[0]}")
        old = self._doc_to_row.pop(doc_id, None)
        if old is not None:
            self._pending_tombs.append(old)
            self._mark_dead(old)  # dead row must not resurrect via _grow
            self.live -= 1
        if doc_id in self._pending:
            self.live -= 1
        self._pending[doc_id] = vector
        self.live += 1
        self._staged_gen += 1
        self._mark_staged()
        if log and self._log is not None:
            self._log.append_add(doc_id, vector)
        if len(self._pending) >= _FLUSH_CHUNK:
            self._flush_pending()

    def _bulk_stage_add(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        """Restore-path bulk staging (single-chip twin in tpu.py): a run of
        add records feeds the staging buffer in one dict update with
        _stage_add's exact semantics; small/fragmented runs and docs the
        index already knows take the per-record path."""
        if len(ids) < 256:
            for d, v in zip(ids.tolist(), vecs):
                self._stage_add(int(d), v, log=False)
            return
        if self.dim is None:
            self._init_device(int(np.asarray(vecs).shape[1]))
        elif np.asarray(vecs).shape[1] != self.dim:
            raise ValueError(
                f"dim mismatch: index has {self.dim}, got {np.asarray(vecs).shape[1]}")
        from weaviate_tpu.index.tpu import _prep_bulk_run

        d2r = self._doc_to_row
        ids64, vecs, known = _prep_bulk_run(
            ids, vecs, self.metric,
            lambda d: d in d2r or d in self._pending)
        if known:
            for i in known:
                self._stage_add(int(ids64[i]), vecs[i], log=False)
            keep = np.ones(len(ids64), bool)
            keep[known] = False
            ids64, vecs = ids64[keep], vecs[keep]
            if len(ids64) == 0:
                return
        self._pending.update(zip(ids64.tolist(), vecs))
        self.live += len(ids64)
        self._staged_gen += 1
        self._mark_staged()
        if len(self._pending) >= _FLUSH_CHUNK:
            self._flush_pending()

    def _stage_delete(self, doc_id: int, log: bool = True) -> None:
        row = self._doc_to_row.pop(doc_id, None)
        if row is None:
            if doc_id in self._pending:
                del self._pending[doc_id]
                self.live -= 1
                self._staged_gen += 1
                self._mark_staged()
                if log and self._log is not None:
                    self._log.append_delete(doc_id)
            return
        self._pending_tombs.append(row)
        self._mark_dead(row)  # dead row must not resurrect via _grow
        self.live -= 1
        self._staged_gen += 1
        self._mark_staged()
        if log and self._log is not None:
            self._log.append_delete(doc_id)

    def _assign_balanced(self, count: int) -> list[np.ndarray]:
        """Split `count` new rows over shards so slab fills equalize
        (the chip-level analog of the virtual-shard ring's even spread,
        usecases/sharding/state.go:261)."""
        counts = self._counts.copy()
        takes = np.zeros(self.n_dev, dtype=np.int64)
        remaining = count
        # level-fill: repeatedly top up the emptiest shards
        while remaining > 0:
            order = np.argsort(counts + takes)
            lo = order[0]
            if self.n_dev > 1:
                second = counts[order[1]] + takes[order[1]]
                gap = int(second - (counts[lo] + takes[lo]))
                step = max(1, min(remaining, gap if gap > 0 else remaining // self.n_dev + 1))
            else:
                step = remaining
            takes[lo] += step
            remaining -= step
        out, off = [], 0
        for s in range(self.n_dev):
            out.append(np.arange(off, off + int(takes[s])))
            off += int(takes[s])
        return out

    def _flush_pending(self) -> None:
        """Land staged adds/tombstones on device. PURE staging drain — no
        compression, no IVF training — so the read path's republish
        (_read_snapshot slow path) can call it without ever reaching a
        stop-the-world maintenance fetch."""
        led = memory.get_ledger()
        if self._pending:
            t0 = time.perf_counter()
            rows = np.stack(list(self._pending.values()))
            docs = np.array(list(self._pending.keys()), dtype=np.int64)
            self._write_balanced(docs, rows)
            self._pending.clear()
            if led is not None:
                led.note_write(
                    "add", "flush", (time.perf_counter() - t0) * 1000.0,
                    rows=rows.shape[0],
                    bytes_moved=rows.shape[0] * (self.dim or 0) * 4)
        if self._pending_tombs:
            t0 = time.perf_counter()
            idx = np.array(self._pending_tombs, dtype=np.int32)
            pad = _bucket_rows(len(idx))
            padded = np.full(pad, -1, dtype=np.int32)
            padded[: len(idx)] = idx
            self._tombs = mesh_delete_step(self._tombs, jnp.asarray(padded), self.mesh)
            if led is not None:
                led.note_write(
                    "delete", "apply_tombstones",
                    (time.perf_counter() - t0) * 1000.0,
                    rows=len(self._pending_tombs))
            self._pending_tombs.clear()
            self._stamp_memory()

    def _maybe_autocompress(self) -> None:
        """Declarative pq.enabled compresses once enough data exists to fit
        codebooks (same trigger as the single-chip index). Reached only
        from flush()/compress()/update_user_config — never from the
        staging threshold sites."""
        if not (
            self.config.pq.enabled
            and not self.compressed
            and not self._restoring
            and self.live >= max(256, self.config.pq.centroids)
        ):
            return
        try:
            self._compress_locked()
        except vi.ConfigValidationError as e:
            # a pq config that only turns out invalid once dims are
            # known (declared before the first import) must not turn
            # every later add/search into an error: auto-disable with a
            # warning and keep serving uncompressed
            import logging

            self.config.pq.enabled = False
            logging.getLogger(__name__).warning(
                "declared pq config is invalid (%s); auto-disabling "
                "compression for this index", e)

    def _write_balanced(self, docs: np.ndarray, rows: np.ndarray) -> None:
        """Land [count, D] rows across slabs in whole-mesh insert steps."""
        assign = self._assign_balanced(rows.shape[0])
        needed = max(
            int(self._counts[s]) + len(assign[s]) for s in range(self.n_dev)
        )
        self._grow(needed)
        queues = [list(a) for a in assign]
        while any(queues):
            max_rem = max(len(q) for q in queues)
            max_off = max(
                int(self._counts[s]) for s in range(self.n_dev) if queues[s]
            )
            c = min(_bucket_rows(max_rem), _MAX_WRITE_C, self.n_loc - max_off)
            c = max(c, 1)
            chunks = np.zeros((self.n_dev, c, self.dim), np.float32)
            pairs = np.zeros((self.n_dev, c, 2), np.uint32)
            offsets = self._counts.astype(np.int32)
            takes = np.zeros(self.n_dev, dtype=np.int32)
            taken: list[np.ndarray] = []
            for s in range(self.n_dev):
                take = min(c, len(queues[s]))
                sel = np.array(queues[s][:take], dtype=np.int64)
                queues[s] = queues[s][take:]
                if take:
                    chunks[s, :take] = rows[sel]
                    du = docs[sel].view(np.uint64)
                    pairs[s, :take, 0] = (du & np.uint64(0xFFFFFFFF)).astype(
                        np.uint32)
                    pairs[s, :take, 1] = (du >> np.uint64(32)).astype(np.uint32)
                takes[s] = take
                taken.append(sel)
            chunks_dev = jax.device_put(
                jnp.asarray(chunks), shard_spec(self.mesh, None, None)
            )
            self._store, self._sq_norms = mesh_insert_step(
                self._store,
                self._sq_norms,
                chunks_dev,
                jnp.asarray(offsets),
                jnp.asarray(takes),
                self.metric == vi.DISTANCE_L2,
                self.mesh,
            )
            # the device translation table lands the same rows, so the fused
            # dispatch's on-device slot->doc stays in lockstep with the host map
            self._s2d_dev = mesh_write_pairs_step(
                self._s2d_dev,
                jax.device_put(jnp.asarray(pairs),
                               shard_spec(self.mesh, None, None)),
                jnp.asarray(offsets),
                jnp.asarray(takes),
                self.mesh,
            )
            if self.compressed:
                # post-compress appends also land codes + recon norms (the
                # single-chip index's encode-on-write parity)
                code_chunks = self._pq.encode(
                    chunks.reshape(-1, self.dim)
                ).reshape(self.n_dev, c, self._pq.segments)
                norm_chunks = self._pq.recon_sq_norms(
                    code_chunks.reshape(-1, self._pq.segments)
                ).reshape(self.n_dev, c).astype(np.float32)
                self._codes, self._recon_norms = mesh_write_rows_step(
                    self._codes,
                    self._recon_norms,
                    jax.device_put(jnp.asarray(code_chunks),
                                   shard_spec(self.mesh, None, None)),
                    jax.device_put(jnp.asarray(norm_chunks),
                                   shard_spec(self.mesh, None)),
                    jnp.asarray(offsets),
                    jnp.asarray(takes),
                    self.mesh,
                )
                if self._pq4 is not None:
                    # encode-on-write parity for the 4-bit rung: the same
                    # rows land packed two-codes-per-byte
                    c4 = self._pq4.encode(chunks.reshape(-1, self.dim))
                    p4 = pq_pack_codes4(c4).reshape(
                        self.n_dev, c, self._pq4.segments // 2)
                    n4 = self._pq4.recon_sq_norms(c4).reshape(
                        self.n_dev, c).astype(np.float32)
                    self._codes4, self._recon_norms4 = mesh_write_rows_step(
                        self._codes4,
                        self._recon_norms4,
                        jax.device_put(jnp.asarray(p4),
                                       shard_spec(self.mesh, None, None)),
                        jax.device_put(jnp.asarray(n4),
                                       shard_spec(self.mesh, None)),
                        jnp.asarray(offsets),
                        jnp.asarray(takes),
                        self.mesh,
                    )
            for s in range(self.n_dev):
                take = len(taken[s])
                if not take:
                    continue
                base = s * self.n_loc + int(self._counts[s])
                grows = np.arange(base, base + take)
                d = docs[taken[s]]
                self._slot_to_doc[grows] = d
                self._doc_to_row.update(zip(d.tolist(), grows.tolist()))
                if self.compressed:
                    self._host_vecs[grows] = rows[taken[s]]
                if self._ivf_backlog is not None:
                    # an off-lock k-means fit is in flight: queue the rows,
                    # the trainer (or its finally block) assigns them
                    self._ivf_backlog.append((grows, rows[taken[s]]))
                elif self._ivf_centroids_host is not None:
                    self._ivf_assign[grows] = ivf_ops.assign_partitions(
                        rows[taken[s]], self._ivf_centroids_host)
                    self._ivf_dirty = True
                self._counts[s] += take
        self._stamp_memory()

    # -- product quantization (mesh twin of index/tpu.py compression) --------

    def compress(self) -> None:
        with self._lock:
            self._flush_pending()
            self._compress_locked()

    def _compress_locked(self) -> None:
        from weaviate_tpu.compress.pq import ProductQuantizer

        if self.compressed:
            return
        if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
            # the mesh PQ kernel is the reconstruction matmul; the LUT path
            # the single-chip index keeps for manhattan/hamming has no mesh
            # twin, and silently-wrong distances are worse than an error
            raise vi.ConfigValidationError(
                f"pq on hnsw_tpu_mesh supports l2-squared/dot/cosine, "
                f"not {self.metric}")
        if self.live == 0:
            raise RuntimeError("compress requires imported vectors to fit on")
        host = np.asarray(self._store, dtype=np.float32)  # [cap, D] gather
        occupied = (self._slot_to_doc >= 0) & ~self._host_tombs
        pq = ProductQuantizer(
            dim=self.dim,
            segments=self.config.pq.segments,
            centroids=self.config.pq.centroids,
            metric=self.metric,
            encoder=self.config.pq.encoder.type,
            distribution=self.config.pq.encoder.distribution,
            rotation=self.config.pq.rotation,
        )
        pq.fit(host[occupied])
        self._enable_pq(pq, host, save=True)

    def _obtain_pq4(self, pq, vecs_n: np.ndarray):
        """The 4-bit rung's quantizer: prefer the persisted pq4.npz during
        restore (deterministic across restarts, skips the kmeans fit); any
        rejected/unreadable file only costs a refit with the pinned
        rotation, never the shard (the pq.npz rejection idiom)."""
        from weaviate_tpu.compress.pq import ProductQuantizer

        if self._restoring and self._pq4_path and os.path.exists(self._pq4_path):
            try:
                pq4 = ProductQuantizer.load(self._pq4_path)
                if pq4.segments == pq.segments and pq4.centroids == 16:
                    return pq4
                import logging

                logging.getLogger(__name__).warning(
                    "persisted pq4.npz does not match the pq config "
                    "(segments %d vs %d, centroids %d); refitting",
                    pq4.segments, pq.segments, pq4.centroids)
            except Exception as e:  # noqa: BLE001 — refit beats a dead shard
                import logging

                logging.getLogger(__name__).warning(
                    "could not load persisted pq4.npz (%s); refitting", e)
        pq4 = ProductQuantizer(
            dim=self.dim,
            segments=pq.segments,
            centroids=16,
            metric=self.metric,
            encoder=vi.PQ_ENCODER_KMEANS,
            distribution=self.config.pq.encoder.distribution,
            rotation=vi.PQ_ROTATION_NONE,
        )
        pq4.fit(vecs_n, rotation_matrix=pq.rotation_matrix)
        return pq4

    def _enable_pq(self, pq, host: np.ndarray, save: bool) -> None:
        """Shard codes + ||recon||^2 over the mesh. Dead/padding rows encode
        garbage but are masked by tombs/high-water in the kernel. The store
        itself stays resident as the per-chip rescore source, downcast to
        bf16 when it was f32 (the single-chip index's drop-the-float-cache
        memory move, mesh-shaped); the full-precision rows move to host RAM
        so compact()'s log rewrite never re-persists bf16-rounded data
        (tpu.py _host_vecs parity)."""
        t0 = time.perf_counter()
        codes = pq.encode(host)                       # [cap, M]
        norms = pq.recon_sq_norms(codes).astype(np.float32)
        self._pq = pq
        self._codes = jax.device_put(jnp.asarray(codes), shard_spec(self.mesh, None))
        self._recon_norms = jax.device_put(jnp.asarray(norms), shard_spec(self.mesh))
        if int(getattr(self.config.pq, "bits", 8)) == 4:
            # the 4-bit rung: a second 16-centroid quantizer fit in the
            # SAME rotated space (the 8-bit fit's OPQ matrix is pinned, so
            # Procrustes runs once and both ladders rank identically under
            # rotation) — per-chip funnel scans its packed slab at M/2
            # bytes/row, stage 2 re-ranks against these very 8-bit codes
            occupied = (self._slot_to_doc >= 0) & ~self._host_tombs
            pq4 = self._obtain_pq4(pq, host[occupied])
            codes4 = pq4.encode(host)
            packed4 = pq_pack_codes4(codes4)
            norms4 = pq4.recon_sq_norms(codes4).astype(np.float32)
            self._pq4 = pq4
            self._codes4 = jax.device_put(
                jnp.asarray(packed4), shard_spec(self.mesh, None))
            self._recon_norms4 = jax.device_put(
                jnp.asarray(norms4), shard_spec(self.mesh))
            self._opq_rot_dev = (
                jax.device_put(jnp.asarray(pq4.rotation_matrix, jnp.float32),
                               replicated(self.mesh))
                if pq4.rotation_matrix is not None else None)
        else:
            self._pq4 = None
            self._codes4 = None
            self._recon_norms4 = None
            self._opq_rot_dev = None
        self._host_vecs = np.array(host, dtype=np.float32)
        if self.dtype == jnp.float32:
            self.dtype = jnp.bfloat16
            # module-level jitted downcast (sharding propagates from the
            # input); re-jitting a lambda here would compile per call
            self._store = jax.device_put(
                _downcast_bf16(self._store), shard_spec(self.mesh, None))
        self.compressed = True
        # compressed mode has no IVF tier (parity with the PQ tiers owning
        # the scan); drop any clustering so snapshots don't carry it
        self._ivf_reset()
        self._staged_gen += 1
        self._mark_staged()
        if save and self._pq_path:
            pq.save(self._pq_path)
        if save and self._pq4_path and self._pq4 is not None:
            self._pq4.save(self._pq4_path)
        led = memory.get_ledger()
        if led is not None:
            led.note_write(
                "compress", "compress", (time.perf_counter() - t0) * 1000.0,
                rows=self.live, bytes_moved=memory.array_bytes(self._codes))
        self._stamp_memory()

    # -- VectorIndex ---------------------------------------------------------

    def add(self, doc_id: int, vector: np.ndarray) -> None:
        with self._lock:
            self._stage_add(int(doc_id), vector)

    def add_batch(self, doc_ids: Sequence[int], vectors: np.ndarray) -> None:
        """Bulk import: fresh unique doc_ids take the fully-vectorized
        balanced-write path; collisions fall back to per-row staging."""
        doc_arr = np.asarray(doc_ids, dtype=np.int64)
        vectors = np.asarray(vectors, dtype=np.float32)
        with self._lock:
            collides = any(int(d) in self._doc_to_row for d in doc_arr) or bool(
                self._pending
            )
            fresh = (
                not collides
                and vectors.ndim == 2
                and np.unique(doc_arr).size == doc_arr.size
            )
            if not fresh:
                for d, v in zip(doc_arr, vectors):
                    self._stage_add(int(d), v)
                return
            if self.metric == vi.DISTANCE_COSINE:
                norms = np.linalg.norm(vectors, axis=1, keepdims=True)
                norms[norms == 0] = 1.0
                vectors = vectors / norms
            if self.dim is None:
                self._init_device(int(vectors.shape[1]))
            elif vectors.shape[1] != self.dim:
                raise ValueError(
                    f"dim mismatch: index has {self.dim}, got {vectors.shape[1]}"
                )
            if self._log is not None and not self._restoring:
                self._log.append_add_batch(doc_arr, vectors)
            self._write_balanced(doc_arr, vectors)
            self.live += doc_arr.size
            self._staged_gen += 1
            self._mark_staged()

    def delete(self, *doc_ids: int) -> None:
        with self._lock:
            for d in doc_ids:
                self._stage_delete(int(d))

    def contains(self, doc_id: int) -> bool:
        with self._lock:
            return doc_id in self._doc_to_row or doc_id in self._pending

    def __len__(self) -> int:
        return self.live

    def distancer_name(self) -> str:
        return self.metric

    def _prep_queries(self, vectors: np.ndarray) -> tuple[np.ndarray, int]:
        q = np.asarray(vectors, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        b = q.shape[0]
        if self.metric == vi.DISTANCE_COSINE:
            norms = np.linalg.norm(q, axis=1, keepdims=True)
            norms[norms == 0] = 1.0
            q = q / norms
        bb = _bucket_b(b)
        if bb != b:
            q = np.concatenate([q, np.zeros((bb - b, q.shape[1]), np.float32)])
        return q, b

    def padded_width(self, b: int) -> int:
        """The query-batch bucket `b` pads to — the coalescer packs lanes
        up to this width for free (same contract as the single-chip twin)."""
        return _bucket_b(max(int(b), 1))

    def _allow_words(self, snap: MeshSnapshot, allow_list: AllowList) -> jax.Array:
        """Sharded packed filter words for `snap`, cached ON the (immutable)
        allowList per index state — same contract as the single-chip twin
        (index/tpu.py _allow_words). Keyed on (allow_token, n_total, cap):
        deletions alone don't rotate the key, but a stale mask only
        re-admits tombstoned rows the device tomb mask kills anyway."""
        from weaviate_tpu.storage.bitmap import (
            Bitmap, allowed_mask, pack_allow_words)

        cap = snap.n_dev * snap.n_loc
        key = (snap.allow_token, snap.n_total, cap)
        cached = getattr(allow_list, "_words_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        mask = np.zeros(cap, dtype=bool)
        occupied = (snap.slot_to_doc >= 0) & ~snap.host_tombs
        if occupied.any():
            docs = snap.slot_to_doc[occupied]
            if isinstance(allow_list, Bitmap):
                mask[occupied] = allowed_mask(allow_list, docs)
            else:
                mask[occupied] = allow_list.contains_array(docs.astype(np.uint64))
        out = jax.device_put(
            jnp.asarray(pack_allow_words(mask, cap)), shard_spec(self.mesh))
        try:
            allow_list._words_cache = (key, out)
        except AttributeError:
            pass
        return out

    # -- snapshot plane (docs/mesh_serving.md) -------------------------------

    def _mark_staged(self) -> None:
        """Stamp the first staging moment of the current unpublished batch
        (ledger publish-lag attribution; no-op when the ledger is down)."""
        if self._staged_t0 is None and memory.get_ledger() is not None:
            self._staged_t0 = time.perf_counter()

    def _publish_snapshot(self) -> None:
        """Build and atomically publish a MeshSnapshot. Caller holds _lock."""
        if self._ivf_dirty:
            self._ivf_rebuild_buckets()
        self._snap_gen += 1
        self._snap = MeshSnapshot(self._snap_gen, self)
        self._published_gen = self._staged_gen
        m = self.metrics
        if m is not None:
            m.index_snapshot_gen.labels(*self._metric_labels()).set(
                self._snap_gen)
        self._stamp_memory()
        led = memory.get_ledger()
        if led is not None and self._staged_t0 is not None:
            led.note_publish(
                (time.perf_counter() - self._staged_t0) * 1000.0)
        self._staged_t0 = None

    def _read_snapshot(self) -> MeshSnapshot:
        """Current MeshSnapshot, lock-free when nothing is staged: one
        reference load + one generation compare. Staged writes take the
        slow path — drain staging under the lock, republish, serve."""
        snap = self._snap
        if snap is not None and self._published_gen == self._staged_gen:
            self._read_local.lock_wait_ms = 0.0
            return snap
        t0 = time.perf_counter()
        with self._lock:
            wait_ms = (time.perf_counter() - t0) * 1000.0
            self._flush_pending()
            if self._snap is None or self._published_gen != self._staged_gen:
                self._publish_snapshot()
            snap = self._snap
        self._read_local.lock_wait_ms = wait_ms
        m = self.metrics
        if m is not None:
            m.index_lock_wait.labels(*self._metric_labels()).observe(wait_ms)
        return snap

    def pop_read_lock_wait(self) -> float:
        """Lock wait of the calling thread's last snapshot read, then 0."""
        w = getattr(self._read_local, "lock_wait_ms", 0.0)
        self._read_local.lock_wait_ms = 0.0
        return w

    @property
    def snapshot_gen(self) -> int:
        snap = self._snap
        return snap.gen if snap is not None else 0

    def _track_inflight(self, delta: int) -> None:
        with self._inflight_lock:
            self._inflight += delta
            n = self._inflight
        m = self.metrics
        if m is None:
            return
        g = self._inflight_gauge
        if g is None:
            g = m.index_inflight_dispatches.labels(*self._metric_labels())
            self._inflight_gauge = g
        g.set(n)

    def pop_dispatch_shape(self):
        """The DispatchShape of the calling thread's last dispatch (serving
        layer hands it to the perf tracer), then None."""
        shape = getattr(self._read_local, "dispatch_shape", None)
        self._read_local.dispatch_shape = None
        return shape

    def pop_audit_snapshot(self):
        """The snapshot the calling thread's last dispatch answered from
        (set only while the quality auditor is up), then None."""
        snap = getattr(self._read_local, "audit_snap", None)
        self._read_local.audit_snap = None
        return snap

    # -- IVF plane (per-device KScaNN buckets, shared codebook) --------------

    def _ivf_nlist(self, s, n: int) -> int:
        if s.nlist > 0:
            return max(1, min(s.nlist, max(n // 8, 1)))
        target = 2 ** int(math.ceil(math.log2(max(n / 256.0, 16.0))))
        return int(max(16, min(target, 4096, max(n // 32, 16))))

    def _ivf_maybe_train(self) -> None:
        """Train/retrain the shared k-means codebook when warranted. Called
        from flush() AFTER the lock is released — the training fetch and
        fit run against a pinned snapshot, never under the index lock."""
        s = ivf_settings()
        if (
            s is None
            or self._restoring
            or self.compressed
            or self.dim is None
            or self.metric not in ivf_ops.MATMUL_METRICS
            or self.live < max(s.min_n, 256)
        ):
            return
        if (self._ivf_centroids_host is not None
                and self.live < self._ivf_trained_n * (1.0 + s.retrain_growth)):
            return
        self._ivf_train(s)

    def _ivf_train(self, s) -> None:
        """Off-lock (re)clustering: pin a snapshot, fetch + fit outside the
        lock while concurrent writes queue into _ivf_backlog, then install
        under the lock iff the device epoch is unchanged."""
        snap = self._read_snapshot()
        if snap.dim is None or snap.n_total == 0:
            return
        epoch = self._device_epoch
        with self._lock:
            if self._ivf_backlog is not None:
                return  # another trainer is in flight
            self._ivf_backlog = []
        t0 = time.perf_counter()
        try:
            # maintenance fetch, off-lock, against the pinned snapshot
            src = np.asarray(snap.store, dtype=np.float32)
            slots = []
            for dev in range(snap.n_dev):
                base = dev * snap.n_loc
                sl = np.arange(base, base + int(snap.counts[dev]))
                slots.append(sl[~snap.host_tombs[sl]])
            rows = src[np.concatenate(slots)] if slots else src[:0]
            n = rows.shape[0]
            if n < 2:
                return
            nlist = self._ivf_nlist(s, n)
            cent = ivf_ops.kmeans_fit(
                rows, nlist, iters=s.train_iters, seed=self._ivf_gen,
                sample=min(len(rows), max(s.train_sample, nlist * 16)))
            if self.metric == vi.DISTANCE_COSINE:
                nrm = np.linalg.norm(cent, axis=1, keepdims=True)
                nrm[nrm == 0] = 1.0
                cent = cent / nrm
            # one shared spill capacity across devices so the per-device
            # balanced assignments stack into one sharded bucket table
            max_per = max((int(sl.size) for sl in slots), default=0)
            cap_t = int(ivf_ops.bucket_capacity(
                np.array([int(1.25 * max_per / nlist) + 1])))
            a_snap = np.full(snap.n_dev * snap.n_loc, -1, dtype=np.int32)
            off = 0
            for sl in slots:
                if sl.size:
                    a_snap[sl] = ivf_ops.balanced_assign(
                        rows[off:off + sl.size], cent, cap_t)
                off += sl.size
            with self._lock:
                if (self._device_epoch != epoch or self.dim != snap.dim
                        or self.n_loc < snap.n_loc):
                    return  # slabs were re-created under us: abandon
                assign = np.full(self.n_dev * self.n_loc, -1, dtype=np.int32)
                for dev in range(snap.n_dev):
                    assign[dev * self.n_loc:
                           dev * self.n_loc + snap.n_loc] = a_snap[
                        dev * snap.n_loc:(dev + 1) * snap.n_loc]
                for g, r in self._ivf_backlog:
                    assign[g] = ivf_ops.assign_partitions(
                        np.asarray(r, np.float32), cent)
                self._ivf_backlog = None
                self._ivf_assign = assign
                self._ivf_centroids_host = cent
                self._ivf_centroids = jax.device_put(
                    jnp.asarray(cent), shard_spec(self.mesh))
                self._ivf_cap_p = cap_t
                self._ivf_trained_n = n
                self._ivf_gen += 1
                self._ivf_dirty = True
                self._staged_gen += 1
                self._mark_staged()
                self._stamp_memory()
            led = memory.get_ledger()
            if led is not None:
                led.note_write(
                    "ivf", "recluster",
                    (time.perf_counter() - t0) * 1000.0, rows=n)
        finally:
            with self._lock:
                bl, self._ivf_backlog = self._ivf_backlog, None
                if bl and self._ivf_centroids_host is not None:
                    # install aborted after writes queued: classify the
                    # leftovers against whatever codebook is current
                    for g, r in bl:
                        self._ivf_assign[g] = ivf_ops.assign_partitions(
                            np.asarray(r, np.float32),
                            self._ivf_centroids_host)
                    self._ivf_dirty = True

    def _ivf_rebuild_buckets(self) -> None:
        """Rebuild the sharded [n_dev, nlist, cap_p] bucket table from the
        per-row assignments. Caller holds _lock (publish path)."""
        cent = self._ivf_centroids_host
        if cent is None or self.dim is None:
            self._ivf_dirty = False
            return
        nlist = cent.shape[0]
        per_dev = []
        for dev in range(self.n_dev):
            a = self._ivf_assign[dev * self.n_loc:(dev + 1) * self.n_loc].copy()
            a[self._host_tombs[dev * self.n_loc:(dev + 1) * self.n_loc]] = -1
            per_dev.append(a)
        fills = np.stack([
            np.bincount(a[a >= 0], minlength=nlist) for a in per_dev])
        # shared capacity: never below what any device needs, never below
        # the training-time spill cap (keeps the table shape monotonic)
        cap_shared = max(int(ivf_ops.bucket_capacity(fills.reshape(-1))),
                         int(self._ivf_cap_p or 0))
        bkt = np.stack([
            ivf_ops.build_buckets(a, nlist, cap_shared)[0] for a in per_dev])
        self._ivf_buckets = jax.device_put(
            jnp.asarray(bkt), shard_spec(self.mesh, None, None))
        self._ivf_fills = fills
        self._ivf_cap_p = cap_shared
        self._ivf_meta = (nlist, cap_shared, self._ivf_gen)
        self._ivf_dirty = False
        self._stamp_memory()

    def _ivf_reset(self) -> None:
        """Drop the clustering (compact/compress/drop paths)."""
        self._ivf_centroids_host = None
        self._ivf_centroids = None
        self._ivf_buckets = None
        self._ivf_assign = np.zeros(0, dtype=np.int32)
        self._ivf_fills = None
        self._ivf_cap_p = 0
        self._ivf_meta = None
        self._ivf_dirty = False
        self._ivf_trained_n = 0

    def ivf_stats(self) -> dict:
        with self._ivf_lock:
            st = dict(self._ivf_stats)
        st["probed_fraction"] = (
            round(st["probed_rows"] / st["base_rows"], 4)
            if st["base_rows"] else None
        )
        return st

    def _ivf_plan(self, snap: MeshSnapshot, k: int) -> Optional[int]:
        """-> effective top_p when the partition-pruned tier applies to
        this snapshot, else None (full scan)."""
        if (snap.ivf_buckets is None or snap.ivf_meta is None
                or snap.compressed):
            return None
        s = ivf_settings()
        if s is None or self.metric not in ivf_ops.MATMUL_METRICS:
            return None
        nlist, cap_p, _gen = snap.ivf_meta
        req = s.top_p if s.top_p > 0 else max(1, nlist // 16)
        req = min(req, nlist)
        eff = max(1, min(req, controller.ivf_top_p_cap(req)))
        if eff < nlist:
            eff = min(_snap_top_p(eff), nlist)
        while eff < nlist and eff * cap_p < 4 * k:
            nxt = _snap_top_p(min(eff * 2, nlist))
            eff = nlist if nxt <= eff else nxt
        return eff

    def _funnel_budgets(self, k: int, n: int):
        """Controller-guarded funnel budgets, mesh-shaped: same ladder
        caps as the single-chip index (index/tpu.py _funnel_budgets), but
        planned against the PER-SHARD slab (n = n_loc) — each chip funnels
        its own rows, so the whole-mesh candidate pool is n_dev x rg4*16.
        The no-starvation floors mirror _rescore_r: the controller may
        only cut work, never break top-k coverage."""
        from weaviate_tpu.ops import pq4 as pq4_ops

        c_top = PQ4_FUNNEL_C_BUCKETS[-1]
        rc_top = PQ4_FUNNEL_RESCORE_BUCKETS[-1]
        c_cap = controller.funnel_c_cap(c_top)
        rc_cap = controller.funnel_rescore_cap(rc_top)
        if c_cap < 4 * k:
            c_cap = c_top
        if rc_cap < 2 * k:
            rc_cap = rc_top
        return pq4_ops.plan_funnel(k, n, c_cap, rc_cap)

    # -- search dispatch (two-phase: enqueue on the snapshot, fetch later) ---

    def dispatch_tier(self, snap: MeshSnapshot,
                      allow_list: Optional[AllowList] = None) -> str:
        """The tier a dispatch against `snap` takes (quality auditor
        attribution). The mesh has no gather tier — small filtered reads
        still run the full sharded scan."""
        if snap.compressed:
            if snap.codes4 is not None and snap.pq4 is not None:
                return TIER_PQ_ADC4
            return TIER_PQ_RESCORE if self.config.pq.rescore else TIER_PQ_CODES
        return TIER_EXACT

    def _dispatch_search(self, snap: MeshSnapshot, vectors: np.ndarray,
                         k: int, allow_list: Optional[AllowList] = None):
        """Enqueue ONE whole-mesh program against `snap` and return the
        finalize closure. The program runs per-shard scan -> local top-k ->
        all-gather -> final select -> on-device slot->doc translation, so
        finalize is one packed fetch + dtype views (the JGL015 one-fetch /
        zero-translation invariant, across chips). No locks anywhere."""
        if snap.dim is None or snap.live == 0 or snap.n_total == 0:
            b = 1 if np.asarray(vectors).ndim == 1 else len(vectors)
            empty = (np.zeros((b, 0), dtype=np.uint64),
                     np.zeros((b, 0), dtype=np.float32))
            return lambda: empty
        faults.fire("index.mesh.dispatch")
        shape = None
        t_enq0 = 0.0
        if tracing.get_tracer() is not None:
            t_enq0 = time.perf_counter()
        q, b = self._prep_queries(vectors)
        chunk = min(snap.n_loc, _MESH_SCAN_CHUNK)
        kk = max(1, min(k, snap.live, chunk))
        use_allow = allow_list is not None
        words = self._allow_words(snap, allow_list) if use_allow else snap.zero_words
        fused = fused_dispatch_enabled()
        exact = getattr(self.config, "exact_topk", False)

        if snap.compressed:
            rescore = self.config.pq.rescore
            packed_dev = None
            funnel_budgets = None
            if snap.codes4 is not None and snap.pq4 is not None:
                # the 4-bit rung: per-chip three-stage funnel (nibble scan
                # -> 8-bit ADC re-rank -> exact rescore against the chip's
                # own store slab), budgets recall-guarded per shard
                from weaviate_tpu.ops import pq4 as pq4_ops
                from weaviate_tpu.ops import pq_gmin

                rg4, rc = self._funnel_budgets(kk, snap.n_loc)
                if rc >= kk:
                    _, flat_cb8 = pq_gmin.cached_cb_constants(self)
                    packed_dev = mesh_search_pq4_step(
                        snap.codes4,
                        snap.codes,
                        snap.recon_norms4,
                        snap.recon_norms,
                        snap.tombs,
                        snap.counts_dev,
                        words,
                        snap.pq4._dev_codebook(),
                        flat_cb8,
                        snap.store,
                        jnp.asarray(q),
                        snap.pq4.rotation_dev(),
                        snap.slot_to_doc_dev,
                        kk,
                        self.metric,
                        use_allow,
                        rg4,
                        rc,
                        exact,
                        fused,
                        self.mesh,
                    )
                    funnel_budgets = (rg4, rc)
            if packed_dev is None and not rescore:
                # codes-only tier: try the fused per-shard ADC kernel
                # (mesh twin of the single-chip pq_gmin dispatch)
                packed_dev = self._pq_gmin_step_or_none(
                    snap, q, kk, words, use_allow, fused)
            if packed_dev is None:
                nchunks_eff = max(1, snap.n_loc // chunk)
                pool_target = self.config.pq.rescore_limit or 1024
                r_chunk = min(
                    max(2 * kk, -(-pool_target // nchunks_eff), 64), 256, chunk)
                # the concatenated per-chip pool must cover k (tpu.py:1080)
                r_chunk = max(r_chunk, min(-(-kk // nchunks_eff), chunk))
                packed_dev = mesh_search_pq_step(
                    snap.codes,
                    snap.recon_norms,
                    snap.tombs,
                    snap.counts_dev,
                    words,
                    snap.pq._dev_codebook(),
                    snap.store,
                    jnp.asarray(q),
                    snap.pq.rotation_dev(),
                    snap.slot_to_doc_dev,
                    kk,
                    r_chunk,
                    self.metric,
                    use_allow,
                    exact,
                    rescore,
                    fused,
                    self.mesh,
                )
            if t_enq0:
                if funnel_budgets is not None:
                    rg4_s, rc_s = funnel_budgets
                    shape = DispatchShape(
                        TIER_PQ_ADC4, n=snap.n_total, dim=snap.dim, batch=b,
                        batch_padded=q.shape[0],
                        bytes_per_row=snap.pq4.segments // 2,
                        k=int(kk), ndev=snap.n_dev,
                        extra={
                            # per-shard budgets x n_dev: whole-dispatch
                            # survivor counts (bytes() attributes stages
                            # 2/3 per batch row, costmodel.py)
                            "funnel_c": rg4_s * 16 * snap.n_dev,
                            "funnel_rescore": rc_s * snap.n_dev,
                            "funnel_stage2_bytes_per_row": snap.pq.segments,
                            "funnel_stage3_bytes_per_row":
                                snap.dim * snap.store.dtype.itemsize,
                        })
                else:
                    shape = DispatchShape(
                        TIER_PQ_RESCORE if rescore else TIER_PQ_CODES,
                        n=snap.n_total, dim=snap.dim, batch=b,
                        batch_padded=q.shape[0],
                        bytes_per_row=(snap.dim * snap.store.dtype.itemsize
                                       if rescore else snap.pq.segments),
                        k=int(kk), ndev=snap.n_dev)
        else:
            top_p = self._ivf_plan(snap, kk)
            if top_p is not None:
                nlist, cap_p, _gen = snap.ivf_meta
                gp = ivf_ops.group_steps(q.shape[0], cap_p, snap.dim, top_p)
                packed_dev = mesh_search_ivf_step(
                    snap.store,
                    snap.tombs,
                    snap.counts_dev,
                    words,
                    snap.ivf_centroids,
                    snap.ivf_buckets,
                    jnp.asarray(q),
                    snap.slot_to_doc_dev,
                    kk,
                    self.metric,
                    use_allow,
                    top_p,
                    exact,
                    gp,
                    fused,
                    self.mesh,
                )
                with self._ivf_lock:
                    st = self._ivf_stats
                    st["dispatches"] += 1
                    st["probed_rows"] += snap.n_dev * top_p * cap_p
                    st["base_rows"] += int(snap.n_total)
                if t_enq0:
                    probed = snap.n_dev * top_p * cap_p + nlist
                    shape = DispatchShape(
                        TIER_EXACT, n=probed, dim=snap.dim, batch=b,
                        batch_padded=q.shape[0],
                        bytes_per_row=snap.dim * snap.store.dtype.itemsize,
                        k=int(kk), ndev=snap.n_dev,
                        extra={"ivf": True, "ivf_top_p": top_p,
                               "ivf_nlist": nlist,
                               "probed_fraction": round(
                                   min(probed / max(snap.n_total, 1), 1.0), 4)})
            else:
                packed_dev = self._gmin_step_or_none(
                    snap, q, kk, words, use_allow, fused)
                if packed_dev is None:
                    packed_dev = mesh_search_step(
                        snap.store,
                        snap.sq_norms,
                        snap.tombs,
                        snap.counts_dev,
                        words,
                        jnp.asarray(q),
                        snap.slot_to_doc_dev,
                        kk,
                        self.metric,
                        use_allow,
                        self.metric == vi.DISTANCE_L2,
                        exact,
                        fused,
                        self.mesh,
                    )
                if t_enq0:
                    shape = DispatchShape(
                        TIER_EXACT, n=snap.n_total, dim=snap.dim, batch=b,
                        batch_padded=q.shape[0],
                        bytes_per_row=snap.dim * snap.store.dtype.itemsize,
                        k=int(kk), ndev=snap.n_dev)

        if shape is not None:
            shape.t_start = t_enq0
            shape.enqueue_ms = (time.perf_counter() - t_enq0) * 1000.0
            if fused:
                shape.fused = True
                shape.translate_ms = 0.0
            self._read_local.dispatch_shape = shape
        if quality.get_auditor() is not None:
            self._read_local.audit_snap = snap  # graftflow: disable=JGL018 TLS pin by design: at most one snapshot per serving thread, overwritten on the next sampled dispatch — the shadow audit must re-read the SAME snapshot the live dispatch answered from
        self._track_inflight(1)
        done = [False]
        slot_to_doc = snap.slot_to_doc

        def finish():
            packed = _fetch_packed(packed_dev, shape)
            if fused:
                ids, dists = unpack_fused(packed)
                return ids[:b], dists[:b]
            top, idx = unpack_topk(packed)
            top = top[:b]
            idx = idx[:b]
            t0 = time.perf_counter() if shape is not None else 0.0
            ids = np.where(idx >= 0, slot_to_doc[np.clip(idx, 0, None)], -1)
            if shape is not None:
                shape.translate_ms = (time.perf_counter() - t0) * 1000.0
            return ids.astype(np.uint64), top.astype(np.float32)

        def finalize():
            try:
                faults.fire("index.mesh.finalize")
                if shape is None:
                    return finish()
                if shape.fetches:
                    shape.fetches = 0  # a retried finalize re-counts
                t0 = time.perf_counter()
                out = finish()
                t1 = time.perf_counter()
                shape.finalize_ms = (t1 - t0) * 1000.0
                shape.t_end = t1
                return out
            finally:
                if not done[0]:
                    done[0] = True
                    self._track_inflight(-1)

        return finalize

    def search_by_vectors(
        self, vectors: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        snap = self._read_snapshot()
        return self._dispatch_search(snap, vectors, k, allow_list)()

    def search_by_vectors_async(
        self, vectors: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ):
        """Two-phase dispatch for the serving coalescer: enqueue the whole
        sharded program now (lock-free, on the current snapshot), return
        the finalize closure. The coalescer overlaps the next lane's
        enqueue with this lane's device time (pipeline depth 2); filtered
        lanes ride the same path (async_supports_filters)."""
        snap = self._read_snapshot()
        return self._dispatch_search(snap, vectors, k, allow_list)

    # -- fused group-min kernels (guarded; separate failure domains) ---------

    def _gmin_plan(self, b: int, kk: int, snap: Optional[MeshSnapshot] = None):
        """-> (rg, active_g) when the fused mesh kernel is eligible for this
        shape (metric, slab size, VMEM budget), else None. Pure gate — no
        kernel execution — so tests can assert eligibility directly."""
        from weaviate_tpu.ops import gmin_scan

        n_loc = snap.n_loc if snap is not None else self.n_loc
        dim = snap.dim if snap is not None else self.dim
        counts = snap.counts if snap is not None else self._counts
        store = snap.store if snap is not None else self._store
        if getattr(self.config, "exact_topk", False):
            return None  # config opt-out, not degradation
        if self._gmin_broken:
            record_device_fallback("index.mesh.gmin", "degraded", log=False)
            return None
        if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
            return None
        if n_loc < 16384 or b < 8:
            return None
        ncols_l = n_loc // gmin_scan.G
        rg = min(max(32, 2 * kk), 128, ncols_l)
        if rg < kk:
            return None
        active_g = max(1, -(-int(counts.max()) // ncols_l))
        if not gmin_scan.fits_vmem(b, dim, ncols_l, active_g,
                                   store.dtype.itemsize):
            return None
        return rg, active_g

    def _pq_gmin_step_or_none(self, snap: MeshSnapshot, q: np.ndarray,
                              kk: int, words, use_allow: bool, fused: bool):
        """Enqueue the fused per-shard PQ codes kernel, or None for the
        legacy reconstruction scan — separate failure domain
        (self._pqg_state); gating and codebook constants are the shared
        helpers in ops/pq_gmin.py. Returns the guarded result RAW (host
        array on the first validation run, device array after), so the
        async finalize defers the fetch."""
        from weaviate_tpu.parallel.mesh_search import mesh_search_pq_gmin_step

        from weaviate_tpu.ops import gmin_scan, pq_gmin

        ncols_l = snap.n_loc // gmin_scan.G
        active_g = max(1, -(-int(snap.counts.max()) // ncols_l)) if ncols_l else 1
        rg = pq_gmin.eligible_rg(
            self._pqg_state, getattr(self.config, "exact_topk", False),
            self.metric, snap.pq, q.shape[0], ncols_l, kk, snap.dim, active_g,
            component="index.mesh.pq_gmin")
        if rg is None:
            return None
        m, c = snap.pq.segments, snap.pq.centroids
        interpret = jax.default_backend() not in ("tpu", "axon")
        cb_chunks, flat_cb = pq_gmin.cached_cb_constants(self)
        key = ("pq", q.shape[0], kk, rg, active_g, snap.n_loc, m, c,
               use_allow, fused)
        return gmin_scan.guarded_kernel_call(
            self._pqg_state, key,
            lambda: mesh_search_pq_gmin_step(
                snap.codes,
                snap.recon_norms,
                snap.tombs,
                snap.counts_dev,
                words,
                cb_chunks,
                flat_cb,
                jnp.asarray(q),
                snap.pq.rotation_dev(),
                snap.slot_to_doc_dev,
                kk,
                self.metric,
                use_allow,
                rg,
                active_g,
                interpret,
                fused,
                self.mesh,
            ),
            "mesh pq codes kernel", component="index.mesh.pq_gmin")

    def _gmin_step_or_none(self, snap: MeshSnapshot, q: np.ndarray, kk: int,
                           words, use_allow: bool, fused: bool):
        """Enqueue the fused group-min mesh kernel, or None for the legacy
        scan. Validation mirrors tpu.py's _gmin_packed_or_none: per
        compiled shape — a Mosaic rejection on a NEW shape falls back for
        that shape only, a failure on a shape that already served
        propagates, and only repeated distinct-shape failures with zero
        successes disable the path. Returns the guarded result RAW so the
        async finalize defers the fetch."""
        from weaviate_tpu.parallel.mesh_search import mesh_search_gmin_step

        from weaviate_tpu.ops import gmin_scan

        plan = self._gmin_plan(q.shape[0], kk, snap)
        if plan is None:
            return None
        rg, active_g = plan
        key = (q.shape[0], kk, rg, active_g, snap.n_loc, use_allow, fused)
        interpret = jax.default_backend() not in ("tpu", "axon")
        return gmin_scan.guarded_kernel_call(
            self, key,
            lambda: mesh_search_gmin_step(
                snap.store,
                snap.sq_norms,
                snap.tombs,
                snap.counts_dev,
                words,
                jnp.asarray(q),
                snap.slot_to_doc_dev,
                kk,
                self.metric,
                use_allow,
                self.metric == vi.DISTANCE_L2,
                rg,
                active_g,
                interpret,
                fused,
                self.mesh,
            ),
            "mesh gmin kernel", component="index.mesh.gmin")

    # -- host fallback plane (breaker-degraded serving + shadow audits) ------

    def _snap_prefix_slots(self, snap: MeshSnapshot) -> np.ndarray:
        """Global row ids of every written slot in `snap`, slab order —
        the per-device counts prefixes concatenated. Includes tombstoned
        rows (masked by the caller), matching the single-chip convention
        that host_rows covers the full high-water prefix."""
        if snap.dim is None or snap.n_total == 0:
            return np.zeros(0, dtype=np.int64)
        return np.concatenate([
            np.arange(dev * snap.n_loc,
                      dev * snap.n_loc + int(snap.counts[dev]))
            for dev in range(snap.n_dev)
        ])

    def host_rows(self, snap: MeshSnapshot) -> tuple[np.ndarray, np.ndarray]:
        """(rows f32 [n, D], sq_norms f32 [n]) for `snap`'s written slots —
        the quality auditor's ground-truth source. Compressed mode serves
        the full-precision host copy (the device store is bf16 by then)."""
        slots = self._snap_prefix_slots(snap)
        if snap.compressed and snap.host_vecs is not None:
            rows = snap.host_vecs[slots]
        else:
            rows = np.asarray(snap.store, dtype=np.float32)[slots]
        sq = np.einsum("ij,ij->i", rows, rows, dtype=np.float32)
        return rows, sq

    def _host_fallback_rows(self, snap: MeshSnapshot):
        """Generation-keyed single-entry cache of host_rows for the breaker
        path — one fetch per snapshot generation while degraded."""
        cached = self._host_rows_cache
        if cached is not None and cached[0] == snap.gen:
            return cached[1], cached[2]
        rows, sq = self.host_rows(snap)
        self._host_rows_cache = (snap.gen, rows, sq)  # graftflow: disable=JGL018 generation-keyed single-entry cache with an explicit release (release_host_fallback_cache on breaker recovery); outliving the snapshot is the point
        return rows, sq

    def release_host_fallback_cache(self) -> None:
        """Drop the breaker-path row cache (called on breaker recovery)."""
        self._host_rows_cache = None

    def search_by_vectors_host(
        self, vectors: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Pure-host scan over the current snapshot (the breaker's degraded
        serving path; bit-compatible contract with the device scan)."""
        snap = self._read_snapshot()
        if snap.dim is None or snap.n_total == 0 or snap.live == 0:
            b = 1 if np.asarray(vectors).ndim == 1 else len(vectors)
            return (np.zeros((b, 0), dtype=np.uint64),
                    np.zeros((b, 0), dtype=np.float32))
        rows, sq = self._host_fallback_rows(snap)
        return self._host_search_snap(snap, vectors, k, allow_list, rows, sq)

    def search_by_vectors_host_pinned(
        self, snap: MeshSnapshot, vectors: np.ndarray, k: int,
        allow_list: Optional[AllowList] = None, rows=None, sq_norms=None,
        deadline: Optional[float] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Host scan against a PINNED snapshot (quality auditor: the shadow
        re-execution must read the exact state the live dispatch saw)."""
        if snap.dim is None or snap.n_total == 0 or snap.live == 0:
            b = 1 if np.asarray(vectors).ndim == 1 else len(vectors)
            return (np.zeros((b, 0), dtype=np.uint64),
                    np.zeros((b, 0), dtype=np.float32))
        if rows is None or sq_norms is None:
            rows, sq_norms = self.host_rows(snap)
        return self._host_search_snap(
            snap, vectors, k, allow_list, rows, sq_norms, deadline)

    def _host_search_snap(self, snap: MeshSnapshot, vectors, k, allow_list,
                          rows, row_sq, deadline: Optional[float] = None):
        from weaviate_tpu.storage.bitmap import Bitmap, allowed_mask

        q = np.asarray(vectors, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if self.metric == vi.DISTANCE_COSINE:
            norms = np.linalg.norm(q, axis=1, keepdims=True)
            norms[norms == 0] = 1.0
            q = q / norms
        slots = self._snap_prefix_slots(snap)
        live = ~snap.host_tombs[slots]
        docs = snap.slot_to_doc[slots]
        if allow_list is not None:
            if isinstance(allow_list, Bitmap):
                live = live & allowed_mask(allow_list, docs)
            else:
                live = live & allow_list.contains_array(docs.astype(np.uint64))
        n = slots.size
        n_live = int(live.sum())
        if n_live == 0:
            return (np.zeros((q.shape[0], 0), dtype=np.uint64),
                    np.zeros((q.shape[0], 0), dtype=np.float32))
        q_sq = (q ** 2).sum(1)[:, None] if self.metric == vi.DISTANCE_L2 else None
        chunk = (4096 if self.metric in (vi.DISTANCE_MANHATTAN,
                                         vi.DISTANCE_HAMMING)
                 else self._HOST_SCAN_CHUNK)
        d = np.empty((q.shape[0], n), dtype=np.float32)
        for s in range(0, n, chunk):
            if deadline is not None and time.perf_counter() > deadline:
                raise quality.AuditDeadlineExceeded(
                    f"host scan over audit budget at row {s}/{n}")
            e = min(s + chunk, n)
            blk = rows[s:e]
            if self.metric == vi.DISTANCE_L2:
                qx = q @ blk.T
                d[:, s:e] = np.maximum(
                    q_sq - 2.0 * qx + row_sq[s:e][None, :], 0.0)
            elif self.metric == vi.DISTANCE_DOT:
                d[:, s:e] = -(q @ blk.T)
            elif self.metric == vi.DISTANCE_COSINE:
                d[:, s:e] = 1.0 - q @ blk.T
            elif self.metric == vi.DISTANCE_MANHATTAN:
                d[:, s:e] = np.abs(q[:, None, :] - blk[None, :, :]).sum(-1)
            else:
                d[:, s:e] = (q[:, None, :] != blk[None, :, :]).sum(-1)
        d[:, ~live] = np.inf
        kk = min(max(int(k), 1), n_live)
        idx = np.argpartition(d, kk - 1, axis=1)[:, :kk]
        top = np.take_along_axis(d, idx, axis=1)
        order = np.argsort(top, axis=1, kind="stable")
        top = np.take_along_axis(top, order, axis=1)
        idx = np.take_along_axis(idx, order, axis=1)
        ids = np.where(np.isinf(top), -1, docs[idx])
        return ids.astype(np.uint64), top.astype(np.float32)

    # -- health (GET /debug/index parity with TpuVectorIndex) ----------------

    def _ivf_health(self) -> dict:
        s = ivf_settings()
        out: dict = {
            "enabled": s is not None,
            "trained": self._ivf_centroids_host is not None,
        }
        if self._ivf_centroids_host is not None:
            nlist, cap_p, gen = self._ivf_meta or (
                self._ivf_centroids_host.shape[0], self._ivf_cap_p or 0,
                self._ivf_gen)
            out.update({"nlist": int(nlist), "cap_p": int(cap_p),
                        "gen": int(gen), "trained_n": self._ivf_trained_n,
                        "pca_dim": 0})
            fills = self._ivf_fills
            if fills is not None:
                flat = fills.reshape(-1)
                mean = float(flat.mean()) if flat.size else 0.0
                total = int(flat.sum())
                out["buckets"] = {
                    "fill_min": int(flat.min()) if flat.size else 0,
                    "fill_mean": round(mean, 1),
                    "fill_max": int(flat.max()) if flat.size else 0,
                    "empty": int((flat == 0).sum()),
                    "padding_waste": round(
                        1.0 - total / max(flat.size * cap_p, 1), 4),
                    "imbalance": (round(float(flat.max()) / mean, 2)
                                  if mean > 0 else None),
                    "fill_histogram": np.histogram(
                        flat, bins=8, range=(0, max(cap_p, 1)))[0].tolist(),
                    "per_device_rows": fills.sum(axis=1).tolist(),
                }
        out["probes"] = self.ivf_stats()
        return out

    def health(self) -> dict:
        """Mesh diagnostics for GET /debug/index — same keys as the
        single-chip index plus the per-device breakdown."""
        with self._lock:
            counts = self._counts.copy()
            slots = int(counts.sum())
            tombs = int(self._host_tombs.sum())
            comps = self._memory_components()
            slab_bytes_total = sum(comps.values())
            per_device = []
            for dev in range(self.n_dev):
                sl = slice(dev * self.n_loc, dev * self.n_loc + self.n_loc)
                per_device.append({
                    "device": dev,
                    "rows": int(counts[dev]),
                    "tombstones": int(self._host_tombs[sl].sum())
                    if self._host_tombs.size else 0,
                    "slab_bytes": slab_bytes_total // self.n_dev,
                })
            out = {
                "type": "hnsw_tpu_mesh",
                "metric": self.metric,
                "dim": self.dim,
                "devices": self.n_dev,
                "rows_per_device": self.n_loc,
                "capacity": self.n_dev * self.n_loc,
                "slots": slots,
                "live": self.live,
                "tombstones": tombs,
                "tombstone_fraction": round(tombs / max(slots, 1), 4),
                "pending_adds": len(self._pending),
                "pending_tombstones": len(self._pending_tombs),
                "snapshot_gen": self.snapshot_gen,
                "staged_gen": self._staged_gen,
                "published_gen": self._published_gen,
                "staged_lag": self._staged_gen - max(self._published_gen, 0),
                "per_device": per_device,
                "compressed": self.compressed,
                # rescore=false is the MULTICHIP_r05 footgun: raw ADC
                # distances at recall ~0.24 — surfaced, not just documented
                "pq": None if self._pq is None else {
                    "segments": self._pq.segments,
                    "centroids": self._pq.centroids,
                    "rotation": bool(self.config.pq.rotation),
                    "rescore": bool(self.config.pq.rescore),
                    "code_dtype": str(np.dtype(self._pq.code_dtype)),
                },
                "ivf": self._ivf_health(),
                "host_fallback_cache": {
                    "resident": self._host_rows_cache is not None,
                    "gen": (self._host_rows_cache[0]
                            if self._host_rows_cache is not None else None),
                    "bytes": memory.host_rows_cache_bytes(self),
                },
                "memory": {
                    "device_components": comps,
                    "host_components": memory.index_host_components(self),
                },
            }
        return out

    # -- single-vector entry points ------------------------------------------

    def search_by_vector(
        self, vector: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        ids, dists = self.search_by_vectors(np.asarray(vector)[None, :], k, allow_list)
        keep = dists[0] != np.inf
        return ids[0][keep], dists[0][keep]

    def search_by_vector_distance(
        self,
        vector: np.ndarray,
        target_distance: float,
        max_limit: int,
        allow_list: Optional[AllowList] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Doubling-limit loop (search.go:90-157 semantics)."""
        limit = 64
        while True:
            ids, dists = self.search_by_vector(vector, min(limit, max_limit), allow_list)
            if len(ids) == 0:
                return ids, dists
            beyond = dists > target_distance
            if beyond.any() or len(ids) >= min(max_limit, self.live):
                keep = dists <= target_distance
                return ids[keep][:max_limit], dists[keep][:max_limit]
            if limit >= max_limit:
                return ids[:max_limit], dists[:max_limit]
            limit *= 2

    # -- config / maintenance ------------------------------------------------

    def update_user_config(self, updated: vi.HnswUserConfig) -> None:
        with self._lock:
            vi.validate_config_update(self.config, updated)
            was_enabled = self.config.pq.enabled
            if updated.pq.enabled and not was_enabled:
                # reject what is knowable NOW instead of deferring the
                # failure into the compression trigger
                if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT,
                                       vi.DISTANCE_COSINE):
                    raise vi.ConfigValidationError(
                        f"pq on hnsw_tpu_mesh supports l2-squared/dot/"
                        f"cosine, not {self.metric}")
                if (self.dim is not None and updated.pq.segments > 0
                        and self.dim % updated.pq.segments != 0):
                    raise vi.ConfigValidationError(
                        f"pq.segments ({updated.pq.segments}) must divide "
                        f"vector dims ({self.dim})")
            prev = self.config
            self.config = updated
            # pq.enabled flipped on triggers compression (compress.go)
            if updated.pq.enabled and not was_enabled and not self.compressed:
                try:
                    self._flush_pending()
                    if self.live > 0:
                        self._compress_locked()
                except Exception:
                    # a failed pq-enable must not stick — config or runtime
                    # (an OOM'd kmeans fit): a committed-but-uncompressed
                    # config would re-run the full fit from the flush-path
                    # declarative trigger on every later flush
                    self.config = prev
                    raise

    def flush(self) -> None:
        with self._lock:
            self._flush_pending()
            self._maybe_autocompress()
            if self._log is not None:
                self._log.flush()
        # IVF (re)training fetches + fits OFF the lock, from a pinned
        # snapshot; concurrent writes queue into the backlog
        self._ivf_maybe_train()

    def compact(self) -> None:
        """Condense: drop tombstoned slots, rewrite the log, rebuild balanced
        (condensor.go analog). In-flight dispatches keep their pinned
        snapshots — the rebuild swaps whole slabs, never mutates them."""
        with self._lock:
            self._flush_pending()
            if self.dim is None or not self._doc_to_row:
                return
            total = int(self._counts.sum())
            if len(self._doc_to_row) == total:
                return
            t_compact0 = time.perf_counter()
            rows = np.array(sorted(self._doc_to_row.values()), dtype=np.int64)
            docs = self._slot_to_doc[rows]
            # compressed mode rewrites the log from the f32 host copy — the
            # device store is bf16 by then and must not degrade durable data
            src = self._host_vecs if self.compressed else np.asarray(
                self._store, dtype=np.float32)
            store_host = np.asarray(src, dtype=np.float32)[rows]
            if self._log is not None:
                self._log.rewrite(zip(docs.tolist(), store_host))
            # mapping rebuild invalidates any packed-words cache keyed on it
            self._allow_token = object()
            self._ivf_reset()
            dim = self.dim
            self.dim = None
            self.n_loc = 0
            self.live = 0
            self._counts = np.zeros(self.n_dev, dtype=np.int64)
            self._doc_to_row.clear()
            self._slot_to_doc = np.zeros(0, dtype=np.int64)
            self._store = self._sq_norms = self._tombs = None
            self._s2d_dev = None
            self._host_tombs = np.zeros(0, dtype=bool)
            self._init_device(dim)
            self._restoring = True
            try:
                self.add_batch(docs, store_host)
            finally:
                self._restoring = False
            self._staged_gen += 1
            self._mark_staged()
            led = memory.get_ledger()
            if led is not None:
                led.note_write(
                    "compact", "compact",
                    (time.perf_counter() - t_compact0) * 1000.0,
                    rows=self.live)

    def drop(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.close()
                try:
                    os.remove(self._log.path)
                except FileNotFoundError:
                    pass
                self._log = None
            self._store = self._sq_norms = self._tombs = None
            self._zero_words = None  # sharded device words must free too
            self._s2d_dev = None
            self._codes = self._recon_norms = None
            self._host_vecs = None
            self._pq = None
            self.compressed = False
            if self._pq_path:
                try:
                    os.remove(self._pq_path)
                except FileNotFoundError:
                    pass
            self.dim = None
            self.n_loc = 0
            self.live = 0
            self._counts = np.zeros(self.n_dev, dtype=np.int64)
            self._slot_to_doc = np.zeros(0, dtype=np.int64)
            self._host_tombs = np.zeros(0, dtype=bool)
            self._doc_to_row.clear()
            self._pending.clear()
            self._pending_tombs.clear()
            self._snap = None
            self._host_rows_cache = None
            self._ivf_reset()
            self._device_epoch += 1
            self._staged_gen += 1
            self._stamp_memory()  # zero this index's device components

    def shutdown(self) -> None:
        with self._lock:
            self._flush_pending()
            if self._log is not None:
                self._log.flush()
                self._log.close()

    def list_files(self) -> list[str]:
        out = [self._log.path] if self._log is not None else []
        if self._pq_path and os.path.exists(self._pq_path):
            out.append(self._pq_path)  # backups must carry the codebook
        return out
