"""The TPU-native vector index ("hnsw_tpu" / "flat").

Design (replaces the reference's HNSW hot path, SURVEY.md §2.4):

The reference walks a graph one edge at a time — pop candidate, fetch ~64
neighbor vectors from a RAM cache, run one AVX2 distance per edge, push heaps
(vector/hnsw/search.go:160 searchLayerByVector). That shape is hostile to a
systolic array. The TPU-first restructuring keeps the *interface contract*
(vector_index.go:23-40: (vector, k, allowList) -> (ids, dists)) but makes the
device do what it is good at:

- the shard's vectors live in HBM as one padded [capacity, D] device array
  (the analog of the sharded-lock vector cache, vector_cache.go:47 — except
  the "cache" IS the store and never misses);
- a query batch is ONE [B, N] distance matmul on the MXU + a masked
  k-selection (ops/distances.py, ops/topk.py). Per-chunk selection defaults
  to lax.approx_min_k at recall_target=0.95 (the TPU PartialReduce /ScaNN
  primitive; measured recall 1.0 on the bench workloads, and never below the
  target — comparable to HNSW's >=0.99 fixture bar, recall_test.go:137);
  config exactTopK=true forces lax.top_k for guaranteed recall 1.0;
- tombstones (delete.go semantics) are a device bool mask, filters
  (helpers/allow_list.go) become packed bitmaps expanded on device;
- filtered searches below flat_search_cutoff take a gather path: only the
  allowed rows are gathered and scored (flat_search.go:19 semantics,
  vectorized);
- mutation is staged host-side and flushed to the device in fixed-size
  chunks via dynamic_update_slice (no reallocation until capacity
  doubles — maintainance.go:31 geometric growth parity);
- reads are SNAPSHOT-ISOLATED (docs/concurrency.md): writers publish an
  immutable IndexSnapshot with one atomic reference swap, readers grab it
  lock-free and run the whole two-phase dispatch (enqueue on the snapshot,
  fetch outside any lock) — concurrent searches never convoy on the index
  mutex, and deletes/compression/compaction can't tear an in-flight
  dispatch because the snapshot pins its arrays.

Durability: an append-only binary vector log per shard (add/delete records),
replayed at startup — the analog of the HNSW commit log
(commit_logger.go:279-292) with only the records a flat store needs; a
snapshot+truncate cycle plays the role of condensing (condensor.go:32).
"""

from __future__ import annotations

import functools
import os
import struct
import threading
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.entities import vectorindex as vi
from weaviate_tpu.index.interface import AllowList, VectorIndex
# dispatch-shape recording for the perf-attribution plane: a
# costmodel.DispatchShape is built per dispatch ONLY while the tracer is
# up (tracing.get_tracer() gate — the zero-cost-when-disabled contract)
from weaviate_tpu.monitoring import costmodel, tracing
# memory ledger (monitoring/memory.py): device components are stamped
# analytically (shapes x dtypes, zero syncs) at snapshot publish and at
# every buffer-mutating method; unconfigured => one comparison, nothing
# constructed. Search dispatches never touch it.
from weaviate_tpu.monitoring import memory
# ops-event journal (monitoring/incidents.py): write-path compress/compact
# phases and degraded-kernel fallbacks are journaled so an incident bundle
# shows what the index was doing around a symptom; unconfigured => one
# comparison, nothing constructed, emit() is exception-guarded internally
from weaviate_tpu.monitoring import incidents
# shadow recall auditing (monitoring/quality.py): the dispatch snapshot is
# pinned in TLS ONLY while an auditor is configured (one comparison,
# nothing constructed — the tracer's zero-cost contract), so the audit
# compares against the exact index state the live answer saw
from weaviate_tpu.monitoring import quality
from weaviate_tpu.monitoring.metrics import record_device_fallback
from weaviate_tpu.ops.distances import DISTANCE_FNS
# self-tuning control plane (serving/controller.py): the recall-guarded
# budget controller caps the PQ fast-scan candidate depth (_rescore_r)
# against the shadow auditor's live recall EWMA — cap values come only
# from jit buckets so shapes stay cached; unconfigured => one
# comparison, the static default. controller imports nothing from the
# index layer, so no cycle.
from weaviate_tpu.serving import controller
# named fault-injection points (testing/faults.py): index.tpu.dispatch /
# index.tpu.finalize / index.tpu.alloc — one-comparison no-ops unless a
# harness is configured
from weaviate_tpu.testing import faults, sanitizers
# the one rescore-candidate bucket table (shared with the control plane's
# recall-guarded cap — serving/controller.py R_BUCKETS aliases it), and
# config's env-bool parser so FUSED_DISPATCH_ENABLED reads the same truth
# table with or without an App
from weaviate_tpu.config.config import (IVF_TOP_P_BUCKETS, IvfConfig,
                                        PQ4_FUNNEL_C_BUCKETS,
                                        PQ4_FUNNEL_RESCORE_BUCKETS,
                                        RESCORE_R_BUCKETS, ivf_from_env)
from weaviate_tpu.config.config import _bool as _env_bool
# the partition-pruned scan plane (ROADMAP item 3): k-means/PCA training
# helpers on the write path, probed-bucket search kernels on the read
# path (ops/ivf.py); every hook below is a one-comparison no-op while
# IVF_ENABLED is off
from weaviate_tpu.ops import ivf as ivf_ops
from weaviate_tpu.ops.topk import (bitmap_to_mask, merge_top_k,
                                   retranslate_packed, translate_pack,
                                   unpack_fused)

_CHUNK = 8192          # rows staged per device write (fixed => no recompiles)
_MIN_CAPACITY = 16384
_LOG_ADD = 1
_LOG_DELETE = 2
_LOG_MAGIC = b"WTVL"
_LOG_VERSION = 2  # v2 = per-record checksums + skip-ahead corrupt-region replay

# query-batch padding buckets (limit distinct compiled shapes)
_B_BUCKETS = (1, 4, 16, 64, 256, 1024)

# -- fused-dispatch toggle ----------------------------------------------------
# When on (the default), every search dispatch is END-TO-END device
# resident: the final top-k, tombstone/allowList masking, and slot->doc
# translation run in ONE XLA program against the snapshot's device
# translation table (IndexSnapshot.slot_to_doc_dev), so the single packed
# fetch carries final doc ids and finalize() is dtype views — zero host
# post-processing. Off = the legacy host slot_to_doc translation (kept as
# the bench's --fused A/B control and as a safety hatch).
_fused_override: Optional[bool] = None
_fused_env: Optional[bool] = None
_fused_token: Optional[object] = None


def set_fused_enabled(on: Optional[bool]) -> Optional[object]:
    """Override the fused-dispatch toggle process-wide (App applies the
    config knob here; bench/tests flip it for A/B runs). None reverts to
    the FUSED_DISPATCH_ENABLED environment default — re-read fresh, so
    the revert actually honors an env change made since the last parse.
    Returns an opaque token identifying THIS override — pass it to
    unset_fused_enabled so a torn-down App reverts only its own setting,
    never a newer App's (the tracer/perf still-ours unconfigure
    discipline)."""
    global _fused_override, _fused_token, _fused_env
    _fused_override = on
    _fused_token = object() if on is not None else None
    if on is None:
        _fused_env = None  # drop the cached parse: revert means re-read
    return _fused_token


def unset_fused_enabled(token: Optional[object]) -> None:
    """Revert set_fused_enabled's override iff `token` is still the
    CURRENT one (a newer override wins); None tokens are no-ops."""
    global _fused_override, _fused_token, _fused_env
    if token is not None and token is _fused_token:
        _fused_override = None
        _fused_token = None
        _fused_env = None  # revert means re-read the environment


def fused_dispatch_enabled() -> bool:
    global _fused_env
    if _fused_override is not None:
        return _fused_override
    if _fused_env is None:
        # the SAME parser Config uses: one knob must never read
        # differently in library use vs under an App
        _fused_env = _env_bool(os.environ, "FUSED_DISPATCH_ENABLED", True)
    return _fused_env


# -- IVF scan-plane toggle ----------------------------------------------------
# Same process-wide override/env-fallback shape as the fused-dispatch
# toggle above: App applies Config.ivf here at init (token-scoped so a
# torn-down App reverts only its own setting); bare-library indexes read
# the IVF_* environment through config's own parser, so one knob can
# never read differently with vs without an App. Disabled (the default)
# => ivf_settings() is None and every IVF hook — write-path training,
# dispatch planning, health — is a one-comparison no-op.
_ivf_override: Optional[IvfConfig] = None
_ivf_env: Optional[IvfConfig] = None
_ivf_token: Optional[object] = None


def set_ivf_config(cfg: Optional[IvfConfig]) -> Optional[object]:
    """Install a process-wide IvfConfig override (App wiring; bench/tests
    flip it for A/B runs). None reverts to the IVF_* environment default,
    re-read fresh. Returns a token for unset_ivf_config — the
    still-ours unconfigure discipline."""
    global _ivf_override, _ivf_token, _ivf_env
    _ivf_override = cfg
    _ivf_token = object() if cfg is not None else None
    if cfg is None:
        _ivf_env = None
    return _ivf_token


def unset_ivf_config(token: Optional[object]) -> None:
    """Revert set_ivf_config's override iff `token` is still current."""
    global _ivf_override, _ivf_token, _ivf_env
    if token is not None and token is _ivf_token:
        _ivf_override = None
        _ivf_token = None
        _ivf_env = None


def ivf_settings() -> Optional[IvfConfig]:
    """The active IVF settings, or None when the plane is disabled (the
    dispatch/write-path gate: one reference read + one bool)."""
    global _ivf_env
    s = _ivf_override
    if s is not None:
        return s if s.enabled else None
    if _ivf_env is None:
        _ivf_env = ivf_from_env()
    return _ivf_env if _ivf_env.enabled else None


def _snap_top_p(v: int) -> int:
    """Largest IVF_TOP_P_BUCKETS entry <= v (floor snap, min bucket) —
    the same bounded-jit-shape discipline as the rescore cap. Beyond the
    ladder's top (large-nlist layouts legitimately probe hundreds of
    partitions) the snap continues on pow2 steps: still one static
    value per octave, so the jit cache stays bounded and a big layout's
    probe width is never silently collapsed to 128."""
    top = IVF_TOP_P_BUCKETS[-1]
    if v > top:
        p = top
        while p * 2 <= v:
            p *= 2
        return int(p)
    best = IVF_TOP_P_BUCKETS[0]
    for b in IVF_TOP_P_BUCKETS:
        if b <= v:
            best = b
    return int(best)


def _bucket_b(b: int) -> int:
    for s in _B_BUCKETS:
        if b <= s:
            return s
    return ((b + 1023) // 1024) * 1024


def _bucket_rows(n: int) -> int:
    """Pad gather row counts to pow2-ish buckets (min 128 for lane alignment)."""
    b = 128
    while b < n:
        b *= 2
    return b


# the write kernels deliberately do NOT donate their input buffers:
# snapshot-isolated readers (IndexSnapshot) may still be dispatching on the
# previous array generation, and donation would invalidate the buffer under
# an in-flight search. Copy-on-write costs one transient extra copy per
# flush on the WRITE path — the trade that makes the read path lock-free.
@jax.jit
def _write_rows(store, chunk, offset):
    return jax.lax.dynamic_update_slice(store, chunk, (offset, 0))


@jax.jit
def _write_norms(norms, chunk, offset):
    return jax.lax.dynamic_update_slice(norms, chunk, (offset,))


@jax.jit
def _set_tombstones(tombs, idx):
    # idx padded with an out-of-range sentinel; mode="drop" ignores those
    return tombs.at[idx].set(True, mode="drop")


@jax.jit
def _write_doc_pairs(s2d, idx, pairs):
    """Scatter doc-id word pairs into the device slot->doc table. idx is
    padded (to a _bucket_rows width, bounding jit shapes) with an
    out-of-range sentinel; mode="drop" ignores the padding rows. Like
    every write kernel: non-donating, so snapshots pinning the previous
    table generation can never tear."""
    return s2d.at[idx].set(pairs, mode="drop")


@jax.jit
def _scatter_rows(arr, idx, rows):
    """Scatter padded row runs into a [capacity, d] device table (the
    IVF plane's low-dim PCA rows); idx padded with an out-of-range
    sentinel, mode="drop" ignores the padding. Non-donating like every
    write kernel — snapshots may pin the previous generation."""
    return arr.at[idx].set(rows, mode="drop")


@jax.jit
def _scatter_bucket(buckets, parts, cols, slots):
    """Scatter freshly-assigned slots into their partitions' free bucket
    columns — the O(batch) incremental bucket update (parts padded with
    an out-of-range id, mode="drop"). Non-donating: snapshots pinning
    the previous bucket generation can never tear."""
    return buckets.at[parts, cols].set(slots, mode="drop")


# unwritten-slot sentinel: both 32-bit words set, so a (bugged) gather of
# an unwritten slot reassembles to 2**64-1 — the same "missing" id the
# kernels' idx -1 sentinel produces, never a plausible doc id
_S2D_FILL = 0xFFFFFFFF


@functools.partial(jax.jit, static_argnames=("new_cap",))
def _grow_pairs(arr, new_cap):
    out = jnp.full((new_cap, arr.shape[1]), _S2D_FILL, arr.dtype)
    return jax.lax.dynamic_update_slice(out, arr, (0, 0))


@functools.partial(jax.jit, static_argnames=("new_cap",))
def _grow_store(store, new_cap):
    out = jnp.zeros((new_cap, store.shape[1]), store.dtype)
    return jax.lax.dynamic_update_slice(out, store, (0, 0))


@functools.partial(jax.jit, static_argnames=("new_cap",))
def _grow_1d(arr, new_cap, fill):
    out = jnp.full((new_cap,), fill, arr.dtype)
    return jax.lax.dynamic_update_slice(out, arr, (0,))


# rows of the store scored per scan step: bounds the [B, chunk] distance
# block so HBM never sees a full [B, N] matrix (at B=4096, N=1M that would be
# 16 GB — more than a v5e chip's HBM)
_SCAN_CHUNK = 131072


def _pack(top: jax.Array, idx: jax.Array) -> jax.Array:
    """Pack (dists f32, idx i32) [B,k] each into one [B, 2k] i32 array so the
    host needs a single device->host fetch (the axon/PCIe round trip costs
    far more than the bytes)."""
    return jnp.concatenate([jax.lax.bitcast_convert_type(top, jnp.int32), idx], axis=1)


def _unpack(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    k = packed.shape[1] // 2
    return packed[:, :k].view(np.float32), packed[:, k:]


def _fetch_packed(packed_dev, shape=None) -> np.ndarray:
    """The ONE blocking device->host fetch of a dispatch's finalize. With a
    perf shape attached (tracer up), stamps the fetch duration as the
    ledger's `device` stage — what finalize spends blocked on the device —
    so the gather-hop split (finalize minus fetch) is measurable; without
    one (disabled path) this is exactly np.asarray."""
    if shape is None:
        return np.asarray(packed_dev)
    t0 = time.perf_counter()
    out = np.asarray(packed_dev)
    shape.fetches += 1  # the fused-dispatch invariant counts these
    shape.t_fetch = time.perf_counter()
    shape.device_ms = (shape.t_fetch - t0) * 1000.0
    # duty-cycle anchor: the in-flight interval ends HERE, not at the
    # perf window's record call (hydration runs in between)
    shape.t_fetch_mono = time.monotonic()
    return out


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "use_allow", "exact", "active_chunks", "rescore_r"),
)
def _search_full(
    store, sq_norms, tombs, n, q, allow_words, k, metric, use_allow, exact=False,
    active_chunks=None, rescore_r=0,
):
    """Full-store masked kNN: lax.scan over HBM chunks, each step one
    [B, chunk] MXU distance block + per-chunk k-selection, exact merge.

    Per-chunk selection uses lax.approx_min_k — the TPU PartialReduce op
    (the ScaNN primitive) — which is ~2-4x faster than lax.top_k at
    measured recall 1.0 on real workloads; the cross-chunk merge is exact.
    Set exact=True (config exactTopK) to force lax.top_k per chunk.

    rescore_r > 0 enables the fast-scan-then-exact-rescore shape (the ScaNN
    recipe): the scan runs at DEFAULT matmul precision (single-pass MXU,
    ~2.3x the 6-pass HIGHEST throughput) selecting top-R candidates, then
    the R winners per query are gathered from the store ON DEVICE and
    re-scored elementwise at exact f32 — selection errors from the fast
    pass sit within R, so the final top-k matches HIGHEST-precision quality
    at DEFAULT-precision cost."""
    cap, dim = store.shape
    chunk = min(cap, _SCAN_CHUNK)
    nchunks = cap // chunk  # cap is a power of two >= 16384, so this divides
    # scan only the chunks that hold live rows (capacity may be up to 2x n
    # after geometric growth; scanning the empty tail would halve throughput)
    if active_chunks is not None:
        nchunks = max(1, min(nchunks, active_chunks))
    qd = q.astype(store.dtype)
    b = q.shape[0]
    kk = max(k, rescore_r) if rescore_r else k

    ext = nchunks * chunk
    store_c = store[:ext].reshape(nchunks, chunk, dim)
    tombs_c = tombs[:ext].reshape(nchunks, chunk)
    norms_c = sq_norms[:ext].reshape(nchunks, chunk) if sq_norms is not None else None
    allow_c = allow_words[: ext // 32].reshape(nchunks, chunk // 32) if use_allow else None

    def fast_dists(qq, store_l, norms_l):
        """Single-pass MXU distances (DEFAULT precision): the fast-scan half
        of the scan+rescore shape. Only matmul metrics reach here."""
        qx = jnp.matmul(qq, store_l.T, preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.DEFAULT)
        if metric == vi.DISTANCE_L2:
            q_sq = jnp.sum(qq.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
            nrm = norms_l if norms_l is not None else jnp.sum(
                store_l.astype(jnp.float32) ** 2, axis=-1
            )
            return jnp.maximum(q_sq - 2.0 * qx + nrm[None, :], 0.0)
        if metric == vi.DISTANCE_DOT:
            return -qx
        return 1.0 - qx  # cosine: rows pre-normalized

    def step(carry, xs):
        best_d, best_i = carry
        ci = xs[0]
        store_l, tombs_l = xs[1], xs[2]
        norms_l = xs[3] if norms_c is not None else None
        base = ci * chunk
        valid = jnp.logical_and(jnp.arange(chunk) + base < n, jnp.logical_not(tombs_l))
        if use_allow:
            valid = jnp.logical_and(valid, bitmap_to_mask(xs[-1], chunk))
        if rescore_r and metric in (vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
            d = fast_dists(qd, store_l, norms_l)
            d = jnp.where(valid[None, :], d, jnp.inf)
            td, li = jax.lax.approx_min_k(d, kk, recall_target=0.95)
        else:
            d = DISTANCE_FNS[metric](qd, store_l, norms_l)
            d = jnp.where(valid[None, :], d, jnp.inf)
            if exact:
                neg, li = jax.lax.top_k(-d, kk)
                td = -neg
            else:
                td, li = jax.lax.approx_min_k(d, kk, recall_target=0.95)
        merged = merge_top_k(best_d, best_i, td, li + base, kk)
        return merged, None

    init = (jnp.full((b, kk), jnp.inf, jnp.float32), jnp.full((b, kk), -1, jnp.int32))
    xs = [jnp.arange(nchunks), store_c, tombs_c]
    if norms_c is not None:
        xs.append(norms_c)
    if use_allow:
        xs.append(allow_c)
    (top, idx), _ = jax.lax.scan(step, init, tuple(xs))
    if rescore_r:
        # exact f32 rescoring of the R merged candidates, fully on device:
        # gather [B, R, D] rows and score elementwise (VPU work, one HBM
        # gather — no host round trip)
        from weaviate_tpu.ops.topk import rescore_distances

        safe = jnp.clip(idx, 0, cap - 1)
        cand = jnp.take(store, safe, axis=0)  # [B, R, D]
        ed = rescore_distances(cand, q, metric)
        ed = jnp.where(idx >= 0, ed, jnp.inf)
        neg, pos = jax.lax.top_k(-ed, k)
        top = -neg
        idx = jnp.take_along_axis(idx, pos, axis=1)
    idx = jnp.where(jnp.isinf(top), -1, idx).astype(jnp.int32)
    return _pack(top, idx)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "use_allow", "exact", "active_chunks", "rescore_r"),
)
def _search_full_fused(
    store, sq_norms, tombs, n, q, allow_words, s2d, k, metric, use_allow,
    exact=False, active_chunks=None, rescore_r=0,
):
    """_search_full with the slot->doc translation fused into the SAME
    XLA program (the inner jitted kernel inlines under this trace): the
    one packed fetch carries final doc ids (ops/topk FUSED layout)."""
    packed = _search_full(store, sq_norms, tombs, n, q, allow_words, k,
                          metric, use_allow, exact, active_chunks, rescore_r)
    return retranslate_packed(packed, s2d)


# rows of the uint8 code matrix scored per PQ scan step ([B, chunk] f32
# accumulator + one [B, C] VMEM table per segment; codes stream from HBM)
_PQ_SCAN_CHUNK = 32768


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "r_chunk", "metric", "use_allow", "exact", "active_chunks",
        "do_rescore",
    ),
)
def _search_pq_recon(codes, recon_norms, tombs, n, codebook, rescore_store, q,
                     allow_words, k, r_chunk, metric, use_allow, exact=False,
                     active_chunks=None, do_rescore=True, rot=None):
    """PQ scan the MXU way: asymmetric ADC distance equals the distance to
    the RECONSTRUCTED row (segments are disjoint dims), so each chunk's
    codes gather their centroids into a [chunk, D] block that feeds one
    bf16 matmul — identical math to the LUT scan
    (product_quantization.go:56-75 LookUp) at systolic-array throughput
    instead of per-element gather rates. ||recon||^2 is precomputed at
    encode time. Matmul metrics only (manhattan/hamming keep the LUT path).

    Candidate handling is collect-then-rescore: each chunk emits its top
    r_chunk (k-selection stays SMALL — large-k PartialReduce/top_k are the
    dominant cost on TPU), the per-chunk winners concatenate into one
    [B, nchunks*r_chunk] pool, and the pool is exact-rescored against the
    on-device bf16 rescore copy in the SAME program before the final
    top-k. No cross-chunk merge sorts, no host round trip."""
    cap, m = codes.shape
    _, c, ds = codebook.shape
    chunk = min(cap, _SCAN_CHUNK)
    nchunks = cap // chunk
    if active_chunks is not None:
        nchunks = max(1, min(nchunks, active_chunks))
    b = q.shape[0]
    flat_cb = codebook.reshape(m * c, ds).astype(jnp.bfloat16)
    seg_off = (jnp.arange(m, dtype=jnp.int32) * c)[None, :]

    ext = nchunks * chunk
    codes_c = codes[:ext].reshape(nchunks, chunk, m)
    norms_c = recon_norms[:ext].reshape(nchunks, chunk)
    tombs_c = tombs[:ext].reshape(nchunks, chunk)
    allow_c = allow_words[: ext // 32].reshape(nchunks, chunk // 32) if use_allow else None

    # OPQ: the ADC scan compares against ROTATED-space reconstructions, so
    # the query rotates too; the float rescore below stays in the original
    # space (the rescore store holds unrotated rows)
    qr = q if rot is None else jnp.matmul(
        q.astype(jnp.float32), rot, preferred_element_type=jnp.float32)
    qd = qr.astype(jnp.bfloat16)
    q_sq = jnp.sum(qr.astype(jnp.float32) ** 2, axis=-1, keepdims=True)

    def step(_, xs):
        ci, codes_l, norms_l, tombs_l = xs[0], xs[1], xs[2], xs[3]
        base = ci * chunk
        idx = codes_l.astype(jnp.int32) + seg_off          # [chunk, M]
        recon = jnp.take(flat_cb, idx, axis=0)             # [chunk, M, ds]
        recon = recon.reshape(chunk, m * ds)
        qx = jnp.matmul(qd, recon.T, preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.DEFAULT)
        if metric == vi.DISTANCE_L2:
            d = jnp.maximum(q_sq - 2.0 * qx + norms_l[None, :], 0.0)
        elif metric == vi.DISTANCE_DOT:
            d = -qx
        else:  # cosine: queries normalized; recon approximates unit rows
            d = 1.0 - qx
        valid = jnp.logical_and(jnp.arange(chunk) + base < n, jnp.logical_not(tombs_l))
        if use_allow:
            valid = jnp.logical_and(valid, bitmap_to_mask(xs[4], chunk))
        d = jnp.where(valid[None, :], d, jnp.inf)
        if exact:
            neg, li = jax.lax.top_k(-d, r_chunk)
            td = -neg
        else:
            td, li = jax.lax.approx_min_k(d, r_chunk, recall_target=0.95)
        return None, (td, li + base)

    xs = [jnp.arange(nchunks), codes_c, norms_c, tombs_c]
    if use_allow:
        xs.append(allow_c)
    _, (tds, lis) = jax.lax.scan(step, None, tuple(xs))  # [nchunks, B, r_chunk]
    pool = nchunks * r_chunk
    cand_d = jnp.moveaxis(tds, 0, 1).reshape(b, pool)
    cand_i = jnp.moveaxis(lis, 0, 1).reshape(b, pool)
    if do_rescore:
        safe = jnp.clip(cand_i, 0, cap - 1)
        cand = jnp.take(rescore_store, safe, axis=0).astype(jnp.float32)
        qf = q.astype(jnp.float32)[:, None, :]
        if metric == vi.DISTANCE_L2:
            ed = jnp.sum((cand - qf) ** 2, axis=-1)
        elif metric == vi.DISTANCE_DOT:
            ed = -jnp.sum(cand * qf, axis=-1)
        else:
            ed = 1.0 - jnp.sum(cand * qf, axis=-1)
        cand_d = jnp.where(jnp.isinf(cand_d), jnp.inf, ed)
    neg, pos = jax.lax.top_k(-cand_d, k)
    top = -neg
    final = jnp.take_along_axis(cand_i, pos, axis=1)
    final = jnp.where(jnp.isinf(top), -1, final).astype(jnp.int32)
    return _pack(top, final)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "r_chunk", "metric", "use_allow", "exact", "active_chunks",
        "do_rescore",
    ),
)
def _search_pq_recon_fused(codes, recon_norms, tombs, n, codebook,
                           rescore_store, q, allow_words, s2d, k, r_chunk,
                           metric, use_allow, exact=False, active_chunks=None,
                           do_rescore=True, rot=None):
    """_search_pq_recon with device-side slot->doc translation fused in."""
    packed = _search_pq_recon(codes, recon_norms, tombs, n, codebook,
                              rescore_store, q, allow_words, k, r_chunk,
                              metric, use_allow, exact, active_chunks,
                              do_rescore, rot)
    return retranslate_packed(packed, s2d)


@functools.partial(
    jax.jit, static_argnames=("r", "use_allow", "exact", "active_chunks")
)
def _search_pq(codes, tombs, n, lut, allow_words, r, use_allow, exact=False,
               active_chunks=None):
    """PQ twin of _search_full: scan the [cap, M] code matrix in HBM chunks,
    score each chunk via the additive LUT gather (compress/pq.py
    lut_scan_block — product_quantization.go:56-75 LookUp, vectorized),
    exact cross-chunk merge of the top-r candidate slots."""
    from weaviate_tpu.compress.pq import lut_scan_block

    cap, m = codes.shape
    chunk = min(cap, _PQ_SCAN_CHUNK)
    nchunks = cap // chunk
    if active_chunks is not None:
        nchunks = max(1, min(nchunks, active_chunks))
    b = lut.shape[0]

    ext = nchunks * chunk
    codes_c = codes[:ext].reshape(nchunks, chunk, m)
    tombs_c = tombs[:ext].reshape(nchunks, chunk)
    allow_c = allow_words[: ext // 32].reshape(nchunks, chunk // 32) if use_allow else None

    def step(carry, xs):
        best_d, best_i = carry
        ci, codes_l, tombs_l = xs[0], xs[1], xs[2]
        base = ci * chunk
        valid = jnp.logical_and(jnp.arange(chunk) + base < n, jnp.logical_not(tombs_l))
        if use_allow:
            valid = jnp.logical_and(valid, bitmap_to_mask(xs[3], chunk))
        d = lut_scan_block(codes_l.astype(jnp.int32), lut)
        d = jnp.where(valid[None, :], d, jnp.inf)
        if exact:
            neg, li = jax.lax.top_k(-d, r)
            td = -neg
        else:
            td, li = jax.lax.approx_min_k(d, r, recall_target=0.95)
        merged = merge_top_k(best_d, best_i, td, li + base, r)
        return merged, None

    init = (jnp.full((b, r), jnp.inf, jnp.float32), jnp.full((b, r), -1, jnp.int32))
    xs = [jnp.arange(nchunks), codes_c, tombs_c]
    if use_allow:
        xs.append(allow_c)
    (top, idx), _ = jax.lax.scan(step, init, tuple(xs))
    idx = jnp.where(jnp.isinf(top), -1, idx).astype(jnp.int32)
    return _pack(top, idx)


@functools.partial(
    jax.jit, static_argnames=("r", "use_allow", "exact", "active_chunks")
)
def _search_pq_fused(codes, tombs, n, lut, allow_words, s2d, r, use_allow,
                     exact=False, active_chunks=None):
    """_search_pq (LUT tier) with device-side slot->doc translation."""
    packed = _search_pq(codes, tombs, n, lut, allow_words, r, use_allow,
                        exact, active_chunks)
    return retranslate_packed(packed, s2d)


def _gather_live(rows, row_valid, tombs):
    """Row validity for the gather tier, tombstone-masked ON DEVICE with
    the dispatching snapshot's own `tombs`: the host-side allow-slot
    resolution is cached per (allow_token, n, capacity) — a key deletes
    do NOT change — so a cached slot list may include slots tombstoned
    since it was computed; the snapshot's device mask keeps every
    dispatch exact for the state it pinned (and an old snapshot's
    dispatch keeps returning its own pre-delete world)."""
    safe = jnp.clip(rows, 0, tombs.shape[0] - 1)
    return jnp.logical_and(row_valid,
                           jnp.logical_not(jnp.take(tombs, safe)))


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _score_rows(sub, q, rows, row_valid, tombs, k, metric):
    """Score an uploaded [R, D] row block against [B, D] queries (the gather
    path when the float store lives host-side under PQ). rows [R] carries
    each block position's store slot for the device tombstone mask."""
    dists = DISTANCE_FNS[metric](q.astype(sub.dtype), sub, None)
    masked = jnp.where(_gather_live(rows, row_valid, tombs)[None, :],
                       dists, jnp.inf)
    neg, idx = jax.lax.top_k(-masked, k)
    top = -neg
    return _pack(top, jnp.where(jnp.isinf(top), -1, idx).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _search_gathered(store, q, rows, row_valid, tombs, k, metric):
    """Gather path for small allowLists (flat_search.go:19 analog): score only
    the gathered rows. rows [R] int32 (padded), row_valid [R] bool; the
    snapshot's tombs mask rides the same program (see _gather_live)."""
    sub = jnp.take(store, rows, axis=0, mode="fill", fill_value=0)
    dists = DISTANCE_FNS[metric](q.astype(store.dtype), sub, None)
    masked = jnp.where(_gather_live(rows, row_valid, tombs)[None, :],
                       dists, jnp.inf)
    kk = min(k, sub.shape[0])
    neg, idx = jax.lax.top_k(-masked, kk)
    top = -neg
    return _pack(top, jnp.where(jnp.isinf(top), -1, idx).astype(jnp.int32))


def _rows_to_slots(packed, rows):
    """Gather-tier epilogue: the kernel's idx are POSITIONS into the
    uploaded `rows` block — map them back to store slots on device so the
    shared translate_pack can emit final doc ids."""
    kc = packed.shape[1] // 2
    top = jax.lax.bitcast_convert_type(packed[:, :kc], jnp.float32)
    idx = packed[:, kc:]
    safe = jnp.clip(idx, 0, rows.shape[0] - 1)
    slots = jnp.where(idx >= 0, jnp.take(rows, safe), -1)
    return top, slots


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _score_rows_fused(sub, q, rows, row_valid, tombs, s2d, k, metric):
    """_score_rows with slot->doc translation fused in (rows carries each
    uploaded block position's store slot)."""
    top, slots = _rows_to_slots(
        _score_rows(sub, q, rows, row_valid, tombs, k, metric), rows)
    return translate_pack(top, slots, s2d)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _search_gathered_fused(store, q, rows, row_valid, tombs, s2d, k, metric):
    """_search_gathered with slot->doc translation fused in."""
    top, slots = _rows_to_slots(
        _search_gathered(store, q, rows, row_valid, tombs, k, metric), rows)
    return translate_pack(top, slots, s2d)


def _prep_bulk_run(ids: np.ndarray, vecs: np.ndarray, metric: str, known_fn):
    """Shared restore-run preparation for the single-chip and mesh indexes:
    f32 cast, cosine normalization, keep-last dedup of in-run duplicate
    docs, and the indices of docs the index already knows (those must take
    the per-record path so their old slots tombstone).
    -> (ids int64 [n], vecs f32 [n, d], known_indices list)."""
    vecs = np.asarray(vecs, np.float32)
    if metric == vi.DISTANCE_COSINE:
        nrm = np.linalg.norm(vecs, axis=1, keepdims=True)
        nrm[nrm == 0] = 1.0
        vecs = vecs / nrm
    ids64 = ids.astype(np.int64)
    if len(np.unique(ids64)) != len(ids64):
        # keep-last within the run (later records overwrite earlier)
        _, last_rev = np.unique(ids64[::-1], return_index=True)
        order = np.sort(len(ids64) - 1 - last_rev)
        ids64, vecs = ids64[order], vecs[order]
    known = [i for i, d in enumerate(ids64.tolist()) if known_fn(d)]
    return ids64, vecs, known


class VectorLog:
    """Append-only durability log for the device store (commit-log analog).

    v2 record layout (header magic WTVL, version 2):
      ADD:    op(1)=1 | doc_id(<Q) | dim(<I) | ck(<I) | dim x <f4 payload
      DELETE: op(1)=2 | doc_id(<Q) | ck(<I)
    where ck is the 32-bit additive byte checksum of every record byte
    EXCEPT the ck field itself. An additive sum (not crc32) is deliberate:
    it detects any single flipped byte, and the vectorized replay can
    verify a million records with two numpy row-sums instead of a Python
    crc loop. The checksum is what makes mid-log corruption DETECTABLE,
    which in turn makes skip-ahead replay safe: on a bad record, replay
    scans forward for the next offset where a whole record parses AND
    checksums (false resync ~2^-32 per candidate) and continues from
    there, counting the skipped bytes — the flat-store analog of the
    reference's corrupt-region repair (corrupt_commit_logs_fixer.go:1),
    which replays around damage rather than abandoning everything after
    it. v1 logs (no checksum) still replay with the old
    stop-at-first-bad-record behavior.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fresh = True
        if os.path.exists(path):
            # a crash can leave a torn/corrupt tail; anything appended after
            # an unreadable region would be durably written yet unreachable —
            # silent data loss on the next restart. For v2 logs the cut point
            # is the end of the LAST valid record (mid-file damage stays in
            # place for skip-ahead replay to route around); for v1 logs it is
            # the first bad record, as before.
            size = os.path.getsize(path)
            valid = self._valid_prefix_len(path)
            if valid < size:
                cut = valid
                if self._version(path) >= 2:
                    cut = max(valid, self._last_valid_end(path))
                with open(path, "r+b") as f:
                    f.truncate(cut)
                fresh = cut == 0
            else:
                fresh = valid == 0
            if not fresh and self._version(path) < 2:
                # one-time in-place upgrade: appends always write v2
                # checksummed records, and mixing formats within one file
                # would make v1 replay mis-parse every appended vector
                # (checksum bytes read as payload) — rewrite the whole log
                # as v2 before reusing it.
                self._upgrade_v1(path)
        self._f = open(path, "ab")
        if fresh:
            self._f.write(_LOG_MAGIC + struct.pack("<H", _LOG_VERSION))
            self._f.flush()

    @staticmethod
    def report_replay_stats(path: str, stats: dict) -> None:
        """One shared skip-report so the single-chip and mesh restores (and
        any future caller) cannot drift in what they tell the operator."""
        if stats.get("skipped_bytes"):
            import logging

            logging.getLogger(__name__).warning(
                "vector log %s: skipped %d corrupt byte(s) across %d "
                "region(s) during replay; records inside the damage are "
                "lost, everything outside it was recovered",
                path, stats["skipped_bytes"], stats.get("skipped_regions", 0))

    @staticmethod
    def _upgrade_v1(path: str) -> None:
        tmp = path + ".upgrade"
        with open(tmp, "wb") as f:
            f.write(_LOG_MAGIC + struct.pack("<H", _LOG_VERSION))
            for op, doc_id, vec in VectorLog.replay(path):
                if op == "add":
                    f.write(VectorLog._enc_add(doc_id, vec))
                else:
                    head = struct.pack("<BQ", _LOG_DELETE, doc_id)
                    f.write(head + struct.pack("<I", VectorLog._sum32(head)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- format helpers ------------------------------------------------------

    @staticmethod
    def _version(path: str) -> int:
        with open(path, "rb") as f:
            head = f.read(6)
        if len(head) < 6 or head[:4] != _LOG_MAGIC:
            return 0
        return struct.unpack_from("<H", head, 4)[0]

    @staticmethod
    def _sum32(*parts) -> int:
        s = 0
        for p in parts:
            s += int(np.frombuffer(p, np.uint8).sum(dtype=np.uint64))
        return s & 0xFFFFFFFF

    @staticmethod
    def _enc_add(doc_id: int, v: np.ndarray) -> bytes:
        head = struct.pack("<BQI", _LOG_ADD, doc_id, v.shape[0])
        payload = v.tobytes()
        return head + struct.pack("<I", VectorLog._sum32(head, payload)) + payload

    @staticmethod
    def _validate_v2(data, off: int, n: int):
        """If a valid v2 record starts at off, return (op, end); else None."""
        op = data[off]
        if op == _LOG_ADD:
            if off + 17 > n:
                return None
            dim, ck = struct.unpack_from("<II", data, off + 9)
            if not 0 < dim <= 65536:
                return None
            end = off + 17 + 4 * dim
            if end > n:
                return None
            if VectorLog._sum32(data[off : off + 13], data[off + 17 : end]) != ck:
                return None
            return (_LOG_ADD, end)
        if op == _LOG_DELETE:
            if off + 13 > n:
                return None
            (ck,) = struct.unpack_from("<I", data, off + 9)
            if VectorLog._sum32(data[off : off + 9]) != ck:
                return None
            return (_LOG_DELETE, off + 13)
        return None

    @staticmethod
    def _resync_v2(data, buf: np.ndarray, off: int, n: int):
        """Smallest off' >= off where a whole v2 record parses and checksums,
        or None. Candidate positions (op byte is 1 or 2) are found with one
        vectorized pass per 1 MiB window; each candidate pays one record-sized
        checksum, so the scan cost is bounded by the damaged span, not the
        log size."""
        pos = off
        while pos < n:
            win = min(pos + (1 << 20), n)
            cands = np.flatnonzero((buf[pos:win] == _LOG_ADD) | (buf[pos:win] == _LOG_DELETE))
            for idx in cands.tolist():
                p = pos + idx
                if VectorLog._validate_v2(data, p, n) is not None:
                    return p
            pos = win
        return None

    @staticmethod
    def _valid_prefix_len(path: str) -> int:
        """Byte length of the longest parseable record prefix. 0 means the
        header itself is unusable (the file must be re-initialized). Scans
        record HEADERS only (seek past payloads), so a multi-GB log costs one
        sequential header walk, not a whole-file read. Does NOT verify
        checksums — it bounds where the cheap walk stops, not data integrity
        (replay re-verifies every record)."""
        with open(path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            head = f.read(6)
            if len(head) < 6 or head[:4] != _LOG_MAGIC:
                return 0
            v2 = struct.unpack_from("<H", head, 4)[0] >= 2
            add_hdr = 17 if v2 else 13
            del_len = 13 if v2 else 9
            off = 6
            while off < size:
                f.seek(off)
                hdr = f.read(add_hdr)
                if not hdr:
                    return off
                op = hdr[0]
                if op == _LOG_ADD:
                    if len(hdr) < add_hdr:
                        return off
                    (dim,) = struct.unpack_from("<I", hdr, 9)
                    if v2 and not 0 < dim <= 65536:
                        return off
                    end = off + add_hdr + 4 * dim
                    if end > size:
                        return off
                    off = end
                elif op == _LOG_DELETE:
                    if len(hdr) < del_len:
                        return off
                    off += del_len
                else:
                    return off
            return off

    @staticmethod
    def _last_valid_end(path: str) -> int:
        """End offset of the last valid v2 record anywhere in the file (the
        truncation point that preserves recoverable data past mid-file
        damage). Walks record offsets only; vectors are never materialized."""
        with open(path, "rb") as f:
            data = f.read()
        n = len(data)
        if n < 6 or data[:4] != _LOG_MAGIC:
            return 0
        buf = np.frombuffer(data, np.uint8)
        off, last = 6, 6
        while off < n:
            v = VectorLog._validate_v2(data, off, n)
            if v is None:
                nxt = VectorLog._resync_v2(data, buf, off + 1, n)
                if nxt is None:
                    return last
                off = nxt
                continue
            off = last = v[1]
        return last

    # -- appends -------------------------------------------------------------

    def append_add(self, doc_id: int, vector: np.ndarray) -> None:
        v = np.ascontiguousarray(vector, dtype=np.float32)
        self._f.write(self._enc_add(doc_id, v))

    def append_add_batch(self, doc_ids: np.ndarray, vectors: np.ndarray) -> None:
        """Vectorized bulk append: one write() for the whole batch, with the
        per-record checksums computed as two numpy row-sums."""
        n, dim = vectors.shape
        rec_len = 17 + 4 * dim
        buf = np.zeros((n, rec_len), np.uint8)
        buf[:, 0] = _LOG_ADD
        buf[:, 1:9] = doc_ids.astype("<u8").view(np.uint8).reshape(n, 8)
        buf[:, 9:13] = np.frombuffer(struct.pack("<I", dim), np.uint8)
        buf[:, 17:] = np.ascontiguousarray(vectors, dtype="<f4").view(np.uint8).reshape(n, 4 * dim)
        sums = buf[:, :13].sum(axis=1, dtype=np.uint64) + buf[:, 17:].sum(axis=1, dtype=np.uint64)
        buf[:, 13:17] = (sums & 0xFFFFFFFF).astype("<u4").view(np.uint8).reshape(n, 4)
        self._f.write(buf.tobytes())

    def append_delete(self, doc_id: int) -> None:
        head = struct.pack("<BQ", _LOG_DELETE, doc_id)
        self._f.write(head + struct.pack("<I", self._sum32(head)))

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()

    @staticmethod
    def replay(path: str, stats: Optional[dict] = None):
        """Yield ('add', doc_id, vec) / ('delete', doc_id, None). v2 logs
        verify per-record checksums and SKIP corrupt regions (resuming at the
        next valid record, with the loss counted in `stats`); v1 logs keep
        the old stop-at-first-bad-record behavior. A torn tail is tolerated
        either way (corrupt_commit_logs_fixer.go behavior)."""
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != _LOG_MAGIC or len(data) < 6:
            return
        if struct.unpack_from("<H", data, 4)[0] >= 2:
            yield from VectorLog._replay_v2(data, stats, batched=False)
            return
        off = 6
        n = len(data)
        while off < n:
            try:
                op = data[off]
                if op == _LOG_ADD:
                    doc_id, dim = struct.unpack_from("<QI", data, off + 1)
                    start = off + 13
                    end = start + dim * 4
                    if end > n:
                        return  # torn write
                    vec = np.frombuffer(data, "<f4", count=dim, offset=start).copy()
                    yield ("add", doc_id, vec)
                    off = end
                elif op == _LOG_DELETE:
                    (doc_id,) = struct.unpack_from("<Q", data, off + 1)
                    yield ("delete", doc_id, None)
                    off += 9
                else:
                    return  # corrupt record type: stop replay
            except struct.error:
                return

    @staticmethod
    def replay_batches(path: str, stats: Optional[dict] = None):
        """Vectorized replay: maximal runs of same-dim add records parse as
        ONE numpy view — ('add', ids [n] u64, vecs [n, dim] f32) — with
        ('delete', doc_id, None) singles in order. Same corruption tolerance
        as replay(); restores parse the log ~10x faster this way."""
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != _LOG_MAGIC or len(data) < 6:
            return
        if struct.unpack_from("<H", data, 4)[0] >= 2:
            yield from VectorLog._replay_v2(data, stats, batched=True)
            return
        buf = np.frombuffer(data, np.uint8)
        off = 6
        n = len(data)
        while off < n:
            try:
                op = data[off]
                if op == _LOG_ADD:
                    if off + 13 > n:
                        return  # torn header
                    doc_id, dim = struct.unpack_from("<QI", data, off + 1)
                    rec = 13 + 4 * dim
                    max_run = (n - off) // rec
                    if max_run == 0:
                        return  # torn vector payload
                    view = buf[off : off + max_run * rec].reshape(max_run, rec)
                    ok = view[:, 0] == _LOG_ADD
                    dim_b = np.frombuffer(struct.pack("<I", dim), np.uint8)
                    ok &= (view[:, 9:13] == dim_b).all(axis=1)
                    run = max_run if bool(ok.all()) else max(1, int(np.argmin(ok)))
                    sel = view[:run]
                    ids = np.ascontiguousarray(sel[:, 1:9]).view("<u8").ravel()
                    vecs = np.ascontiguousarray(sel[:, 13:]).view("<f4").reshape(run, dim)
                    yield ("add", ids, vecs)
                    off += run * rec
                elif op == _LOG_DELETE:
                    if off + 9 > n:
                        return
                    (doc_id,) = struct.unpack_from("<Q", data, off + 1)
                    yield ("delete", doc_id, None)
                    off += 9
                else:
                    return  # corrupt record type: stop replay
            except struct.error:
                return

    @staticmethod
    def _replay_v2(data: bytes, stats: Optional[dict], batched: bool):
        """Shared v2 walk. Valid add-runs still parse as one numpy view (the
        checksum column verifies vectorized, two row-sums per run); any
        record that fails validation starts a skip-ahead scan, and the
        skipped span is accumulated into `stats` so callers can REPORT the
        loss instead of silently shrinking the store."""
        buf = np.frombuffer(data, np.uint8)
        off = 6
        n = len(data)

        def _skip(start: int):
            nxt = VectorLog._resync_v2(data, buf, start + 1, n)
            end = n if nxt is None else nxt
            if stats is not None:
                stats["skipped_bytes"] = stats.get("skipped_bytes", 0) + (end - start)
                stats["skipped_regions"] = stats.get("skipped_regions", 0) + 1
            return nxt

        while off < n:
            op = data[off]
            if op == _LOG_ADD and off + 17 <= n:
                dim, ck0 = struct.unpack_from("<II", data, off + 9)
                rec = 17 + 4 * dim
                max_run = (n - off) // rec if 0 < dim <= 65536 else 0
                if max_run == 0:
                    off = _skip(off)
                    if off is None:
                        return
                    continue
                view = buf[off : off + max_run * rec].reshape(max_run, rec)
                ok = view[:, 0] == _LOG_ADD
                dim_b = np.frombuffer(struct.pack("<I", dim), np.uint8)
                ok &= (view[:, 9:13] == dim_b).all(axis=1)
                sums = view[:, :13].sum(axis=1, dtype=np.uint64) + view[:, 17:].sum(
                    axis=1, dtype=np.uint64
                )
                stored = np.ascontiguousarray(view[:, 13:17]).view("<u4").ravel()
                ok &= (sums & 0xFFFFFFFF) == stored
                run = max_run if bool(ok.all()) else int(np.argmin(ok))
                if run == 0:  # first record is corrupt — resync
                    off = _skip(off)
                    if off is None:
                        return
                    continue
                sel = view[:run]
                ids = np.ascontiguousarray(sel[:, 1:9]).view("<u8").ravel()
                vecs = np.ascontiguousarray(sel[:, 17:]).view("<f4").reshape(run, dim)
                if batched:
                    yield ("add", ids, vecs)
                else:
                    for i in range(run):
                        yield ("add", int(ids[i]), vecs[i].copy())
                off += run * rec
            elif op == _LOG_DELETE and off + 13 <= n:
                if VectorLog._validate_v2(data, off, n) is None:
                    off = _skip(off)
                    if off is None:
                        return
                    continue
                (doc_id,) = struct.unpack_from("<Q", data, off + 1)
                yield ("delete", doc_id, None)
                off += 13
            else:
                off = _skip(off)
                if off is None:
                    return

    def rewrite(self, entries) -> None:
        """Condense: atomically rewrite the log with only live entries."""
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_LOG_MAGIC + struct.pack("<H", _LOG_VERSION))
            for doc_id, vec in entries:
                v = np.ascontiguousarray(vec, dtype=np.float32)
                f.write(self._enc_add(doc_id, v))
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")


class IndexSnapshot:
    """One immutable published generation of the device state a search
    dispatch reads.

    Writers stage under the index lock and publish a NEW snapshot with one
    atomic reference swap (`TpuVectorIndex._publish_snapshot`); readers grab
    the current reference lock-free and dispatch on it. The snapshot's
    references pin its arrays: a concurrent delete/compress/compact swaps
    the index's attributes to new arrays but can never tear an in-flight
    dispatch, because

      - the device write kernels do not donate (every update REPLACES the
        array object, the old buffer stays valid until the last snapshot
        holding it drops), and
      - the host-side `host_tombs` mirror is copy-on-written by any
        writer that would mutate an array a published snapshot still
        references; `slot_to_doc` needs NO copy — writers only assign
        slots at indices >= this snapshot's `n` (slot assignment is
        append-only between compactions, and compact/grow replace the
        array object wholesale), so the `[:n]` prefix a reader consults
        is immutable by construction.

    `slot_to_doc_dev` is the DEVICE twin of `slot_to_doc`: a
    [capacity, 2] uint32 table of each slot's 64-bit doc-id words,
    maintained by the same staged-generation handshake (rows land via
    `_stage_doc_ids` before `_publish_snapshot` swaps the reference), so
    a fused dispatch's in-program slot->doc translation reads exactly the
    mapping this snapshot's host arrays describe. Everything here is
    frozen at publish.
    """

    __slots__ = ("gen", "dim", "capacity", "n", "live", "store", "sq_norms",
                 "tombs", "slot_to_doc", "slot_to_doc_dev", "host_tombs",
                 "allow_token", "compressed", "pq", "codes", "recon_norms",
                 "rescore_dev", "rescore_sq_norms", "host_vecs",
                 "pq4", "codes4", "recon_norms4", "opq_rot",
                 "ivf_centroids", "ivf_buckets", "ivf_pca_proj",
                 "ivf_pca_rows", "ivf_meta")

    def __init__(self, gen: int, idx: "TpuVectorIndex"):
        self.gen = gen
        self.dim = idx.dim
        self.capacity = idx.capacity
        self.n = idx.n
        self.live = idx.live
        self.store = idx._store
        self.sq_norms = idx._sq_norms
        self.tombs = idx._tombs
        self.slot_to_doc = idx._slot_to_doc
        self.slot_to_doc_dev = idx._s2d_dev
        self.host_tombs = idx._host_tombs
        self.allow_token = idx._allow_token
        self.compressed = idx.compressed
        self.pq = idx._pq
        self.codes = idx._codes
        self.recon_norms = idx._recon_norms
        self.rescore_dev = idx._rescore_dev
        self.rescore_sq_norms = idx._rescore_sq_norms
        self.host_vecs = idx._host_vecs
        # 4-bit funnel ladder: nibble-packed codes + their recon norms +
        # the shared OPQ rotation, pinned exactly like the 8-bit slabs —
        # a re-compress mid-dispatch serves this snapshot's ladder
        self.pq4 = idx._pq4
        self.codes4 = idx._codes4
        self.recon_norms4 = idx._recon_norms4
        self.opq_rot = idx._opq_rot_dev
        # the IVF scan plane's device slabs ride the snapshot exactly
        # like the store: a recluster/compact replaces the arrays
        # wholesale (non-donating), so an in-flight dispatch pinning
        # this snapshot keeps answering from ITS partition layout
        self.ivf_centroids = idx._ivf_centroids
        self.ivf_buckets = idx._ivf_buckets
        self.ivf_pca_proj = idx._ivf_pca_proj
        self.ivf_pca_rows = idx._ivf_pca_rows
        # (nlist, cap_p, recluster_gen) — host ints, frozen at publish
        self.ivf_meta = idx._ivf_meta


class TpuVectorIndex(VectorIndex):
    # the async dispatch path handles filtered searches, the PQ codes-only
    # tier, and the small-allowList gather (everything rides the snapshot
    # two-phase enqueue/finalize pipeline) — serving layers key off this
    async_supports_filters = True

    def __init__(
        self,
        config: vi.HnswUserConfig,
        shard_path: str,
        shard_name: str = "",
        metrics=None,
        device=None,
        persist: bool = True,
        class_name: str = "",
    ):
        self.config = config
        self.metric = config.distance
        self.shard_path = shard_path
        self.shard_name = shard_name
        # set before _restore: replay-time metrics must carry the right label
        self.class_name = class_name
        self.metrics = metrics
        self.device = device
        self.dtype = jnp.bfloat16 if getattr(config, "store_dtype", "float32") == "bfloat16" else jnp.float32
        self._lock = sanitizers.register_lock(
            threading.RLock(), "index.tpu")

        self.dim: Optional[int] = None
        self.capacity = 0
        self.n = 0  # high-water slot count (includes tombstoned slots)
        self.live = 0
        self._store = None       # device [capacity, D]
        self._sq_norms = None    # device [capacity] float32 (l2 only)
        self._tombs = None       # device [capacity] bool
        self._slot_to_doc = np.zeros(0, dtype=np.int64)
        # device slot->doc translation table [capacity, 2] uint32 (lo/hi
        # words of the int64 doc id per slot): what lets a fused dispatch
        # emit FINAL doc ids from the one packed fetch (ops/topk
        # translate_pack) with zero host translation
        self._s2d_dev = None
        # reusable pre-pinned host staging buffers for query upload, one
        # small free-list per (padded batch, dim) jit bucket — the
        # per-dispatch numpy concat/zeros allocations the fused-dispatch
        # tentpole collapses. Returned to the pool by finalize, AFTER the
        # one blocking fetch: by then the program has consumed its inputs,
        # so reuse is safe even where device_put aliases host memory
        # (the cpu backend).
        self._stage_free: dict[tuple[int, int], list[np.ndarray]] = {}
        self._stage_lock = sanitizers.register_lock(
            threading.Lock(), "index.tpu.stage_pool")
        # host mirror of the device tombstone mask: snapshots derive the
        # live doc->slot map from it without a device fetch
        self._host_tombs = np.zeros(0, dtype=bool)
        self._doc_to_slot: dict[int, int] = {}
        # snapshot-isolated read plane: readers dispatch on the published
        # IndexSnapshot lock-free; writers republish under self._lock.
        # _staged_gen/_published_gen is the read-your-writes handshake: any
        # staging bumps _staged_gen (under the lock), publication copies it
        # — a reader that sees them equal may use the snapshot as-is.
        self._snap: Optional[IndexSnapshot] = None
        self._snap_gen = 0
        self._staged_gen = 0
        self._published_gen = -1
        # monotonic stamp of the OLDEST staged-but-unpublished mutation
        # (ledger staged-publish lag; None = nothing staged / ledger off)
        self._staged_t0: Optional[float] = None
        self._read_local = threading.local()  # per-thread last lock wait
        self._inflight = 0                    # dispatches between enqueue
        self._inflight_lock = sanitizers.register_lock(
            threading.Lock(), "index.tpu.inflight")  # ...and finalize
        self._inflight_gauge = None  # resolved lazily (None) / broken (False)
        # staging buffer keyed by doc_id: a re-add of a staged doc replaces it
        self._pending: dict[int, np.ndarray] = {}
        self._pending_tombs: list[int] = []
        # PQ state (compress.go analog): when compressed, the device holds
        # [cap, M] uint8/16 codes instead of floats; full-precision rows move
        # to host RAM for the rescoring pass
        self.compressed = False
        self._pq = None                     # ProductQuantizer
        self._codes = None                  # device [capacity, M]
        self._rescore_dev = None            # device bf16 [capacity, D]
        self._rescore_sq_norms = None       # device f32 [capacity] (l2 bias)
        self._recon_norms = None            # device f32 [capacity] ||recon||^2
        self._host_vecs: Optional[np.ndarray] = None  # np [capacity, D] f32
        self._pq_path = os.path.join(shard_path, "pq.npz")
        # 4-bit funnel ladder (pq.bits=4): a SECOND quantizer with 16
        # centroids per segment sharing the 8-bit quantizer's OPQ rotation,
        # its nibble-packed codes [cap, M/2] uint8, recon norms, and the
        # rotation as its own device slab (applied to queries at dispatch)
        self._pq4 = None                    # ProductQuantizer (centroids=16)
        self._codes4 = None                 # device [capacity, M/2] uint8
        self._recon_norms4 = None           # device f32 [capacity]
        self._opq_rot_dev = None            # device f32 [D, D] (or None)
        self._pq4_path = os.path.join(shard_path, "pq4.npz")
        self._restoring = False
        # flips true on a Mosaic compile failure of the fused gmin kernel;
        # searches then stay on the lax.scan kernel permanently
        self._gmin_broken = False
        # identity token for the per-allowList packed-words cache: the cache
        # tuple holds a strong ref, so the identity can never be recycled
        self._allow_token = object()
        # separate failure domain + codebook-constant cache for the PQ
        # codes-only fused kernel (ops/pq_gmin.py)
        from weaviate_tpu.ops.gmin_scan import KernelState

        self._pqg_state = KernelState()
        self._pqg_cb = None  # (pq identity, cb_chunks dev, flat_cb dev)
        # separate failure domain + codebook-constant cache for the 4-bit
        # funnel kernel family (ops/pq4.py): a Mosaic failure of the 4-bit
        # scan must not poison the 8-bit paths, and vice versa
        self._pq4_state = KernelState()
        self._pq4_cb = None  # (pq4 identity, cb4 chunks dev, dense cb4 dev)
        # per-stage funnel survivor accounting for health()["pq"], updated
        # per funnel dispatch under a leaf lock (lock_hierarchy level 45 —
        # nothing ever nests inside it)
        self._pq4_lock = sanitizers.register_lock(
            threading.Lock(), "index.tpu.pq4")
        self._pq4_stats = {"dispatches": 0, "stage1_rows": 0,
                           "stage2_survivors": 0, "stage3_survivors": 0}
        # per-store-generation [ncols, G*D] rescore-block layouts (see
        # gmin_scan.build_rescore_blocks): keyed by the exact device array
        # object — every write replaces the store array with a fresh copy
        # (copy-on-write, nothing donated: snapshots may still pin the old
        # generation), so object identity IS the write generation. Strong
        # refs keep ids stable.
        self._blk_cache: dict = {}
        # -- IVF scan plane (ROADMAP item 3; ops/ivf.py) ----------------
        # device slabs (None until the write path trains a layout):
        # centroids [nlist, D] f32, padded partition buckets
        # [nlist, cap_p] i32 (-1 padding), optional PCA projection
        # [D, dp] + per-slot low-dim rows [capacity, dp] — all
        # JGL012-stamped, all replaced wholesale (never donated) so
        # published snapshots can pin them
        self._ivf_centroids = None
        self._ivf_buckets = None
        self._ivf_pca_proj = None
        self._ivf_pca_rows = None
        # host twins: centroid matrix + PCA basis for write-path
        # assignment, per-slot partition assignment (-1 = unassigned),
        # per-partition fills for health, layout metadata
        self._ivf_centroids_host: Optional[np.ndarray] = None
        self._ivf_pca_host: Optional[np.ndarray] = None
        self._ivf_assign = np.zeros(0, dtype=np.int32)
        self._ivf_fills: Optional[np.ndarray] = None
        self._ivf_meta: Optional[tuple[int, int, int]] = None
        self._ivf_cap_p: Optional[int] = None
        # freshly-written (slots, partitions) runs awaiting the O(batch)
        # incremental bucket fold at the next snapshot publish
        self._ivf_pending_slots: list[tuple[np.ndarray, np.ndarray]] = []
        self._ivf_trained_n = 0
        self._ivf_gen = 0            # recluster generation (health)
        self._ivf_dirty = False      # buckets stale vs assignments
        # probe-accounting counters (health / bench probed_fraction),
        # updated per IVF dispatch under a leaf lock (lock_hierarchy
        # level 45 — nothing ever nests inside it)
        self._ivf_lock = sanitizers.register_lock(
            threading.Lock(), "index.tpu.ivf")
        self._ivf_stats = {"dispatches": 0, "probed_rows": 0,
                           "base_rows": 0}
        # host f32 copy of the store (+ its row sq-norms) for the breaker's
        # fallback plane (search_by_vectors_host), built once per snapshot
        # generation — (gen, rows, sq_norms)
        self._host_rows_cache: Optional[
            tuple[int, np.ndarray, np.ndarray]] = None
        # compiled-shape keys (b, k, rg, active_g, use_allow) that completed a
        # materialized search — each key is its own Mosaic compilation, so one
        # small-shape success must not vouch for a larger VMEM footprint
        self._gmin_validated: set = set()
        self._gmin_shape_broken: set = set()  # keys Mosaic rejected
        # host-memory provider (monitoring/memory.py): the slot/tombstone
        # mirrors, PQ host rows, staged rows, and the breaker's fallback
        # cache become /debug/memory host components. Weakref-held — the
        # registry never outlives the index.
        memory.register_host_provider(self, memory.index_host_components)
        self._log = VectorLog(os.path.join(shard_path, "vector.log")) if persist else None
        if self._log is not None:
            self._restore()

    # -- lifecycle -----------------------------------------------------------

    def _restore(self) -> None:
        """Replay the vector log (startup.go:56 restoreFromDisk analog); if a
        persisted PQ codebook exists, re-enter compressed mode (the analog of
        commit-log AddPQ replay, deserializer.go) — codes are re-derived on
        device, which beats persisting them."""
        self._restoring = True
        try:
            replay_stats: dict = {}
            for op, ids, vecs in VectorLog.replay_batches(self._log.path, stats=replay_stats):
                if op == "add":
                    self._bulk_stage_add(ids, vecs)
                else:
                    self._stage_delete(int(ids), log=False)
            VectorLog.report_replay_stats(self._log.path, replay_stats)
            self.last_replay_stats = replay_stats
            if os.path.exists(self._pq_path):
                from weaviate_tpu.compress.pq import ProductQuantizer

                self._flush_pending()
                if self.n > 0:
                    try:
                        pq = ProductQuantizer.load(self._pq_path)
                        vecs = np.asarray(self._store[: self.n], dtype=np.float32)
                        self._enable_pq(pq, vecs, save=False)
                    except Exception as e:  # noqa: BLE001 — see below
                        # a pq.npz this build cannot use — rejected config
                        # (hamming), corrupt zip, missing key, dim mismatch —
                        # must not make the shard unloadable: serve
                        # uncompressed with a warning AND a fallback count
                        # (a fleet of shards quietly serving uncompressed is
                        # a capacity incident, not a log line)
                        import logging

                        self.config.pq.enabled = False
                        record_device_fallback(
                            "index.tpu.restore", "pq_codebook_rejected", e,
                            log=False)
                        logging.getLogger(__name__).warning(
                            "persisted pq codebook rejected (%s: %s); "
                            "serving uncompressed", type(e).__name__, e)
        finally:
            self._restoring = False

    def post_startup(self) -> None:
        self._flush_pending()

    # -- device plumbing -----------------------------------------------------

    def _init_device(self, dim: int) -> None:
        self.dim = dim
        self.capacity = _MIN_CAPACITY
        dev = self.device
        self._store = jax.device_put(jnp.zeros((self.capacity, dim), self.dtype), dev)
        self._sq_norms = jax.device_put(jnp.zeros((self.capacity,), jnp.float32), dev)
        self._tombs = jax.device_put(jnp.zeros((self.capacity,), jnp.bool_), dev)
        self._slot_to_doc = np.full(self.capacity, -1, dtype=np.int64)
        self._s2d_dev = jax.device_put(
            jnp.full((self.capacity, 2), _S2D_FILL, jnp.uint32), dev)
        self._host_tombs = np.zeros(self.capacity, dtype=bool)
        self._stamp_memory()

    def _ensure_capacity(self, needed: int) -> None:
        if self._store is None and self._codes is None:
            raise RuntimeError("store not initialised")
        cap = self.capacity
        while cap < needed:
            cap *= 2  # geometric growth (maintainance.go:31)
        if cap != self.capacity:
            faults.fire("index.tpu.alloc")
            if self.compressed:
                self._codes = _grow_store(self._codes, cap)
                hv = np.zeros((cap, self.dim), np.float32)
                hv[: self.capacity] = self._host_vecs
                self._host_vecs = hv
                if self._rescore_dev is not None:
                    self._rescore_dev = _grow_store(self._rescore_dev, cap)
                    if self._rescore_sq_norms is not None:
                        self._rescore_sq_norms = _grow_1d(
                            self._rescore_sq_norms, cap, jnp.float32(0))
                self._recon_norms = _grow_1d(self._recon_norms, cap, jnp.float32(0))
                if self._codes4 is not None:
                    self._codes4 = _grow_store(self._codes4, cap)
                    self._recon_norms4 = _grow_1d(
                        self._recon_norms4, cap, jnp.float32(0))
            else:
                self._store = _grow_store(self._store, cap)
                self._sq_norms = _grow_1d(self._sq_norms, cap, jnp.float32(0))
            self._tombs = _grow_1d(self._tombs, cap, False)
            if self._s2d_dev is not None:
                self._s2d_dev = _grow_pairs(self._s2d_dev, cap)
            if self._ivf_pca_rows is not None:
                self._ivf_pca_rows = _grow_store(self._ivf_pca_rows, cap)
            if self._ivf_assign.size:
                ia = np.full(cap, -1, np.int32)
                ia[: self.capacity] = self._ivf_assign[: self.capacity]
                self._ivf_assign = ia
            s2d = np.full(cap, -1, dtype=np.int64)
            s2d[: self.capacity] = self._slot_to_doc
            self._slot_to_doc = s2d
            ht = np.zeros(cap, dtype=bool)
            ht[: self.capacity] = self._host_tombs
            self._host_tombs = ht
            self.capacity = cap
            led = memory.get_ledger()
            if led is not None:
                led.note_write_shape(
                    ("grow", cap, self.dim or 0, self.compressed))
            self._stamp_memory()

    def _write_block(self, rows: np.ndarray, start: int) -> None:
        """Land [count, D] float32 rows at slots [start, start+count) in
        fixed-size chunks (one compiled shape). In compressed mode the chunk
        is PQ-encoded on device and only the codes hit HBM; the float rows go
        to the host-side rescoring store."""
        count = rows.shape[0]
        off = 0
        while off < count:
            take = min(_CHUNK, count - off)
            chunk = np.zeros((_CHUNK, self.dim), dtype=np.float32)
            chunk[:take] = rows[off : off + take]
            self._ensure_capacity(start + off + _CHUNK)
            if self.compressed:
                codes = self._pq.encode(chunk)  # [_CHUNK, M]
                self._codes = _write_rows(self._codes, jnp.asarray(codes), start + off)
                self._recon_norms = _write_norms(
                    self._recon_norms,
                    jnp.asarray(self._pq.recon_sq_norms(codes)),
                    start + off,
                )
                if self._pq4 is not None:
                    from weaviate_tpu.compress import pq as pq_mod

                    codes4 = self._pq4.encode(chunk)  # [_CHUNK, M] 0..15
                    self._codes4 = _write_rows(
                        self._codes4,
                        jnp.asarray(pq_mod.pack_codes4(codes4)),
                        start + off)
                    self._recon_norms4 = _write_norms(
                        self._recon_norms4,
                        jnp.asarray(self._pq4.recon_sq_norms(codes4)),
                        start + off)
                if self._rescore_dev is not None:
                    self._rescore_dev = _write_rows(
                        self._rescore_dev, jnp.asarray(chunk, jnp.bfloat16), start + off
                    )
                    if self._rescore_sq_norms is not None:
                        self._rescore_sq_norms = _write_norms(
                            self._rescore_sq_norms,
                            jnp.asarray(np.einsum("ij,ij->i", chunk, chunk,
                                                  dtype=np.float64)
                                        .astype(np.float32)),
                            start + off,
                        )
            else:
                self._store = _write_rows(self._store, jnp.asarray(chunk, self.dtype), start + off)
                if self.metric == vi.DISTANCE_L2:
                    nchunk = jnp.asarray((chunk.astype(np.float64) ** 2).sum(1).astype(np.float32))
                    self._sq_norms = _write_norms(self._sq_norms, nchunk, start + off)
            off += take
        if self.compressed:
            self._host_vecs[start : start + count] = rows
        self._ivf_on_rows_written(rows, start)
        led = memory.get_ledger()
        if led is not None:
            led.note_write_shape(
                ("write_rows", self.capacity, self.dim, self.compressed))
        self._stamp_memory()

    def _stage_add(self, doc_id: int, vector: np.ndarray, log: bool = True) -> None:
        vector = np.asarray(vector, dtype=np.float32)
        if self.metric == vi.DISTANCE_COSINE:
            nrm = float(np.linalg.norm(vector))
            if nrm > 0:
                vector = vector / nrm
        if self.dim is None:
            self._init_device(int(vector.shape[0]))
        elif vector.shape[0] != self.dim:
            raise ValueError(f"dim mismatch: index has {self.dim}, got {vector.shape[0]}")
        # gen bump AFTER validation: a rejected add must not dirty the
        # published snapshot and push the next reader onto the locked path
        self._staged_gen += 1
        self._mark_staged()
        old = self._doc_to_slot.pop(doc_id, None)
        if old is not None:
            self._pending_tombs.append(old)
            self.live -= 1
        if doc_id in self._pending:
            self.live -= 1
        self._pending[doc_id] = vector
        self.live += 1
        if log and self._log is not None:
            self._log.append_add(doc_id, vector)
        if len(self._pending) >= _CHUNK:
            self._flush_pending()

    def _bulk_stage_add(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        """Restore-path bulk staging with _stage_add's exact semantics
        (keep-last for duplicate docs in the run, per-record path for docs
        the index already knows so their old slots tombstone correctly).
        Tiny runs stay per-record; mid-size runs feed the staging buffer in
        one dict update; only runs of at least a full device chunk
        direct-write — a padded _CHUNK write per fragmented run would make
        churned logs restore SLOWER than the per-record path."""
        if len(ids) < 256:
            for d, v in zip(ids.tolist(), vecs):
                self._stage_add(int(d), v, log=False)
            return
        if self.dim is None:
            self._init_device(int(np.asarray(vecs).shape[1]))
        elif np.asarray(vecs).shape[1] != self.dim:
            raise ValueError(
                f"dim mismatch: index has {self.dim}, got {np.asarray(vecs).shape[1]}")
        d2s = self._doc_to_slot
        ids64, vecs, known = _prep_bulk_run(
            ids, vecs, self.metric,
            lambda d: d in d2s or d in self._pending)
        if known:
            for i in known:
                self._stage_add(int(ids64[i]), vecs[i], log=False)
            keep = np.ones(len(ids64), bool)
            keep[known] = False
            ids64, vecs = ids64[keep], vecs[keep]
            if len(ids64) == 0:
                return
        if len(ids64) < _CHUNK:
            self._pending.update(zip(ids64.tolist(), vecs))
            self.live += len(ids64)
            if len(self._pending) >= _CHUNK:
                self._flush_pending()
            return
        self._flush_pending()  # earlier staged singles keep their slots
        count = len(ids64)
        self._staged_gen += 1
        self._mark_staged()
        self._ensure_capacity(self.n + count)
        self._cow_host_state()
        self._write_block(np.ascontiguousarray(vecs), self.n)
        self._slot_to_doc[self.n : self.n + count] = ids64
        self._stage_doc_ids(ids64, self.n)
        d2s.update(zip(ids64.tolist(), range(self.n, self.n + count)))
        self.n += count
        self.live += count

    def _stage_delete(self, doc_id: int, log: bool = True) -> None:
        slot = self._doc_to_slot.pop(doc_id, None)
        if slot is None:
            # may still be in the staging buffer; an unknown doc changes
            # nothing and must not dirty the published snapshot
            if doc_id in self._pending:
                del self._pending[doc_id]
                self.live -= 1
                self._staged_gen += 1
                self._mark_staged()
                if log and self._log is not None:
                    self._log.append_delete(doc_id)
            return
        self._pending_tombs.append(slot)
        self.live -= 1
        self._staged_gen += 1
        self._mark_staged()
        if log and self._log is not None:
            self._log.append_delete(doc_id)

    def _stage_doc_ids(self, docs: np.ndarray, start: int) -> None:
        """Mirror a run of newly-assigned slot->doc entries onto the
        DEVICE translation table (the fused dispatch's in-program
        slot->doc source). Row counts pad to _bucket_rows so the scatter's
        jit shapes stay bounded; padding rows carry an out-of-range slot
        index that mode="drop" ignores. Runs under the write lock, before
        _publish_snapshot — the staged-generation handshake that makes the
        device table and the host mirror describe the same mapping."""
        if self._s2d_dev is None:
            return
        count = len(docs)
        pad = _bucket_rows(count)
        idx = np.full(pad, self.capacity + 1, dtype=np.int32)
        idx[:count] = np.arange(start, start + count, dtype=np.int32)
        pairs = np.zeros((pad, 2), dtype=np.uint32)
        pairs[:count] = np.ascontiguousarray(
            docs.astype("<i8")).view("<u4").reshape(count, 2)
        self._s2d_dev = _write_doc_pairs(
            self._s2d_dev, jnp.asarray(idx), jnp.asarray(pairs))
        led = memory.get_ledger()
        if led is not None:
            led.note_write_shape(("write_docs", self.capacity, pad))
        self._stamp_memory()

    def _cow_host_state(self) -> None:
        """Copy-on-write the host mirrors a published snapshot still pins,
        so in-place writer mutation can never tear a lock-free reader.
        Only `host_tombs` needs the copy (deletes flip bits at arbitrary
        live slots); `slot_to_doc` is append-only between compactions —
        writers assign only at indices >= every published snapshot's `n`,
        so the `[:n]` prefix a snapshot reads is immutable in place and
        the per-flush O(capacity) copy the fused-dispatch PR deleted was
        pure overhead."""
        snap = self._snap
        if snap is None:
            return
        copied = 0
        if snap.host_tombs is self._host_tombs:
            self._host_tombs = self._host_tombs.copy()
            copied += int(self._host_tombs.nbytes)
        if copied:
            led = memory.get_ledger()
            if led is not None:
                led.note_cow(copied)

    def _flush_pending(self) -> None:
        flushed = bool(self._pending or self._pending_tombs)
        led = memory.get_ledger()
        if flushed:
            self._cow_host_state()
        if self._pending:
            t0 = time.perf_counter()
            rows = np.stack(list(self._pending.values()))
            docs = np.array(list(self._pending.keys()), dtype=np.int64)
            count = rows.shape[0]
            self._ensure_capacity(self.n + count)
            if led is not None:
                # the non-donating write pass transiently holds BOTH the
                # old and new buffer generations (the snapshot-isolation
                # trade) — record the per-flush peak
                led.note_cow(0, transient_peak=self._write_transient_bytes())
            # chunked writes pad the tail; capacity is padded in _CHUNK
            # multiples beyond need so padding only lands in unused slots
            self._write_block(rows, self.n)
            self._slot_to_doc[self.n : self.n + count] = docs
            self._stage_doc_ids(docs, self.n)
            for i, d in enumerate(docs):
                self._doc_to_slot[int(d)] = self.n + i
            self.n += count
            self._pending.clear()
            self._obs_index("add", "flush", t0, ops=count)
            if led is not None:
                led.note_write(
                    "add", "flush", (time.perf_counter() - t0) * 1000.0,
                    rows=count, bytes_moved=count * (self.dim or 0) * 4)
        if self._pending_tombs:
            t0 = time.perf_counter()
            idx = np.array(self._pending_tombs, dtype=np.int32)
            pad = _bucket_rows(len(idx))
            padded = np.full(pad, self.capacity + 1, dtype=np.int32)
            padded[: len(idx)] = idx
            self._tombs = _set_tombstones(self._tombs, jnp.asarray(padded))
            self._host_tombs[idx] = True
            self._obs_index("delete", "apply_tombstones", t0,
                            ops=len(self._pending_tombs))
            if led is not None:
                led.note_write(
                    "delete", "apply_tombstones",
                    (time.perf_counter() - t0) * 1000.0,
                    rows=len(self._pending_tombs))
                led.note_write_shape(("set_tombstones", self.capacity, pad))
            self._pending_tombs.clear()
        if flushed:
            # gauges refresh only when state changed: _flush_pending runs at
            # the top of every search and must stay free on the hot path
            self._update_index_gauges()
        self._maybe_declared_compress()
        self._maybe_ivf_train()
        if flushed or self._published_gen != self._staged_gen:
            # publication is the LAST step: readers grabbing the new
            # reference must see every staged mutation already applied
            self._publish_snapshot()

    def _maybe_declared_compress(self) -> None:
        # pq.enabled set at class creation: compress once enough data exists
        # to fit codebooks (the reference requires an explicit post-import
        # config update; we also honor the declarative form). Evaluated on
        # every flush AND every direct batch write — the snapshot read path
        # no longer flushes on each search, so writes must carry the trigger
        if (
            self.config.pq.enabled
            and not self.compressed
            and not self._restoring
            and self.n >= max(256, self.config.pq.centroids)
        ):
            try:
                self._compress_locked()
            except vi.ConfigValidationError as e:
                # a pq config that only turns out invalid once dims are
                # known (declared before the first import) must not turn
                # every later add/search into an error: auto-disable with a
                # warning and keep serving uncompressed
                import logging

                self.config.pq.enabled = False
                logging.getLogger(__name__).warning(
                    "declared pq config is invalid (%s); auto-disabling "
                    "compression for this index", e)

    # -- IVF scan plane: write-path training / layout maintenance ------------
    # (ROADMAP item 3.) The clustered layout is WRITE-PATH state like the
    # PQ codebook: k-means trains under the index lock once enough rows
    # exist, every later row run is assigned to its nearest centroid as
    # it lands (host matmul over the rows the write already holds — no
    # device fetch), and the padded partition buckets are rebuilt before
    # the next snapshot publish so readers always see a layout that
    # matches the slot space they dispatch on. All of it is a
    # one-comparison no-op while IVF_ENABLED is off.

    def _ivf_on_rows_written(self, rows: np.ndarray, start: int) -> None:
        """Assign a freshly-written row run to the trained layout (and
        mirror its PCA projection onto the device low-dim table). Rides
        _write_block, so every write path — flush, bulk import, restore,
        compact rebuild — maintains the layout through one hook."""
        cent = self._ivf_centroids_host
        if cent is None:
            return
        count = rows.shape[0]
        assign = ivf_ops.assign_partitions(rows, cent)
        if self._ivf_assign.shape[0] < self.capacity:
            ia = np.full(self.capacity, -1, np.int32)
            ia[: self._ivf_assign.shape[0]] = self._ivf_assign
            self._ivf_assign = ia
        self._ivf_assign[start: start + count] = assign
        if self._ivf_pca_host is not None:
            self._write_ivf_pca(rows @ self._ivf_pca_host, start)
        # queue the run for the O(batch) incremental bucket fold at the
        # next publish (_ivf_apply_pending)
        self._ivf_pending_slots.append(
            (np.arange(start, start + count, dtype=np.int32), assign))
        self._ivf_dirty = True

    def _write_ivf_pca(self, block: np.ndarray, start: int) -> None:
        """Scatter a [count, dp] PCA row run into the device table,
        padded to the shared pow2 row buckets (bounded jit shapes)."""
        if self._ivf_pca_rows is None:
            return
        count = block.shape[0]
        pad = _bucket_rows(count)
        idx = np.full(pad, self.capacity + 1, dtype=np.int32)
        idx[:count] = np.arange(start, start + count, dtype=np.int32)
        rows = np.zeros((pad, block.shape[1]), np.float32)
        rows[:count] = block
        self._ivf_pca_rows = _scatter_rows(
            self._ivf_pca_rows, jnp.asarray(idx), jnp.asarray(rows))
        self._stamp_memory()

    def _ivf_nlist(self, s: IvfConfig, n: int) -> int:
        """Partition count for an n-row layout: the configured value, or
        auto targeting ~256 rows per partition snapped to a pow2 —
        measured on the CPU A/B, fill-targeted sizing beats the sqrt(n)
        rule by 2-4x in both probe recall and probed_fraction (finer
        partitions localize better AND shrink the padded bucket the
        probe pays for); bounded so no layout averages fewer than ~32
        rows per partition."""
        if s.nlist > 0:
            return max(1, min(s.nlist, max(n // 8, 1)))
        import math

        # ceil, not round: rounding DOWN doubles the mean fill (and with
        # it the padded bucket every probe reads). The 4096 ceiling is
        # the HOST k-means budget: training is a write-lock pause, and
        # past ~4096 partitions the fit/assignment cost stops being one
        # (device-side training is the 10M-scale follow-up, ROADMAP
        # item 3) — beyond it the layout goes coarser, not slower
        target = 2 ** int(math.ceil(math.log2(max(n / 256.0, 16.0))))
        return int(max(16, min(target, 4096, max(n // 32, 16))))

    def _ivf_rows_for_training(self) -> np.ndarray:
        """The occupied store rows, host-side, for k-means/PCA fitting.
        Under PQ the f32 rows already live host-side (host_vecs); the
        uncompressed store pays ONE bulk fetch under the write lock —
        the same stop-the-world cold-path trade as compact/compress
        (the graftsan baseline carries the mirrored runtime waiver)."""
        if self.compressed and self._host_vecs is not None:
            return self._host_vecs[: self.n]
        return np.asarray(self._store[: self.n]).astype(np.float32, copy=False)  # graftlint: disable=JGL001 recluster is a write-path cold pass like compress: the k-means fit runs host-side, so the store must materialize once under the lock that covers the layout swap

    def _maybe_ivf_train(self) -> None:
        """Declarative training/recluster trigger (the write-path twin of
        _maybe_declared_compress): train once min_n rows exist, retrain
        once n outgrows the trained layout by retrain_growth. One
        comparison while IVF is disabled."""
        s = ivf_settings()
        if s is None or self._restoring or self.dim is None:
            return
        if self.metric not in ivf_ops.MATMUL_METRICS:
            return
        if self.n < max(s.min_n, 256):
            return
        if self._ivf_centroids is not None and \
                self.n < self._ivf_trained_n * (1.0 + s.retrain_growth):
            return
        self._ivf_train_locked(s)

    def _ivf_train_locked(self, s: IvfConfig) -> None:
        """Train (or re-train) the clustered layout: k-means centroids,
        full partition assignment, optional PCA basis + low-dim rows,
        padded buckets — then a fresh snapshot publishes it. Runs under
        the index write lock (callers hold it); a recluster replaces
        every IVF array wholesale, so snapshots pinned by in-flight
        dispatches keep their old layout (the COW discipline)."""
        t0 = time.perf_counter()
        n = self.n
        rows = self._ivf_rows_for_training()
        nlist = self._ivf_nlist(s, n)
        # sample floors at 16 rows per centroid: capping at train_sample
        # alone would degenerate a large-nlist fit to ~one row per
        # cluster (the layout would be the sample, not a clustering)
        cent = ivf_ops.kmeans_fit(
            rows, nlist, iters=s.train_iters, seed=self._ivf_gen,
            sample=min(len(rows), max(s.train_sample, nlist * 16)))
        if self.metric == vi.DISTANCE_COSINE:
            nrm = np.linalg.norm(cent, axis=1, keepdims=True)
            nrm[nrm == 0] = 1.0
            cent = cent / nrm
        # capacity-bounded buckets (ops/ivf.balanced_assign): the padded
        # width is pinned by the MEAN fill with 25% slack — pow2-snapped
        # — instead of by the worst cluster, so skewed data cannot make
        # every probe pay a worst-case-sized bucket read; overfull
        # partitions spill their farthest rows to the nearest centroid
        # with space
        cap_t = ivf_ops.bucket_capacity(
            np.array([int(1.25 * n / nlist) + 1]))
        assign = np.full(self.capacity, -1, np.int32)
        assign[:n] = ivf_ops.balanced_assign(rows, cent, cap_t)
        self._ivf_cap_p = cap_t
        self._ivf_centroids_host = cent
        self._ivf_assign = assign
        self._ivf_centroids = jax.device_put(jnp.asarray(cent), self.device)
        dp = int(s.pca_dim)
        if 0 < dp < self.dim:
            # a RANDOM sample, like the k-means fit — a prefix slice
            # would bias the basis to insertion-ordered data (early
            # tenants/domains) and silently misrank later rows
            psamp = min(len(rows), max(s.train_sample, 4096))
            if psamp < len(rows):
                pick = np.random.default_rng(self._ivf_gen).choice(
                    len(rows), size=psamp, replace=False)
                proj = ivf_ops.pca_fit(rows[pick], dp)
            else:
                proj = ivf_ops.pca_fit(rows, dp)
            self._ivf_pca_host = proj
            self._ivf_pca_proj = jax.device_put(
                jnp.asarray(proj), self.device)
            pr = np.zeros((self.capacity, dp), np.float32)
            pr[:n] = rows @ proj
            self._ivf_pca_rows = jax.device_put(jnp.asarray(pr), self.device)
        else:
            self._ivf_pca_host = None
            self._ivf_pca_proj = None
            self._ivf_pca_rows = None
        self._ivf_trained_n = n
        self._ivf_gen += 1
        self._ivf_rebuild_buckets()  # keeps the balanced cap_t padding
        self._staged_gen += 1
        self._mark_staged()
        self._stamp_memory()
        ms = (time.perf_counter() - t0) * 1000.0
        led = memory.get_ledger()
        if led is not None:
            led.note_write("ivf", "recluster", ms, rows=n)
        incidents.emit("write_phase", scope="ivf_recluster", rows=n,
                       nlist=nlist, ms=round(ms, 1))

    def _ivf_apply_pending(self) -> None:
        """Fold freshly-written slots into the padded buckets: an
        O(batch) device scatter into each bucket's free columns (fills
        tracked host-side), so a small write's flush cost stays O(batch)
        like the flat write path — the full O(n log n) rebuild + whole-
        table upload runs only when a bucket overflows its padding
        (which widens it) or after a retrain."""
        pend, self._ivf_pending_slots = self._ivf_pending_slots, []
        if self._ivf_buckets is None or self._ivf_fills is None or not pend:
            self._ivf_rebuild_buckets()
            return
        slots = np.concatenate([s for s, _ in pend])
        parts = np.concatenate([p for _, p in pend])
        nlist = self._ivf_fills.shape[0]
        counts = np.bincount(parts, minlength=nlist)
        if bool((self._ivf_fills + counts > self._ivf_cap_p).any()):
            self._ivf_rebuild_buckets()
            return
        order = np.argsort(parts, kind="stable")
        sp, ss = parts[order], slots[order]
        starts = np.zeros(nlist + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        cols = (np.arange(sp.size, dtype=np.int64) - starts[sp]
                + self._ivf_fills[sp]).astype(np.int32)
        pad = _bucket_rows(sp.size)
        pi = np.full(pad, nlist + 1, np.int32)  # out of range: dropped
        ci = np.zeros(pad, np.int32)
        si = np.full(pad, -1, np.int32)
        pi[: sp.size] = sp
        ci[: sp.size] = cols
        si[: sp.size] = ss
        self._ivf_buckets = _scatter_bucket(
            self._ivf_buckets, jnp.asarray(pi), jnp.asarray(ci),
            jnp.asarray(si))
        self._ivf_fills = self._ivf_fills + counts
        self._ivf_dirty = False
        self._stamp_memory()

    def _ivf_rebuild_buckets(self) -> None:
        """Rebuild the padded partition buckets from the host assignment
        (one vectorized bucket sort + one device upload). The padding
        width cap_p is KEPT while every bucket still fits — the
        jit-shape stability contract: a handful of inserts re-uploads
        the [nlist, cap_p] table but never re-compiles the search — and
        pow2-widens only on overflow."""
        cent = self._ivf_centroids_host
        if cent is None:
            return
        nlist = cent.shape[0]
        buckets, fills = ivf_ops.build_buckets(
            self._ivf_assign, nlist, self._ivf_cap_p)
        self._ivf_cap_p = int(buckets.shape[1])
        self._ivf_fills = fills
        self._ivf_buckets = jax.device_put(jnp.asarray(buckets), self.device)
        self._ivf_meta = (nlist, self._ivf_cap_p, self._ivf_gen)
        self._ivf_pending_slots = []  # the rebuild covered them
        self._ivf_dirty = False
        self._stamp_memory()

    def _ivf_reset(self) -> None:
        """Drop the whole IVF layout (compact's rebuild and drop() call
        this before wiping the slot space the assignments index)."""
        self._ivf_centroids = None
        self._ivf_buckets = None
        self._ivf_pca_proj = None
        self._ivf_pca_rows = None
        self._ivf_centroids_host = None
        self._ivf_pca_host = None
        self._ivf_assign = np.zeros(0, dtype=np.int32)
        self._ivf_fills = None
        self._ivf_meta = None
        self._ivf_cap_p = None
        self._ivf_pending_slots = []
        self._ivf_trained_n = 0
        self._ivf_dirty = False

    def ivf_stats(self) -> dict:
        """Cumulative probe accounting (bench probed_fraction rows and
        the health() block): dispatches served by the IVF plane, rows
        the probes actually scanned (top_p x cap_p, padding included —
        the honest device-work count), and the flat-scan rows each
        dispatch WOULD have scanned."""
        with self._ivf_lock:
            st = dict(self._ivf_stats)
        st["probed_fraction"] = round(
            st["probed_rows"] / st["base_rows"], 4) if st["base_rows"] \
            else None
        return st

    # -- memory ledger stamping (monitoring/memory.py) -----------------------

    def _memory_components(self) -> dict:
        """Analytic byte sizes of every device buffer this index holds —
        shapes x dtypes only (zero syncs); each value equals the buffer's
        ``nbytes`` exactly. The bounded component names are the
        memory.DEVICE_COMPONENTS taxonomy."""
        comps: dict = {}
        for name, arr in (("store", self._store),
                          ("sq_norms", self._sq_norms),
                          ("tombs", self._tombs),
                          ("slot_to_doc", self._s2d_dev),
                          ("pq_codes", self._codes),
                          ("recon_norms", self._recon_norms),
                          ("pq4_codes", self._codes4),
                          ("pq4_norms", self._recon_norms4),
                          ("opq_rot", self._opq_rot_dev),
                          ("rescore_store", self._rescore_dev),
                          ("rescore_sq_norms", self._rescore_sq_norms),
                          ("ivf_centroids", self._ivf_centroids),
                          ("ivf_buckets", self._ivf_buckets),
                          ("ivf_pca_proj", self._ivf_pca_proj),
                          ("ivf_pca_rows", self._ivf_pca_rows)):
            b = memory.array_bytes(arr)
            if b:
                comps[name] = b
        return comps

    def _stamp_memory(self) -> None:
        """Stamp the ledger with this index's current device components
        (the JGL012-registered snapshot-builder hook: every method that
        binds a device buffer to a snapshot field flows through here or
        through _publish_snapshot). One comparison when unconfigured."""
        led = memory.get_ledger()
        if led is not None:
            led.stamp_device(self, self._memory_components())

    def _mark_staged(self) -> None:
        """Record the first staged-but-unpublished mutation's time so
        publication can report the staged-generation lag."""
        if self._staged_t0 is None and memory.get_ledger() is not None:
            self._staged_t0 = time.perf_counter()

    def _write_transient_bytes(self) -> int:
        """Device bytes transiently DOUBLED by one non-donating write
        pass: the replaced buffer generations stay alive (pinned by
        snapshots / the functional update) while the new ones build."""
        # every IVF slab is functionally replaced by its write/fold
        # kernel (pca scatter, bucket fold) or wholesale on recluster —
        # the old generation stays snapshot-pinned while the new builds
        ivf = (memory.array_bytes(self._ivf_pca_rows)
               + memory.array_bytes(self._ivf_buckets)
               + memory.array_bytes(self._ivf_centroids)
               + memory.array_bytes(self._ivf_pca_proj))
        if self.compressed:
            return (memory.array_bytes(self._codes)
                    + memory.array_bytes(self._recon_norms)
                    + memory.array_bytes(self._codes4)
                    + memory.array_bytes(self._recon_norms4)
                    + memory.array_bytes(self._rescore_dev)
                    + memory.array_bytes(self._rescore_sq_norms)
                    + memory.array_bytes(self._s2d_dev) + ivf)
        return (memory.array_bytes(self._store)
                + memory.array_bytes(self._sq_norms)
                + memory.array_bytes(self._s2d_dev) + ivf)

    # -- snapshot publication / lock-free reads ------------------------------

    def _publish_snapshot(self) -> None:
        """Publish the current device state as a new immutable snapshot
        (one reference swap — callers hold self._lock). Always the LAST
        step of a mutation: a reader that grabs the new reference sees a
        fully applied write."""
        if self._ivf_dirty:
            # partition assignments changed since the last bucket build:
            # the buckets a snapshot carries must describe exactly the
            # slot space its other arrays hold (the staged-generation
            # handshake, extended to the partition table)
            self._ivf_apply_pending()
        self._snap_gen += 1
        self._snap = IndexSnapshot(self._snap_gen, self)
        self._published_gen = self._staged_gen
        m = self.metrics
        if m is not None:
            cls, shard = self._metric_labels()
            m.index_snapshot_gen.labels(cls, shard).set(self._snap_gen)
        self._stamp_memory()
        led = memory.get_ledger()
        if led is not None and self._staged_t0 is not None:
            led.note_publish(
                (time.perf_counter() - self._staged_t0) * 1000.0)
        self._staged_t0 = None

    def _read_snapshot(self) -> IndexSnapshot:
        """The snapshot a search dispatches on. Fast path: one reference
        read and one generation compare, NO lock — concurrent writers
        cannot block it. Slow path (staged writes not yet published, or
        never published): take the write lock once, flush + publish, and
        observe the wait — this is the read-your-writes pre-read check,
        paid only by the first read after a write."""
        snap = self._snap
        if snap is not None and self._published_gen == self._staged_gen:
            self._read_local.lock_wait_ms = 0.0
            return snap
        t0 = time.perf_counter()
        with self._lock:
            wait_ms = (time.perf_counter() - t0) * 1000.0
            self._flush_pending()
            if self._snap is None or self._published_gen != self._staged_gen:
                self._publish_snapshot()
            snap = self._snap
        self._read_local.lock_wait_ms = wait_ms
        m = self.metrics
        if m is not None:
            cls, shard = self._metric_labels()
            m.index_lock_wait.labels(cls, shard).observe(wait_ms)
        return snap

    def pop_read_lock_wait(self) -> float:
        """ms the CALLING thread's last snapshot read waited on the write
        lock (0.0 on the lock-free fast path); reading clears it. The shard
        layer attaches it as a dispatch trace fact."""
        w = getattr(self._read_local, "lock_wait_ms", 0.0)
        self._read_local.lock_wait_ms = 0.0
        return w

    @property
    def snapshot_gen(self) -> int:
        """Published snapshot generation (0 = never published)."""
        snap = self._snap
        return snap.gen if snap is not None else 0

    def _track_inflight(self, delta: int) -> None:
        """Enqueued-but-not-finalized dispatch count (the read pipeline's
        depth). The labeled gauge child resolves ONCE — per-dispatch cost
        is one small lock and one gauge set."""
        with self._inflight_lock:
            self._inflight += delta
            val = self._inflight
        g = self._inflight_gauge
        if g is None:
            if self.metrics is None:
                return
            cls, shard = self._metric_labels()
            g = self.metrics.index_inflight_dispatches.labels(cls, shard)
            self._inflight_gauge = g
        g.set(val)

    # -- product quantization (compress.go analog) ---------------------------

    def compress(self) -> None:
        """Fit PQ on the current store, encode all rows, swap the device
        float store for codes (compress.go:39: fit on cached vectors, encode,
        persist codebook, drop float cache, flip compressed)."""
        with self._lock:
            self._pending_flush_for_compress()
            self._compress_locked()

    def _pending_flush_for_compress(self) -> None:
        if self._pending or self._pending_tombs:
            self._flush_pending()

    def _compress_locked(self) -> None:
        from weaviate_tpu.compress.pq import ProductQuantizer

        if self.compressed:
            return
        if self.n == 0:
            raise RuntimeError("compress requires imported vectors to fit on")
        pq = ProductQuantizer(
            dim=self.dim,
            segments=self.config.pq.segments,
            centroids=self.config.pq.centroids,
            metric=self.metric,
            encoder=self.config.pq.encoder.type,
            distribution=self.config.pq.encoder.distribution,
            rotation=self.config.pq.rotation,
        )
        vecs = np.asarray(self._store[: self.n], dtype=np.float32)
        pq.fit(vecs)
        self._enable_pq(pq, vecs, save=True)

    def _fit_pq4(self, pq, vecs_n: np.ndarray):
        """Fit the funnel's 4-bit sub-quantizer: same segment count as the
        8-bit quantizer, 16 centroids per segment, ranked in the SAME
        rotated space (the 8-bit quantizer's OPQ rotation is pinned, not
        re-learned — both ladders of the funnel then agree on geometry and
        queries rotate once per dispatch)."""
        from weaviate_tpu.compress.pq import ProductQuantizer

        pq4 = ProductQuantizer(
            dim=self.dim,
            segments=pq.segments,
            centroids=16,
            metric=self.metric,
            encoder=vi.PQ_ENCODER_KMEANS,
            distribution=self.config.pq.encoder.distribution,
            rotation=vi.PQ_ROTATION_NONE,
        )
        pq4.fit(vecs_n, rotation_matrix=pq.rotation_matrix)
        return pq4

    def _obtain_pq4(self, pq, vecs_n: np.ndarray):
        """The funnel quantizer for _enable_pq: a restore prefers the
        persisted pq4.npz (deterministic across restarts, skips the kmeans
        refit); anything else — fresh compress, missing/stale/corrupt file
        — fits from scratch with the pinned rotation. A rejected pq4.npz
        only costs the refit, never the shard."""
        if self._restoring and os.path.exists(self._pq4_path):
            from weaviate_tpu.compress.pq import ProductQuantizer

            try:
                pq4 = ProductQuantizer.load(self._pq4_path)
                if pq4.segments == pq.segments and pq4.centroids == 16:
                    return pq4
            except Exception as e:  # noqa: BLE001 — refit is always safe
                import logging

                logging.getLogger(__name__).warning(
                    "persisted pq4 codebook rejected (%s: %s); refitting",
                    type(e).__name__, e)
        return self._fit_pq4(pq, vecs_n)

    def _enable_pq(self, pq, vecs_n: np.ndarray, save: bool,
                   pq4=None) -> None:
        from weaviate_tpu.compress import pq as pq_mod

        t0 = time.perf_counter()
        codes = pq.encode(vecs_n)  # [n, M]
        full = np.zeros((self.capacity, pq.segments), dtype=pq.code_dtype)
        full[: self.n] = codes
        self._codes = jax.device_put(jnp.asarray(full), self.device)
        hv = np.zeros((self.capacity, self.dim), np.float32)
        hv[: self.n] = vecs_n
        self._host_vecs = hv
        self._recon_norms = jax.device_put(
            jnp.asarray(
                np.concatenate([
                    pq.recon_sq_norms(codes),
                    np.zeros(self.capacity - self.n, np.float32),
                ])
            ),
            self.device,
        )
        # bf16 rescore copy stays in HBM: the candidate rescoring pass then
        # never crosses the host boundary (half the f32 footprint the codes
        # just replaced; disable via pq.rescore=false for memory-tightest)
        if self.config.pq.rescore:
            # hv already holds the zero-padded [capacity, D] rows
            self._rescore_dev = jax.device_put(
                jnp.asarray(hv, jnp.bfloat16), self.device
            )
            # the fast scan runs straight over this copy; only l2 reads the
            # norms (einsum: f64 accumulation without a full f64 temp)
            self._rescore_sq_norms = (
                jax.device_put(jnp.asarray(np.einsum(
                    "ij,ij->i", hv, hv, dtype=np.float64).astype(np.float32)),
                    self.device)
                if self.metric == vi.DISTANCE_L2 else None)
        else:
            self._rescore_dev = None
            self._rescore_sq_norms = None
        # 4-bit funnel ladder (pq.bits=4): a SECOND 16-centroid quantizer
        # fit in the 8-bit quantizer's rotated space (its OPQ rotation is
        # PINNED via fit(rotation_matrix=...), so the Procrustes
        # alternation runs once per compress, not once per bit depth) —
        # nibble-packed codes halve the code bytes again and serve as the
        # funnel's stage-1 scan plane, with the 8-bit codes as stage 2
        if self.config.pq.bits == 4:
            if pq4 is None:
                pq4 = self._obtain_pq4(pq, vecs_n)
            codes4 = pq4.encode(vecs_n)  # [n, M] values 0..15
            packed = pq_mod.pack_codes4(codes4)  # [n, M/2]
            full4 = np.zeros((self.capacity, pq4.segments // 2), np.uint8)
            full4[: self.n] = packed
            self._codes4 = jax.device_put(jnp.asarray(full4), self.device)
            self._recon_norms4 = jax.device_put(
                jnp.asarray(np.concatenate([
                    pq4.recon_sq_norms(codes4),
                    np.zeros(self.capacity - self.n, np.float32),
                ])),
                self.device,
            )
            self._opq_rot_dev = (
                jax.device_put(
                    jnp.asarray(pq4.rotation_matrix, jnp.float32),
                    self.device)
                if pq4.rotation_matrix is not None else None)
            self._pq4 = pq4
            self._pq4_cb = None
        else:
            self._pq4 = None
            self._codes4 = None
            self._recon_norms4 = None
            self._opq_rot_dev = None
            self._pq4_cb = None
        self._store = None
        self._sq_norms = None
        self._pq = pq
        self.compressed = True
        if not self.config.pq.enabled:
            self.config.pq.enabled = True
        if save and self._log is not None:
            pq.save(self._pq_path)
            if self._pq4 is not None:
                self._pq4.save(self._pq4_path)
        self._staged_gen += 1
        self._mark_staged()
        led = memory.get_ledger()
        if led is not None:
            led.note_write(
                "compress", "compress", (time.perf_counter() - t0) * 1000.0,
                rows=self.n, bytes_moved=memory.array_bytes(self._codes))
        incidents.emit("write_phase", scope="compress", rows=self.n,
                       ms=round((time.perf_counter() - t0) * 1000.0, 1))
        self._publish_snapshot()

    # -- VectorIndex ---------------------------------------------------------

    def add(self, doc_id: int, vector: np.ndarray) -> None:
        with self._lock:
            self._stage_add(int(doc_id), vector)

    def add_batch(self, doc_ids: Sequence[int], vectors: np.ndarray) -> None:
        """Bulk import. Fresh doc_ids take a fully-vectorized path (the common
        batch-import case, shard_write_batch_objects.go); doc_ids that collide
        with existing/staged entries fall back to per-row staging."""
        doc_arr = np.asarray(doc_ids, dtype=np.int64)
        vectors = np.asarray(vectors, dtype=np.float32)
        with self._lock:
            if self._doc_to_slot:
                existing = np.fromiter(self._doc_to_slot.keys(), dtype=np.int64)
                collides = bool(np.isin(doc_arr, existing).any())
            else:
                collides = False
            fresh = (
                not self._pending
                and not collides
                and np.unique(doc_arr).size == doc_arr.size
            )
            if not fresh or vectors.ndim != 2:
                for d, v in zip(doc_arr, vectors):
                    self._stage_add(int(d), v)
                return
            if self.metric == vi.DISTANCE_COSINE:
                norms = np.linalg.norm(vectors, axis=1, keepdims=True)
                norms[norms == 0] = 1.0
                vectors = vectors / norms
            if self.dim is None:
                self._init_device(int(vectors.shape[1]))
            elif vectors.shape[1] != self.dim:
                raise ValueError(f"dim mismatch: index has {self.dim}, got {vectors.shape[1]}")
            if self._log is not None:
                self._log.append_add_batch(doc_arr, vectors)
            t0 = time.perf_counter()
            count = vectors.shape[0]
            self._staged_gen += 1
            self._mark_staged()
            self._ensure_capacity(self.n + count + _CHUNK)
            self._cow_host_state()
            self._write_block(vectors, self.n)
            self._slot_to_doc[self.n : self.n + count] = doc_arr
            self._stage_doc_ids(doc_arr, self.n)
            new_slots = dict(zip(doc_arr.tolist(), range(self.n, self.n + count)))
            self._doc_to_slot.update(new_slots)
            self.n += count
            self.live += count
            self._obs_index("add", "device_write", t0, ops=count)
            led = memory.get_ledger()
            if led is not None:
                led.note_write(
                    "add", "device_write",
                    (time.perf_counter() - t0) * 1000.0,
                    rows=count, bytes_moved=count * self.dim * 4)
            self._update_index_gauges()
            self._maybe_declared_compress()
            self._maybe_ivf_train()
            self._publish_snapshot()

    def delete(self, *doc_ids: int) -> None:
        with self._lock:
            for d in doc_ids:
                self._stage_delete(int(d))

    def contains(self, doc_id: int) -> bool:
        with self._lock:
            return doc_id in self._doc_to_slot or doc_id in self._pending

    def __len__(self) -> int:
        return self.live

    def distancer_name(self) -> str:
        return self.metric

    # -- index metrics (hnsw metrics.go / insert_metrics.go parity;
    # _obs_index/_metric_labels inherited from VectorIndex) ------------------

    def _update_index_gauges(self) -> None:
        m = self.metrics
        if m is None:
            return
        cls, shard = self._metric_labels()
        m.vector_index_tombstones.labels(cls, shard).set(self.n - self.live)
        m.vector_index_size.labels(cls, shard).set(self.capacity)
        # cheap always-on health gauges (the /debug/index satellites):
        # stamped here on the write path, so quality reporting needs
        # neither tracing nor auditing enabled
        m.vector_index_live.labels(cls, shard).set(self.live)
        m.index_tombstone_fraction.labels(cls, shard).set(
            (self.n - self.live) / self.n if self.n > 0 else 0.0)
        if self.dim:
            m.vector_dimensions.labels(cls, shard).set(self.live * self.dim)
            if self.compressed and self._pq is not None:
                m.vector_segments.labels(cls, shard).set(self.live * self._pq.segments)

    # -- fused group-min fast scan (ops/gmin_scan.py) ------------------------

    def _gmin_rg(self, k: int, capacity: int) -> int:
        """Groups kept by the fused scan: >= k guarantees exact selection
        under exact arithmetic (at most k groups hold the true top-k);
        2k..128 adds slack for bf16 fast-scan ranking error. 0 = shape
        unsupported, use the legacy scan."""
        from weaviate_tpu.ops import gmin_scan

        ncols = capacity // gmin_scan.G
        rg = min(max(32, 2 * k), 128, ncols)
        return rg if rg >= k else 0

    def _use_gmin(self, snap: IndexSnapshot, b: int, k: int) -> bool:
        if getattr(self.config, "exact_topk", False):
            return False  # config opt-out, not degradation
        if self._gmin_broken:
            record_device_fallback("index.tpu.gmin", "degraded", log=False)
            incidents.emit("device_fallback", scope="index.tpu.gmin")
            return False
        if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
            return False
        # pallas tiling wants >= 8 query sublanes; tiny batches stay on the
        # legacy scan (they're dispatch-latency-bound anyway)
        if snap.capacity < _MIN_CAPACITY or b < 8:
            return False
        return self._gmin_rg(k, snap.capacity) > 0

    def _gen_blocks(self, arr, build_fn):
        """Generation-cached block layout for `arr` (the store, the bf16
        rescore store, or the PQ codes): rebuilt only when the underlying
        array object changes (copy-on-write updates replace it). On every
        miss, entries whose source array is no longer a live index member
        are dropped FIRST — a replaced store generation plus its block
        layout (~1 GB HBM at 1M x 128 f32) must free before the new one
        builds, and still-valid entries for the other arrays stay cached.
        Concurrent snapshot readers may race here: dict get/set/pop are
        atomic under the GIL and a lost race only recomputes a layout."""
        hit = self._blk_cache.get(id(arr))
        if hit is not None and hit[0] is arr:
            return hit[1]
        live = {id(x) for x in (self._store, self._rescore_dev, self._codes)
                if x is not None}
        for k in [k for k in list(self._blk_cache) if k not in live]:
            self._blk_cache.pop(k, None)
        blk = build_fn(arr)
        self._blk_cache[id(arr)] = (arr, blk)
        return blk

    def _search_full_gmin(self, snap: IndexSnapshot, q: np.ndarray, kk: int,
                          allow_words, store=None, sq_norms=None, s2d=None):
        from weaviate_tpu.ops import gmin_scan

        interpret = jax.default_backend() not in ("tpu", "axon")
        ncols = snap.capacity // gmin_scan.G
        s = snap.store if store is None else store
        args = (
            s,
            snap.sq_norms if sq_norms is None else sq_norms,
            snap.tombs,
            snap.n,
            jnp.asarray(q),
            allow_words if allow_words is not None
            else jnp.zeros((snap.capacity // 32,), jnp.uint32),
        )
        statics = (
            allow_words is not None,
            kk,
            self.metric,
            self._gmin_rg(kk, snap.capacity),
            -(-snap.n // ncols),  # live store slices only
            interpret,
            self._gen_blocks(s, gmin_scan.build_rescore_blocks),
        )
        if s2d is not None:
            return gmin_scan.search_gmin_fused(*args, s2d, *statics)
        return gmin_scan.search_gmin(*args, *statics)

    def _gmin_packed_or_none(self, snap: IndexSnapshot, q: np.ndarray,
                             kk: int, allow_words, store=None, sq_norms=None,
                             s2d=None):
        """Run the fused scan, or None to use the legacy kernel. Validation
        is per compiled shape: each distinct (b, k, rg, active_g, use_allow)
        is a separate Mosaic compilation with its own VMEM footprint
        (active_g grows as the slab fills), so a failure on a NEW shape falls
        back for that shape only, while a failure on a shape that already
        completed a materialized search is a real runtime fault and
        propagates instead of silently halving throughput."""
        if not self._use_gmin(snap, q.shape[0], kk):
            return None
        from weaviate_tpu.ops import gmin_scan

        ncols = snap.capacity // gmin_scan.G
        active_g = -(-snap.n // ncols)
        sb = (store if store is not None else snap.store).dtype.itemsize
        if not gmin_scan.fits_vmem(q.shape[0], snap.dim, ncols, active_g, sb):
            # even the smallest tiling exceeds the VMEM budget (very wide
            # vectors): never hand Mosaic a kernel that can wedge the chip
            return None
        # capacity is part of the key: the compilation is parameterized by
        # the [capacity, D] store, so growth invalidates prior validation
        # (and fused translation is its own program — its own validation)
        key = (q.shape[0], kk, self._gmin_rg(kk, snap.capacity), active_g,
               snap.capacity, allow_words is not None, store is not None,
               s2d is not None)
        return gmin_scan.guarded_kernel_call(
            self, key,
            lambda: self._search_full_gmin(snap, q, kk, allow_words, store,
                                           sq_norms, s2d),
            "fused gmin kernel", component="index.tpu.gmin")

    def _pq_gmin_packed_or_none(self, snap: IndexSnapshot, q: np.ndarray,
                                b: int, k: int, allow_list, s2d=None):
        """Run the fused PQ codes kernel, or None for the legacy recon
        scan. Same per-shape validation contract as the dense kernel, on a
        SEPARATE failure domain (self._pqg_state); gating and codebook
        constants are the shared helpers in ops/pq_gmin.py."""
        from weaviate_tpu.ops import gmin_scan, pq_gmin

        ncols = snap.capacity // gmin_scan.G
        kk = min(k, snap.live)
        active_g = max(1, -(-snap.n // ncols))
        rg = pq_gmin.eligible_rg(
            self._pqg_state, getattr(self.config, "exact_topk", False),
            self.metric, snap.pq, q.shape[0], ncols, kk, snap.dim, active_g,
            component="index.tpu.pq_gmin")
        if rg is None:
            return None
        m, c = snap.pq.segments, snap.pq.centroids
        interpret = jax.default_backend() not in ("tpu", "axon")
        use_allow = allow_list is not None
        words = (self._allow_words(snap, allow_list) if use_allow
                 else jnp.zeros((snap.capacity // 32,), jnp.uint32))
        cb_chunks, flat_cb = pq_gmin.cached_cb_constants(self, snap.pq)
        key = (q.shape[0], kk, rg, active_g, snap.capacity, m, c, use_allow,
               s2d is not None)

        def thunk():
            args = (snap.codes, snap.recon_norms, snap.tombs, snap.n,
                    jnp.asarray(q), cb_chunks, flat_cb, words)
            statics = (use_allow, kk, self.metric, rg, active_g, interpret,
                       snap.pq.rotation_dev(),
                       self._gen_blocks(snap.codes,
                                        pq_gmin.build_codes_blocks))
            if s2d is not None:
                return pq_gmin.search_pq_gmin_fused(*args, s2d, *statics)
            return pq_gmin.search_pq_gmin(*args, *statics)

        return gmin_scan.guarded_kernel_call(
            self._pqg_state, key, thunk,
            "fused pq codes kernel", component="index.tpu.pq_gmin")

    def _funnel_budgets(self, k: int, n: int) -> tuple[int, int]:
        """(rg4 stage-1 groups, rc stage-2 survivors) for a funnel whose
        scan plane holds n rows — the SLAB capacity on the full-store
        tier (dead slots mask to inf; the group-column count plan_funnel
        clamps against is slab-derived), the probed candidate count on
        the IVF tier. The two caps are the controller's recall-guarded
        budgets (serving/controller.py), single-sourced from the
        config.PQ4_FUNNEL_*_BUCKETS ladders exactly like rescore_r_cap —
        bucket values in, so the jit shapes plan_funnel emits stay
        bounded. The same no-starvation floor as _rescore_r: a cap too
        shallow for this query's k lapses to the static max (the
        controller may only cut work, never break coverage)."""
        from weaviate_tpu.ops import pq4 as pq4_ops

        c_top = PQ4_FUNNEL_C_BUCKETS[-1]
        rc_top = PQ4_FUNNEL_RESCORE_BUCKETS[-1]
        c_cap = controller.funnel_c_cap(c_top)
        rc_cap = controller.funnel_rescore_cap(rc_top)
        if c_cap < 4 * k:
            c_cap = c_top
        if rc_cap < 2 * k:
            rc_cap = rc_top
        return pq4_ops.plan_funnel(k, n, c_cap, rc_cap)

    def _pq4_funnel_packed_or_none(self, snap: IndexSnapshot, q: np.ndarray,
                                   b: int, k: int, allow_list, s2d=None):
        """Run the three-stage 4-bit funnel (ops/pq4.py), or None for the
        8-bit fallback paths. Its own failure domain (self._pq4_state) and
        per-shape validation, like the other fused kernels — but unlike
        eligible_rg, Pallas ineligibility here only downgrades STAGE 1 to
        the traceable byte-LUT scan; the funnel itself still serves."""
        from weaviate_tpu.ops import gmin_scan, pq_gmin
        from weaviate_tpu.ops import pq4 as pq4_ops

        if snap.codes4 is None or snap.pq4 is None:
            return None
        if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT,
                               vi.DISTANCE_COSINE):
            return None
        kk = min(max(k, 1), snap.live)
        ncols = snap.capacity // gmin_scan.G
        active_g = max(1, -(-snap.n // ncols))
        mb = snap.pq4.segments // 2
        # budgets plan against the SLAB (capacity), not live n: the scan
        # plane's group-columns are capacity-derived, and on a sparse slab
        # the live rows spread across up to min(n, ncols) columns — a
        # live-n clamp would keep far fewer columns than actually carry
        # data (dead slots already score inf, so capacity never
        # over-scans)
        rg4, rc = self._funnel_budgets(kk, snap.capacity)
        if rc < kk:
            return None  # candidate set too small to cover k: 8-bit paths
        bq = q.shape[0]
        use_pallas = pq4_ops.pallas_eligible(
            self._pq4_state, self.metric, bq, ncols, snap.dim, mb, active_g,
            component="index.tpu.pq4")
        interpret = jax.default_backend() not in ("tpu", "axon")
        exact = bool(getattr(self.config, "exact_topk", False))
        use_allow = allow_list is not None
        words = (self._allow_words(snap, allow_list) if use_allow
                 else jnp.zeros((snap.capacity // 32,), jnp.uint32))
        cb4_chunks, cb4_dense = pq4_ops.cached_cb4_constants(self, snap.pq4)
        _cb8_chunks, flat_cb8 = pq_gmin.cached_cb_constants(self, snap.pq)
        codes8_blk = self._gen_blocks(snap.codes, pq_gmin.build_codes_blocks)
        key = (bq, kk, rg4, rc, active_g, snap.capacity, mb, use_allow,
               use_pallas, s2d is not None)

        def thunk():
            args = (snap.codes4, snap.codes, snap.recon_norms4,
                    snap.recon_norms, snap.tombs, snap.n, jnp.asarray(q),
                    cb4_chunks, cb4_dense, flat_cb8, snap.rescore_dev, words)
            statics = dict(use_allow=use_allow, k=kk, metric=self.metric,
                           rg4=rg4, rc=rc, active_g=active_g,
                           use_pallas=use_pallas, interpret=interpret,
                           exact=exact, rot=snap.opq_rot,
                           codes8_blk=codes8_blk)
            if s2d is not None:
                return pq4_ops.search_pq4_funnel_fused(*args, s2d, **statics)
            return pq4_ops.search_pq4_funnel(*args, **statics)

        packed = gmin_scan.guarded_kernel_call(
            self._pq4_state, key, thunk,
            "pq4 funnel kernel", component="index.tpu.pq4")
        if packed is not None:
            # per-stage survivor accounting (health()["pq"]["funnel"]):
            # a leaf lock, four integer adds — nothing nests inside it
            with self._pq4_lock:
                st = self._pq4_stats
                st["dispatches"] += 1
                # survivor counts are LIVE rows, so the funnel reads
                # monotone even on a sparse slab where the slot budgets
                # (rg4*G, rc) exceed the data they can keep
                st["stage1_rows"] += int(snap.n)
                st["stage2_survivors"] += min(rg4 * gmin_scan.G,
                                              int(snap.n))
                st["stage3_survivors"] += min(rc, int(snap.n))
        return packed

    def _rescore_r(self, k: int, n: int) -> int:
        """Fast-scan candidate depth: 0 disables (exactTopK config or
        non-matmul metrics); otherwise 4k clamped to [32, r_max] —
        selection errors of the single-pass scan sit well within 4k
        candidates. r_max is 128 statically; the control plane's
        recall-guarded budget controller (serving/controller.py) may
        lower it bucket-by-bucket while the shadow auditor's recall EWMA
        holds measured slack over the configured floor — the cap is
        clamped, jit-bucket-snapped, and lapses back to 128 when the
        controller stalls or dies."""
        if getattr(self.config, "exact_topk", False):
            return 0
        if self.metric not in (vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
            return 0
        # R_BUCKETS single source of truth (config.RESCORE_R_BUCKETS,
        # aliased by serving/controller.py): cap values are buckets and
        # the static choices are {max(4k, floor)} ∪ buckets, so a
        # controller cut can never mint a jit shape the static path
        # wouldn't also compile
        r_top = RESCORE_R_BUCKETS[-1]
        r_max = controller.rescore_r_cap(r_top)
        if r_max < 2 * k:
            # a cap below this query's slack threshold would zero r and
            # force the full-precision exact scan — strictly MORE device
            # work than the static path; the budget controller may only
            # cut, so queries too deep for the cap keep the static max
            r_max = r_top
        r = int(min(max(4 * k, RESCORE_R_BUCKETS[0]), r_max, max(n, 1)))
        # no candidate slack over k => the fast pass would pick the FINAL set
        # at reduced precision; fall back to the HIGHEST-precision scan
        return r if r >= 2 * k else 0

    # bound per-bucket free-list length: buffers parked beyond the live
    # pipeline depth are dead weight (a burst of concurrent dispatches can
    # momentarily check out more; the extras just get collected)
    _STAGE_POOL_CAP = 4

    def _prep_queries_staged(
            self, vectors: np.ndarray) -> tuple[np.ndarray, int]:
        """Query prep (f32 cast, cosine normalization, bucket padding)
        into a REUSABLE pre-staged host buffer from the per-jit-bucket
        pool: the per-dispatch concatenate/zeros allocations of enqueue
        collapse to one copy into a warm buffer.
        -> (padded [bb, D] f32 buffer, actual rows). The buffer must go
        back via _release_stage AFTER the dispatch's blocking fetch (the
        finalize wrapper does) — by then the program has consumed its
        inputs, so the next checkout may overwrite the memory even where
        device_put aliases it (cpu backend)."""
        q = np.asarray(vectors, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        b = q.shape[0]
        bb = _bucket_b(b)
        key = (bb, q.shape[1])
        with self._stage_lock:
            lst = self._stage_free.get(key)
            buf = lst.pop() if lst else None
        if buf is None:
            buf = np.empty(key, np.float32)
        np.copyto(buf[:b], q)
        if self.metric == vi.DISTANCE_COSINE:
            norms = np.linalg.norm(buf[:b], axis=1, keepdims=True)
            norms[norms == 0] = 1.0
            buf[:b] /= norms
        if bb != b:
            buf[b:] = 0.0
        return buf, b

    def _release_stage(self, buf: Optional[np.ndarray]) -> None:
        if buf is None:
            return
        key = (buf.shape[0], buf.shape[1])
        with self._stage_lock:
            # dim is None once drop() ran (or mid-compact teardown): an
            # in-flight dispatch finalizing after drop must NOT re-park
            # its buffer into the cleared pool — "stage_buffers reads 0
            # after drop" would break, and a re-created index with
            # another dim could never check the stale-keyed buffer out
            # again. Checked UNDER the lock: drop() sets dim before its
            # locked clear, so a racing finalize either sees dim None
            # here or appends before the clear wipes it — never after
            if self.dim is None:
                return
            lst = self._stage_free.setdefault(key, [])
            if len(lst) < self._STAGE_POOL_CAP:
                lst.append(buf)

    def _allow_words(self, snap: IndexSnapshot, allow_list: AllowList) -> jax.Array:
        """Packed device filter words for a snapshot's slot layout, cached
        ON the (immutable) allowList: repeated queries with the same filter
        skip the host-side pack entirely. The cache key holds a strong ref
        to the allow token object, so identity can never be recycled; the
        (token, n, capacity) triple still uniquely identifies the layout
        under snapshots because slot assignment is append-only between
        token refreshes (compact issues a fresh token)."""
        from weaviate_tpu.storage.bitmap import (
            Bitmap, allowed_mask, pack_allow_words)

        key = (snap.allow_token, snap.n, snap.capacity)
        cached = getattr(allow_list, "_words_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        live_docs = snap.slot_to_doc[: snap.n]
        if isinstance(allow_list, Bitmap):
            allowed = allowed_mask(allow_list, live_docs)
        else:
            allowed = allow_list.contains_array(live_docs.astype(np.uint64))
        words = jnp.asarray(pack_allow_words(allowed, snap.capacity))
        try:
            allow_list._words_cache = (key, words)
        except AttributeError:
            pass  # foreign AllowList impls without the cache slot
        return words

    def padded_width(self, b: int) -> int:
        """Query rows after bucket padding (`_bucket_b`) — the dispatch
        width the jit cache is keyed on. Serving traces use it to report
        per-request padding waste (monitoring/tracing.py dispatch facts)."""
        return _bucket_b(max(int(b), 1))

    def search_by_vectors(
        self, vectors: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Batched kNN on the current published snapshot: grab the
        reference (lock-free unless writes are pending), dispatch, fetch.
        Concurrent writers republish new snapshots but can never tear or
        block this dispatch — the snapshot pins its arrays."""
        snap = self._read_snapshot()
        return self._dispatch_search(snap, vectors, k, allow_list)()

    def _dispatch_search(self, snap: IndexSnapshot, vectors: np.ndarray,
                         k: int, allow_list: Optional[AllowList] = None):
        """Two-phase search on `snap`: enqueue the device work NOW (query
        upload + kernels — nothing blocks), return finalize() -> (ids,
        dists) whose ONE blocking device->host fetch runs outside any
        lock. Every read-path case — full scan, both PQ tiers, filtered
        scans, the small-allowList gather — dispatches through here, so
        sync and async searches run the same kernels with the same
        arguments (the bit-identical contract)."""
        if snap.n == 0 or snap.live == 0:
            b = 1 if np.asarray(vectors).ndim == 1 else len(vectors)
            empty = (np.zeros((b, 0), dtype=np.uint64),
                     np.zeros((b, 0), dtype=np.float32))
            return lambda: empty
        faults.fire("index.tpu.dispatch")
        # perf-attribution shape (monitoring/costmodel.py): built ONLY
        # while the tracer is up — the disabled serving path constructs
        # nothing here (one comparison; spy-pinned in tests/test_perf.py).
        # Stamped with the host-overhead ledger as the dispatch executes
        # and popped by the shard on the dispatching thread
        # (pop_dispatch_shape, the pop_read_lock_wait idiom).
        shape = None
        t_enq0 = 0.0
        if tracing.get_tracer() is not None:
            t_enq0 = time.perf_counter()
        q, b = self._prep_queries_staged(vectors)
        stage_buf = q  # returned to the pool by the finalize wrapper
        k_eff = min(k, snap.live)
        # fused dispatch: the device translation table rides the snapshot,
        # so the program's final top-k emits doc ids directly (the legacy
        # host slot_to_doc translation only runs with the toggle off)
        s2d = (snap.slot_to_doc_dev
               if fused_dispatch_enabled() else None)
        if allow_list is not None and len(allow_list) < self.config.flat_search_cutoff:
            if t_enq0:
                shape = costmodel.DispatchShape(
                    costmodel.TIER_GATHER,
                    n=min(len(allow_list), snap.live), dim=snap.dim,
                    batch=b, batch_padded=q.shape[0],
                    bytes_per_row=snap.dim * 4, k=int(k_eff))
            fin = self._dispatch_small_allow(snap, q, b, k_eff, allow_list,
                                             shape, s2d)
        elif (ivf_plan := self._ivf_plan(snap, k_eff)) is not None:
            # partition-pruned path (ROADMAP item 3): scan only the
            # probed buckets; large allowLists compose via the same
            # packed words, small ones took the gather tier above
            if t_enq0:
                shape = self._ivf_shape(snap, ivf_plan, b, q.shape[0],
                                        k_eff)
            fin = self._dispatch_ivf(snap, q, b, k_eff, allow_list,
                                     ivf_plan, shape, s2d)
        elif snap.compressed:
            if t_enq0:
                rescore = (self.config.pq.rescore
                           and snap.rescore_dev is not None)
                funnel = (snap.codes4 is not None
                          and self.metric in (vi.DISTANCE_L2,
                                              vi.DISTANCE_DOT,
                                              vi.DISTANCE_COSINE))
                if funnel:
                    # the 4-bit funnel tier: stage 1 reads M/2 packed
                    # bytes per scanned row; the re-ranking stages are
                    # attributed in extra (C/c rows at M and 2·D bytes)
                    # — a mid-dispatch refusal re-labels this below
                    rg4_s, rc_s = self._funnel_budgets(
                        int(k_eff), snap.capacity)
                    shape = costmodel.DispatchShape(
                        costmodel.TIER_PQ_ADC4,
                        n=snap.n, dim=snap.dim, batch=b,
                        batch_padded=q.shape[0],
                        bytes_per_row=snap.pq4.segments // 2,
                        k=int(k_eff),
                        extra={"funnel_c": rg4_s * 16,
                               "funnel_rescore": rc_s,
                               "funnel_stage2_bytes_per_row":
                                   snap.pq.segments,
                               "funnel_stage3_bytes_per_row":
                                   (2 * snap.dim if rescore else 0)})
                else:
                    shape = costmodel.DispatchShape(
                        costmodel.TIER_PQ_RESCORE if rescore
                        else costmodel.TIER_PQ_CODES,
                        n=snap.n, dim=snap.dim, batch=b,
                        batch_padded=q.shape[0],
                        # rescore scans the bf16 copy (2·D); codes-only
                        # reads the uint8 codes (M = segments bytes/row)
                        bytes_per_row=(2 * snap.dim if rescore
                                       else snap.pq.segments),
                        k=int(k_eff))
            fin = self._dispatch_full_pq(snap, q, b, k_eff, allow_list,
                                         shape, s2d)
        else:
            if t_enq0:
                shape = costmodel.DispatchShape(
                    costmodel.TIER_EXACT, n=snap.n, dim=snap.dim,
                    batch=b, batch_padded=q.shape[0],
                    bytes_per_row=snap.dim * snap.store.dtype.itemsize,
                    k=int(k_eff))
            allow_words = (self._allow_words(snap, allow_list)
                           if allow_list is not None else None)
            fin = self._dispatch_scan(snap, q, b, k_eff, allow_words,
                                      shape=shape, s2d=s2d)
        if shape is not None:
            now = time.perf_counter()
            shape.t_start = t_enq0
            shape.enqueue_ms = (now - t_enq0) * 1000.0
            if s2d is not None:
                # the fused-dispatch ledger invariant: one blocking fetch,
                # zero host-translation time (test-pinned; the perf window
                # counts violations)
                shape.fused = True
                shape.translate_ms = 0.0
            self._read_local.dispatch_shape = shape
        # shadow-audit snapshot pin (monitoring/quality.py): record which
        # snapshot THIS dispatch read so a sampled audit re-executes
        # against the same index state — writers publishing between
        # enqueue and finalize must not skew the comparison. TLS holds at
        # most one snapshot per serving thread; gated so the disabled
        # path stores nothing (one comparison, the tracer contract).
        if quality.get_auditor() is not None:
            self._read_local.audit_snap = snap
        self._track_inflight(1)
        done = [False]

        def finalize():
            fetched = False
            try:
                faults.fire("index.tpu.finalize")
                if shape is None:
                    out = fin()
                    fetched = True
                    return out
                if shape.fetches:
                    # a RETRIED finalize (permitted — see done[] below)
                    # re-runs the fetch; the ledger invariant is per
                    # attempt, and the recorded shape must describe the
                    # attempt whose results the caller actually got — a
                    # leftover count would read as a spurious double-
                    # fetch violation in /debug/perf
                    shape.fetches = 0
                t0 = time.perf_counter()
                out = fin()
                fetched = True
                t1 = time.perf_counter()
                shape.finalize_ms = (t1 - t0) * 1000.0
                shape.t_end = t1
                return out
            finally:
                if not done[0]:  # idempotent: finalize may be retried
                    done[0] = True
                    self._track_inflight(-1)
                    if fetched:
                        # the staging buffer goes back to the pool ONLY
                        # after a completed fetch: by then the program has
                        # consumed its inputs (cpu-backend device_put may
                        # alias host memory). A pre-fetch failure strands
                        # the buffer for the GC instead — a recycled
                        # buffer could be overwritten under a still-
                        # enqueued program and corrupt a permitted retry
                        self._release_stage(stage_buf)

        return finalize

    def pop_dispatch_shape(self):
        """The costmodel.DispatchShape of the CALLING thread's last
        dispatch (None while the tracer is down); reading clears it. The
        shard pops it on the dispatching thread — like the lock-wait fact
        — and attaches it to the trace record / perf window after
        finalize stamps the device timings (the shape object is shared
        with the finalize closure, so a pop at enqueue time still
        observes them)."""
        s = getattr(self._read_local, "dispatch_shape", None)
        if s is not None:
            self._read_local.dispatch_shape = None
        return s

    def pop_audit_snapshot(self) -> Optional[IndexSnapshot]:
        """The IndexSnapshot the CALLING thread's last dispatch read (None
        unless an auditor was configured at dispatch time); reading clears
        it. Popped by the shard on the dispatching thread — the
        pop_read_lock_wait idiom — and handed to the quality auditor so
        the shadow re-execution is generation-pinned."""
        s = getattr(self._read_local, "audit_snap", None)
        if s is not None:
            self._read_local.audit_snap = None
        return s

    def dispatch_tier(self, snap: IndexSnapshot, allow_list=None) -> str:
        """The costmodel TIER_* a dispatch on `snap` with `allow_list`
        takes — the same branching as _dispatch_search, exposed so the
        quality auditor labels its bounded-cardinality gauges without a
        tracer-built DispatchShape."""
        if allow_list is not None \
                and len(allow_list) < self.config.flat_search_cutoff:
            return costmodel.TIER_GATHER
        if snap.compressed:
            if snap.codes4 is not None and self.metric in (
                    vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
                return costmodel.TIER_PQ_ADC4
            if self.config.pq.rescore and snap.rescore_dev is not None:
                return costmodel.TIER_PQ_RESCORE
            return costmodel.TIER_PQ_CODES
        return costmodel.TIER_EXACT

    # -- IVF scan plane: dispatch half ---------------------------------------

    def _ivf_plan(self, snap: IndexSnapshot,
                  k: int) -> Optional[tuple[int, int]]:
        """(top_p, prefilter_c) for an IVF dispatch on `snap`, or None to
        take the flat path. None whenever the plane is disabled, the
        snapshot carries no trained layout, or the metric has no
        matmul/rescore form — the first two checks are one comparison
        each (the zero-hop contract). The effective probe count is the
        configured value capped by the controller's recall-guarded
        budget (serving/controller.py ivf_top_p_cap) and snapped to the
        bounded IVF_TOP_P_BUCKETS ladder (or to nlist exactly when the
        request covers every partition), so top_p — a jit static — can
        only take bounded values."""
        if snap.ivf_buckets is None:
            return None
        s = ivf_settings()
        if s is None:
            return None
        if self.metric not in ivf_ops.MATMUL_METRICS:
            return None
        nlist, cap_p, _gen = snap.ivf_meta
        req = s.top_p if s.top_p > 0 else max(1, nlist // 16)
        req = min(req, nlist)
        eff = max(1, min(req, controller.ivf_top_p_cap(req)))
        if eff < nlist:
            eff = min(_snap_top_p(eff), nlist)
        # deep-k coverage: a probe set under ~4k candidates starves the
        # final selection (the flat fast-scan's slack rationale) — widen
        # up the ladder before dispatching; neither the config nor the
        # controller cap may shrink a query below its own k
        while eff < nlist and eff * cap_p < 4 * k:
            nxt = _snap_top_p(min(eff * 2, nlist))
            eff = nlist if nxt <= eff else nxt
        pre_c = 0
        if snap.ivf_pca_proj is not None:
            r = eff * cap_p
            # auto: 8k floor for selection quality, r/8 cut, capped at
            # 2048 — past that the full-dim pass stops being the
            # bottleneck the prefilter exists to shrink
            pc = s.prefilter_c if s.prefilter_c > 0 \
                else max(8 * k, min(2048, r // 8))
            pc = _bucket_rows(min(pc, r))  # pow2: bounded jit shapes
            if pc < r:
                pre_c = pc
        return (eff, pre_c)

    def _ivf_shape(self, snap: IndexSnapshot, plan: tuple[int, int],
                   b: int, padded: int, k_eff: int):
        """The probed-aware costmodel shape of an IVF dispatch: `n` is
        the rows the device actually reads (top_p x cap_p candidates,
        padding included, plus the nlist centroid rows), so flops/bytes
        — and every roofline derived from them — never credit the rows
        the probe skipped (no phantom work)."""
        top_p, _pre_c = plan
        nlist, cap_p, _gen = snap.ivf_meta
        probed = top_p * cap_p + nlist
        rescore = (snap.compressed and self.config.pq.rescore
                   and snap.rescore_dev is not None)
        if not snap.compressed:
            tier = costmodel.TIER_EXACT
            bpr = snap.dim * snap.store.dtype.itemsize
        elif snap.codes4 is not None and self.metric in (
                vi.DISTANCE_L2, vi.DISTANCE_DOT, vi.DISTANCE_COSINE):
            tier = costmodel.TIER_PQ_ADC4
            bpr = snap.pq4.segments // 2
        elif rescore:
            tier = costmodel.TIER_PQ_RESCORE
            bpr = 2 * snap.dim
        else:
            tier = costmodel.TIER_PQ_CODES
            bpr = snap.pq.segments
        return costmodel.DispatchShape(
            tier, n=probed, dim=snap.dim, batch=b, batch_padded=padded,
            bytes_per_row=bpr, k=int(k_eff),
            extra={"ivf": True, "ivf_top_p": top_p, "ivf_nlist": nlist,
                   "probed_fraction": round(
                       min(probed / max(snap.n, 1), 1.0), 4)})

    def _dispatch_ivf(self, snap: IndexSnapshot, q: np.ndarray, b: int,
                      k: int, allow_list, plan: tuple[int, int],
                      shape=None, s2d=None):
        """Partition-pruned search: probe the centroids, score only the
        probed buckets (ops/ivf.py), finish through the SAME packed /
        fused-translate epilogue as every flat tier. Covers the exact,
        PQ-rescore, and PQ-codes tiers; tombstones and allowLists mask
        with identical semantics to the flat kernels (the snapshot's own
        device tombs, the same packed filter words)."""
        top_p, pre_c = plan
        nlist, cap_p, _gen = snap.ivf_meta
        allow_words = (self._allow_words(snap, allow_list)
                       if allow_list is not None else None)
        use_allow = allow_words is not None
        words = (allow_words if use_allow
                 else jnp.zeros((snap.capacity // 32,), jnp.uint32))
        exact = getattr(self.config, "exact_topk", False)
        kk = min(max(k, 1), top_p * cap_p)
        gp = ivf_ops.group_steps(q.shape[0], cap_p, snap.dim, top_p)
        # second-stage chunking (prefilter survivors): pow2 steps so the
        # full-dim gather stays within the same element budget
        steps2 = 1
        if pre_c:
            while steps2 < pre_c and \
                    (q.shape[0] * (pre_c // steps2) * snap.dim) > (1 << 21):
                steps2 *= 2
        rescore = (snap.compressed and self.config.pq.rescore
                   and snap.rescore_dev is not None)
        funnel4 = (snap.codes4 is not None and snap.pq4 is not None
                   and self.metric in (vi.DISTANCE_L2, vi.DISTANCE_DOT,
                                       vi.DISTANCE_COSINE))
        if funnel4:
            # probed three-stage funnel (ops/pq4.search_ivf_pq4): grouped
            # 4-bit byte-LUT cut -> exact 8-bit ADC of the survivors ->
            # bf16 rescore — the funnel budgets bound stages 1/2 over the
            # probed candidate set exactly as over the full store
            from weaviate_tpu.ops import pq4 as pq4_ops

            r_cand = top_p * cap_p
            rg4, rc = self._funnel_budgets(kk, r_cand)
            c1 = min(rg4 * 16, r_cand)
            # stage-2 chunking over the c1 survivors: pow2 steps under the
            # shared element budget, stopped early if a further halving
            # would stop dividing c1 (the _regroup contract)
            steps2_4 = 1
            while (steps2_4 * 2 <= c1 and c1 % (steps2_4 * 2) == 0
                   and (q.shape[0] * (c1 // steps2_4) * snap.dim)
                   > (1 << 21)):
                steps2_4 *= 2
            if rc >= kk and c1 >= rc:
                statics4 = (kk, self.metric, use_allow, top_p, c1, rc,
                            exact, gp, steps2_4)
                args4 = (snap.codes4, snap.codes, snap.recon_norms4,
                         snap.recon_norms, snap.tombs, snap.n,
                         jnp.asarray(q), words, snap.pq4._dev_codebook(),
                         snap.pq._dev_codebook(), snap.ivf_centroids,
                         snap.ivf_buckets, snap.opq_rot, snap.rescore_dev)
                if s2d is not None:
                    packed_dev = pq4_ops.search_ivf_pq4_fused(
                        *args4, s2d, *statics4)
                else:
                    packed_dev = pq4_ops.search_ivf_pq4(*args4, *statics4)
                with self._ivf_lock:
                    st = self._ivf_stats
                    st["dispatches"] += 1
                    st["probed_rows"] += top_p * cap_p
                    st["base_rows"] += int(snap.n)
                with self._pq4_lock:
                    st = self._pq4_stats
                    st["dispatches"] += 1
                    st["stage1_rows"] += r_cand
                    st["stage2_survivors"] += min(c1, r_cand)
                    st["stage3_survivors"] += min(rc, r_cand)
                if s2d is not None:
                    return self._finalize_fused(packed_dev, shape, b)
                slot_to_doc = snap.slot_to_doc

                def finalize4():
                    packed = _fetch_packed(packed_dev, shape)
                    top, idx = _unpack(packed)
                    top = top[:b]
                    idx = idx[:b]
                    t0 = time.perf_counter() if shape is not None else 0.0
                    ids = np.where(idx >= 0,
                                   slot_to_doc[np.clip(idx, 0, None)], -1)
                    if shape is not None:
                        shape.translate_ms = \
                            (time.perf_counter() - t0) * 1000.0
                    return ids.astype(np.uint64), top.astype(np.float32)

                return finalize4
            if shape is not None and shape.tier == costmodel.TIER_PQ_ADC4:
                # budgets can't cover this k over the probed set: the
                # 8-bit IVF tier serves — re-label (no phantom traffic)
                shape.tier = (costmodel.TIER_PQ_RESCORE if rescore
                              else costmodel.TIER_PQ_CODES)
                shape.bytes_per_row = (2 * snap.dim if rescore
                                       else snap.pq.segments)
        statics = (kk, self.metric, use_allow, top_p, pre_c, exact, gp,
                   steps2)
        if not snap.compressed or rescore:
            store = snap.store if not snap.compressed else snap.rescore_dev
            args = (store, snap.tombs, snap.n, jnp.asarray(q), words,
                    snap.ivf_centroids, snap.ivf_buckets,
                    snap.ivf_pca_proj, snap.ivf_pca_rows)
            if s2d is not None:
                packed_dev = ivf_ops.search_ivf_dense_fused(
                    *args, s2d, *statics)
            else:
                packed_dev = ivf_ops.search_ivf_dense(*args, *statics)
        else:
            args = (snap.codes, snap.recon_norms, snap.tombs, snap.n,
                    jnp.asarray(q), words, snap.pq._dev_codebook(),
                    snap.ivf_centroids, snap.ivf_buckets,
                    snap.ivf_pca_proj, snap.ivf_pca_rows,
                    snap.pq.rotation_dev())
            if s2d is not None:
                packed_dev = ivf_ops.search_ivf_codes_fused(
                    *args, s2d, *statics)
            else:
                packed_dev = ivf_ops.search_ivf_codes(*args, *statics)
        # probe accounting (health / bench probed_fraction): a leaf lock,
        # three integer adds — nothing nests inside it
        with self._ivf_lock:
            st = self._ivf_stats
            st["dispatches"] += 1
            st["probed_rows"] += top_p * cap_p
            st["base_rows"] += int(snap.n)
        if s2d is not None:
            return self._finalize_fused(packed_dev, shape, b)
        slot_to_doc = snap.slot_to_doc

        def finalize():
            # the ONE blocking fetch of the legacy (non-fused) IVF
            # dispatch, outside any lock
            packed = _fetch_packed(packed_dev, shape)
            top, idx = _unpack(packed)
            top = top[:b]
            idx = idx[:b]
            t0 = time.perf_counter() if shape is not None else 0.0
            ids = np.where(idx >= 0, slot_to_doc[np.clip(idx, 0, None)], -1)
            if shape is not None:
                shape.translate_ms = (time.perf_counter() - t0) * 1000.0
            return ids.astype(np.uint64), top.astype(np.float32)

        return finalize

    def _dispatch_scan(self, snap: IndexSnapshot, q: np.ndarray, b: int,
                       k_eff: int, allow_words, store=None, sq_norms=None,
                       shape=None, s2d=None):
        """Full-store scan (fused gmin when eligible, legacy lax.scan kernel
        otherwise) over `store` — the f32 store uncompressed, or the bf16
        rescore copy under PQ-with-rescore (scanning codes first would read
        MORE HBM than the copy the rescore pass consults anyway). With
        `s2d` (the snapshot's device translation table) the slot->doc
        translation fuses into the same program and finalize is a
        reshape."""
        kk = min(max(k_eff, 1), snap.n)
        packed_dev = self._gmin_packed_or_none(snap, q, kk, allow_words,
                                               store, sq_norms, s2d)
        if packed_dev is None:
            sq = snap.sq_norms if sq_norms is None else sq_norms
            args = (
                snap.store if store is None else store,
                sq if self.metric == vi.DISTANCE_L2 else None,
                snap.tombs,
                snap.n,
                jnp.asarray(q),
                allow_words if allow_words is not None
                else jnp.zeros((snap.capacity // 32,), jnp.uint32),
            )
            statics = (
                kk,
                self.metric,
                allow_words is not None,
                getattr(self.config, "exact_topk", False),
                -(-snap.n // _SCAN_CHUNK),
                self._rescore_r(kk, snap.n),
            )
            if s2d is not None:
                packed_dev = _search_full_fused(*args, s2d, *statics)
            else:
                packed_dev = _search_full(*args, *statics)
        if s2d is not None:
            return self._finalize_fused(packed_dev, shape, b)
        slot_to_doc = snap.slot_to_doc

        def finalize():
            # the ONE deliberate blocking fetch per search dispatch
            # (results packed [B,2k] = a single transfer), outside any lock
            packed = _fetch_packed(packed_dev, shape)
            top, idx = _unpack(packed)
            top = top[:b]
            idx = idx[:b]
            t0 = time.perf_counter() if shape is not None else 0.0
            ids = np.where(idx >= 0, slot_to_doc[np.clip(idx, 0, None)], -1)
            if shape is not None:
                shape.translate_ms = (time.perf_counter() - t0) * 1000.0
            return ids.astype(np.uint64), top.astype(np.float32)

        return finalize

    def _finalize_fused(self, packed_dev, shape, b: int,
                        k: Optional[int] = None):
        """Finalize for a FUSED dispatch: the one blocking fetch already
        carries final doc ids, so the host half is dtype views plus two
        vectorized word copies (ops/topk.unpack_fused) — no slot->doc
        table read, no per-row work (the JGL015 contract, and the reason
        the perf ledger's gather_hop share collapses)."""
        def finalize():
            packed = _fetch_packed(packed_dev, shape)
            ids, dists = unpack_fused(packed)
            if k is not None:
                ids, dists = ids[:, :k], dists[:, :k]
            return ids[:b], dists[:b]

        return finalize

    def _dispatch_full_pq(self, snap: IndexSnapshot, q: np.ndarray, b: int,
                          k: int, allow_list, shape=None, s2d=None):
        """Compressed full-store search.

        With rescore enabled a full bf16 copy of the rows already lives in
        HBM for the rescoring pass — so the fast scan reads THAT copy
        directly (fused gmin kernel / legacy scan), which is strictly less
        HBM traffic and strictly more accurate than scanning the codes
        first; the codes then only serve writes and restarts. The reference
        has no such copy, hence its LUT scan (product_quantization.go:56-75).

        With rescore disabled (memory-tightest tier) the scan really runs
        over the codes: reconstruction-matmul ADC for matmul metrics, LUT
        gathers for manhattan. (hamming never compresses — ProductQuantizer
        rejects it at fit/load.)"""
        from weaviate_tpu.compress.pq import build_lut

        pqc = self.config.pq
        # 4-bit funnel tier first (pq.bits=4): the stage-1 scan reads M/2
        # bytes per row — less HBM than the bf16 copy (2D) or even the
        # 8-bit codes (M) — and the two re-ranking stages restore recall.
        # A broken/ineligible funnel falls through to the 8-bit paths
        # below (the codes and rescore slabs both still exist).
        packed4 = self._pq4_funnel_packed_or_none(snap, q, b, k, allow_list,
                                                  s2d)
        if packed4 is not None:
            if s2d is not None:
                return self._finalize_fused(packed4, shape, b, k)
            slot_to_doc = snap.slot_to_doc

            def finalize4():
                packed = _fetch_packed(packed4, shape)
                top, slots = _unpack(packed)
                top, slots = top[:b], slots[:b]
                t0 = time.perf_counter() if shape is not None else 0.0
                ids = np.where(slots >= 0,
                               slot_to_doc[np.clip(slots, 0, None)], -1)
                if shape is not None:
                    shape.translate_ms = (time.perf_counter() - t0) * 1000.0
                return (ids[:, :k].astype(np.uint64),
                        top[:, :k].astype(np.float32))

            return finalize4
        if shape is not None and shape.tier == costmodel.TIER_PQ_ADC4:
            # the funnel refused mid-dispatch (broken kernel / shallow
            # budgets): re-label the shape for the tier that actually
            # serves, so /debug/perf carries no phantom 4-bit traffic
            rescore_fb = pqc.rescore and snap.rescore_dev is not None
            shape.tier = (costmodel.TIER_PQ_RESCORE if rescore_fb
                          else costmodel.TIER_PQ_CODES)
            shape.bytes_per_row = (2 * snap.dim if rescore_fb
                                   else snap.pq.segments)
        rescore = pqc.rescore and snap.rescore_dev is not None
        if rescore:
            allow_words = (self._allow_words(snap, allow_list)
                           if allow_list is not None else None)
            return self._dispatch_scan(
                snap, q, b, k, allow_words,
                store=snap.rescore_dev, sq_norms=snap.rescore_sq_norms,
                shape=shape, s2d=s2d)
        slot_to_doc = snap.slot_to_doc
        # codes-only tier from here: raw ADC distances, no rescoring pass.
        # Fast path: the fused PQ-ADC group-min kernel (ops/pq_gmin.py) —
        # reconstruction-as-matmul in VMEM, codes never expand in HBM
        packed_dev = self._pq_gmin_packed_or_none(snap, q, b, k, allow_list,
                                                  s2d)
        if packed_dev is None:
            # legacy reconstruction-scan path:
            # per-chunk candidate depth: selection cost on TPU grows sharply
            # with k, so each chunk contributes a SMALL top-r and the
            # candidate pool is nchunks * r_chunk deep. Sized so the pool
            # stays >= 512 regardless of chunk count (64/chunk over a 1M
            # store; deeper per chunk when the store fits fewer chunks).
            nchunks_eff = max(1, -(-snap.n // _SCAN_CHUNK))
            pool_target = pqc.rescore_limit or 1024
            r_top = RESCORE_R_BUCKETS[-1]
            r_cap = controller.rescore_r_cap(r_top)
            if r_cap < r_top:
                # the budget controller's cap scales the codes-tier
                # candidate pool too (the ISSUE's per-chunk budget): cap
                # values are bucketed, so the derived r_chunk set stays
                # bounded and jit shapes stay cached; the floor keeps
                # the pool's own recall guarantee without ever RAISING
                # a configured rescore_limit below 512 (the controller
                # may only cut work)
                pool_target = max(int(pool_target * r_cap / r_top),
                                  min(512, pool_target))
            r_chunk = min(
                max(2 * k, -(-pool_target // nchunks_eff), 64), 256, snap.n
            )
            # the concatenated pool must cover k (final top_k rejects k > pool)
            r_chunk = max(r_chunk, min(-(-k // nchunks_eff), snap.n))
            allow_words = (self._allow_words(snap, allow_list)
                           if allow_list is not None else None)
            words = (allow_words if allow_words is not None
                     else jnp.zeros((snap.capacity // 32,), jnp.uint32))
            if self.metric in (vi.DISTANCE_L2, vi.DISTANCE_DOT,
                               vi.DISTANCE_COSINE):
                args = (
                    snap.codes,
                    snap.recon_norms,
                    snap.tombs,
                    snap.n,
                    snap.pq._dev_codebook(),
                    jnp.zeros((1, snap.dim), jnp.bfloat16),
                    jnp.asarray(q),
                    words,
                )
                statics = (
                    min(k, snap.live),
                    r_chunk,
                    self.metric,
                    allow_words is not None,
                    getattr(self.config, "exact_topk", False),
                    -(-snap.n // _SCAN_CHUNK),
                    False,
                    snap.pq.rotation_dev(),
                )
                if s2d is not None:
                    packed_dev = _search_pq_recon_fused(*args, s2d, *statics)
                else:
                    packed_dev = _search_pq_recon(*args, *statics)
            else:
                lut = build_lut(jnp.asarray(q), snap.pq._dev_codebook(),
                                self.metric)
                args = (snap.codes, snap.tombs, snap.n, lut, words)
                statics = (
                    min(k, snap.n, _PQ_SCAN_CHUNK),
                    allow_words is not None,
                    getattr(self.config, "exact_topk", False),
                    -(-snap.n // _PQ_SCAN_CHUNK),
                )
                if s2d is not None:
                    packed_dev = _search_pq_fused(*args, s2d, *statics)
                else:
                    packed_dev = _search_pq(*args, *statics)
        if s2d is not None:
            return self._finalize_fused(packed_dev, shape, b, k)

        def finalize():
            # the ONE deliberate blocking fetch per PQ search dispatch,
            # outside any lock
            packed = _fetch_packed(packed_dev, shape)
            top, slots = _unpack(packed)
            top, slots = top[:b], slots[:b]
            t0 = time.perf_counter() if shape is not None else 0.0
            # (cosine: the recon path already emits 1 - dot directly)
            ids = np.where(slots >= 0, slot_to_doc[np.clip(slots, 0, None)], -1)
            if shape is not None:
                shape.translate_ms = (time.perf_counter() - t0) * 1000.0
            return (ids[:, :k].astype(np.uint64),
                    top[:, :k].astype(np.float32))

        return finalize

    def _allow_slots(self, snap: IndexSnapshot,
                     allow_list: AllowList) -> np.ndarray:
        """Store slots of `allow_list`'s docs in this snapshot, the
        gather path's input-side resolution: ONE vectorized membership
        pass over the snapshot's slot->doc prefix (the same primitive the
        packed-words filter path uses), cached on the (immutable)
        allowList per slot layout exactly like `_allow_words` — repeated
        queries with the same filter skip the pass entirely (the shard's
        allowList cache reuses AllowList objects per filter signature,
        and the coalescer only admits filters proven hot, so the serving
        path hits this cache; a one-off filter pays one vectorized O(n)
        pass, the cold-filter cost class `_allow_words` already set).
        This replaced the per-snapshot lazily-sorted doc->slot binary-
        search map, which died with host-side result translation.

        Staleness contract: the (allow_token, n, capacity) key changes on
        adds, re-adds, and compaction, but NOT on deletes — so the
        cached slot list is computed WITHOUT tombstone knowledge (every
        matching slot, tombstoned or not) and is therefore identical no
        matter which same-key snapshot computed it. Tombstones are
        masked ON DEVICE with the dispatching snapshot's own `tombs`
        (_gather_live): each dispatch is exact for the state it pinned,
        in BOTH staleness directions — a new snapshot's dispatch hitting
        an old cache masks fresh deletes, and an old pinned snapshot's
        dispatch hitting a cache computed after a delete still gathers
        (and keeps) the doc its own world holds live. Excluding
        host_tombs here would break that second direction."""
        from weaviate_tpu.storage.bitmap import Bitmap, allowed_mask

        key = (snap.allow_token, snap.n, snap.capacity)
        cached = getattr(allow_list, "_slots_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        live_docs = snap.slot_to_doc[: snap.n]
        if isinstance(allow_list, Bitmap):
            allowed = allowed_mask(allow_list, live_docs)
        else:
            allowed = allow_list.contains_array(live_docs.astype(np.uint64))
        slots = np.flatnonzero(allowed).astype(np.int32)
        try:
            allow_list._slots_cache = (key, slots)
        except AttributeError:
            pass  # foreign AllowList impls without the cache slot
        return slots

    def _dispatch_small_allow(self, snap: IndexSnapshot, q: np.ndarray,
                              b: int, k: int, allow_list: AllowList,
                              shape=None, s2d=None):
        """Gather path (flatSearch over allowList, flat_search.go:19): the
        host-side doc->slot resolution is one cached vectorized membership
        pass (`_allow_slots`); the row scoring is one enqueued device
        call, and with `s2d` the result-side slot->doc translation rides
        the same program."""
        empty = (np.zeros((b, 0), np.uint64), np.zeros((b, 0), np.float32))
        slots = self._allow_slots(snap, allow_list)
        # short-circuit when NOTHING can match in THIS snapshot: the
        # cached slot list is tombstone-blind, so consult the dispatching
        # snapshot's own host mirror (O(A), per dispatch — never cached):
        # a fully-deleted filter must cost zero device work, not a
        # dispatch that gathers dead rows into all-sentinel columns
        if slots.size == 0 or not np.any(~snap.host_tombs[slots]):
            if shape is not None:
                shape.n = 0  # no device work ran: zero the analytic cost
            return lambda: empty
        if shape is not None:
            # the gather scores only the rows PRESENT in this shard — an
            # allowList spanning other shards must not credit this
            # dispatch their flops/bytes
            shape.n = int(slots.size)
        r = _bucket_rows(slots.size)
        rows = np.full(r, 0, dtype=np.int32)
        rows[: slots.size] = slots
        row_valid = np.zeros(r, dtype=bool)
        row_valid[: slots.size] = True
        kk = min(k, slots.size)
        rows_dev = jnp.asarray(rows)
        valid_dev = jnp.asarray(row_valid)
        if snap.compressed:
            # float rows live host-side under PQ: upload the gathered block
            sub = np.zeros((r, snap.dim), np.float32)
            sub[: slots.size] = snap.host_vecs[slots]
            if s2d is not None:
                packed_dev = _score_rows_fused(
                    jnp.asarray(sub), jnp.asarray(q), rows_dev, valid_dev,
                    snap.tombs, s2d, kk, self.metric)
            else:
                packed_dev = _score_rows(
                    jnp.asarray(sub), jnp.asarray(q), rows_dev, valid_dev,
                    snap.tombs, kk, self.metric)
        else:
            if s2d is not None:
                packed_dev = _search_gathered_fused(
                    snap.store, jnp.asarray(q), rows_dev, valid_dev,
                    snap.tombs, s2d, kk, self.metric)
            else:
                packed_dev = _search_gathered(
                    snap.store, jnp.asarray(q), rows_dev, valid_dev,
                    snap.tombs, kk, self.metric)
        if s2d is not None:
            return self._finalize_fused(packed_dev, shape, b)
        slot_to_doc = snap.slot_to_doc

        def finalize():
            # the ONE deliberate blocking fetch of the gather-path
            # dispatch, outside any lock
            packed = _fetch_packed(packed_dev, shape)
            top, idx = _unpack(packed)
            top = top[:b]
            idx = idx[:b]
            t0 = time.perf_counter() if shape is not None else 0.0
            safe = np.clip(idx, 0, r - 1)
            ids = np.where(idx >= 0, slot_to_doc[rows[safe]], -1)
            if shape is not None:
                shape.translate_ms = (time.perf_counter() - t0) * 1000.0
            return ids.astype(np.uint64), top.astype(np.float32)

        return finalize

    # -- host fallback plane (serving/robustness.py circuit breaker) ---------

    def host_rows(
            self, snap: IndexSnapshot) -> tuple[np.ndarray, np.ndarray]:
        """Host f32 ([n, D] rows, [n] row sq-norms) of `snap`'s occupied
        region — one bulk device->host transfer + one norms pass, no
        caching (callers own their policy: the breaker caches per live
        generation in _host_fallback_rows, the quality auditor keeps its
        own snapshot-pinned cache). Under PQ the full-precision rows
        already live host-side (host_vecs); only the norms are derived."""
        if snap.compressed and snap.host_vecs is not None:
            rows = snap.host_vecs[: snap.n]  # a view — no extra memory
        else:
            rows = np.asarray(snap.store[: snap.n]).astype(
                np.float32, copy=False)
        # einsum: the norms pass must not transiently duplicate the rows
        sq = np.einsum("ij,ij->i", rows, rows, dtype=np.float32)
        return rows, sq

    def _host_fallback_rows(
            self, snap: IndexSnapshot) -> tuple[np.ndarray, np.ndarray]:
        """host_rows built ONCE per snapshot generation and cached: the
        breaker's fallback pays one bulk transfer + one norms pass when it
        first opens, not per degraded query — this path exists precisely
        for sustained load on the slowest plane. (A device too far gone
        even to read HBM makes the fetch raise; the caller then surfaces
        the original dispatch error.)"""
        cached = self._host_rows_cache
        if cached is not None and cached[0] == snap.gen:
            return cached[1], cached[2]
        rows, sq = self.host_rows(snap)
        self._host_rows_cache = (snap.gen, rows, sq)
        return rows, sq

    def release_host_fallback_cache(self) -> None:
        """Drop the host fallback copy — a full f32 store materialization
        at serving scale — once the breaker has recovered and the device
        serves THIS index again (db/shard.py calls this on the first
        healthy dispatch after a degraded window, per shard); it rebuilds
        on the next breaker-open episode."""
        self._host_rows_cache = None

    def search_by_vectors_host(
        self, vectors: np.ndarray, k: int,
        allow_list: Optional[AllowList] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Batched kNN entirely on the HOST (numpy brute force) over the
        published snapshot — the read path db/shard.py routes to while the
        device circuit breaker is open (and for the breaker's own recovery
        probes' riders). Same contract as search_by_vectors ([B, k] ids +
        dists, inf-padded absent slots); selection is exact, so recall can
        only go UP while degraded — latency and throughput pay instead."""
        snap = self._read_snapshot()
        if snap.n == 0 or snap.live == 0:
            b = 1 if np.asarray(vectors).ndim == 1 else len(vectors)
            return (np.zeros((b, 0), np.uint64),
                    np.zeros((b, 0), np.float32))
        rows, row_sq = self._host_fallback_rows(snap)
        return self._host_search_snap(snap, vectors, k, allow_list,
                                      rows, row_sq)

    def search_by_vectors_host_pinned(
        self, snap: IndexSnapshot, vectors: np.ndarray, k: int,
        allow_list: Optional[AllowList] = None,
        rows: Optional[np.ndarray] = None,
        sq_norms: Optional[np.ndarray] = None,
        deadline: Optional[float] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """The quality auditor's host-plane entry (monitoring/quality.py):
        exact brute-force kNN over a CALLER-PINNED snapshot — the exact
        index state the audited live dispatch read, so deletes or
        compression published in between cannot skew the comparison.
        Bypasses _read_snapshot (no flush, no lock, no read-your-writes)
        and the breaker's fallback cache (callers pass their own `rows`).
        `deadline` (time.monotonic seconds) bounds the scan: row chunks
        are checked against it and quality.AuditDeadlineExceeded aborts
        an over-budget audit — audits are subordinate to everything."""
        if snap.n == 0 or snap.live == 0:
            b = 1 if np.asarray(vectors).ndim == 1 else len(vectors)
            return (np.zeros((b, 0), np.uint64),
                    np.zeros((b, 0), np.float32))
        if rows is None:
            rows, sq_norms = self.host_rows(snap)
        return self._host_search_snap(snap, vectors, k, allow_list,
                                      rows, sq_norms, deadline)

    # rows per host-scan chunk: bounds the work between deadline checks
    # (and the [B, chunk, D] broadcast of the non-matmul metrics)
    _HOST_SCAN_CHUNK = 65536

    def _host_search_snap(
        self, snap: IndexSnapshot, vectors: np.ndarray, k: int,
        allow_list: Optional[AllowList], rows: np.ndarray,
        row_sq: np.ndarray, deadline: Optional[float] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Shared exact host scan over a snapshot's materialized rows.
        Distances stream in row chunks (output-column splits — bit-
        identical to the one-shot matmul, since the reduction runs over
        the full dim either way) with a deadline check per chunk."""
        q = np.asarray(vectors, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        b = q.shape[0]
        empty = (np.zeros((b, 0), np.uint64), np.zeros((b, 0), np.float32))
        if snap.n == 0 or snap.live == 0:
            return empty
        if self.metric == vi.DISTANCE_COSINE:
            norms = np.linalg.norm(q, axis=1, keepdims=True)
            norms[norms == 0] = 1.0
            q = q / norms
        live = ~snap.host_tombs[: snap.n]
        if allow_list is not None:
            from weaviate_tpu.storage.bitmap import Bitmap, allowed_mask

            docs = snap.slot_to_doc[: snap.n]
            if isinstance(allow_list, Bitmap):
                amask = allowed_mask(allow_list, docs)
            else:
                amask = allow_list.contains_array(docs.astype(np.uint64))
            live = live & amask
        n_live = int(live.sum())
        if n_live == 0:
            return empty
        q_sq = (q ** 2).sum(1)[:, None] if self.metric == vi.DISTANCE_L2 \
            else None
        d = np.empty((b, snap.n), np.float32)
        chunk = 4096 if self.metric in (vi.DISTANCE_MANHATTAN,
                                        vi.DISTANCE_HAMMING) \
            else self._HOST_SCAN_CHUNK
        for s in range(0, snap.n, chunk):
            if deadline is not None and time.monotonic() > deadline:
                raise quality.AuditDeadlineExceeded(
                    f"host scan over audit budget at row {s}/{snap.n}")
            blk = rows[s: s + chunk]
            e = s + blk.shape[0]
            if self.metric == vi.DISTANCE_L2:
                qx = q @ blk.T
                d[:, s:e] = np.maximum(
                    q_sq - 2.0 * qx + row_sq[s:e][None, :], 0.0)
            elif self.metric == vi.DISTANCE_DOT:
                d[:, s:e] = -(q @ blk.T)
            elif self.metric == vi.DISTANCE_COSINE:
                d[:, s:e] = 1.0 - q @ blk.T  # rows are insert-normalized
            elif self.metric == vi.DISTANCE_MANHATTAN:
                d[:, s:e] = np.abs(q[:, None, :] - blk[None, :, :]).sum(-1)
            else:  # hamming
                d[:, s:e] = (q[:, None, :] != blk[None, :, :]).sum(-1)
        d[:, ~live] = np.inf
        kk = min(max(int(k), 1), n_live)
        part = np.argpartition(d, kk - 1, axis=1)[:, :kk]
        pd = np.take_along_axis(d, part, axis=1)
        order = np.argsort(pd, axis=1, kind="stable")
        idx = np.take_along_axis(part, order, axis=1)
        top = np.take_along_axis(pd, order, axis=1)
        ids = np.where(np.isinf(top), -1, snap.slot_to_doc[idx])
        return ids.astype(np.uint64), top.astype(np.float32)

    def _ivf_health(self) -> dict:
        """The health() block for the IVF scan plane: partition count,
        bucket fill / padding-waste histogram, imbalance factor, last
        recluster generation, probe accounting. Lock-free racy reads
        like the rest of health()."""
        s = ivf_settings()
        cent = self._ivf_centroids_host
        out = {"enabled": s is not None, "trained": cent is not None}
        if cent is None:
            return out
        meta = self._ivf_meta or (cent.shape[0], self._ivf_cap_p or 0,
                                  self._ivf_gen)
        nlist, cap_p, gen = meta
        out.update({
            "nlist": int(nlist),
            "bucket_capacity": int(cap_p),
            "trained_n": int(self._ivf_trained_n),
            "last_recluster_gen": int(gen),
            "pca_dim": (int(self._ivf_pca_host.shape[1])
                        if self._ivf_pca_host is not None else 0),
        })
        fills = self._ivf_fills
        if fills is not None and fills.size and cap_p:
            total = int(fills.sum())
            mean = total / max(int(nlist), 1)
            out["buckets"] = {
                "fill_min": int(fills.min()),
                "fill_mean": round(mean, 1),
                "fill_max": int(fills.max()),
                "empty": int((fills == 0).sum()),
                # fraction of the padded [nlist, cap_p] table holding
                # sentinel rows the probes still read — the price of
                # jit-stable shapes, and the first thing to check when
                # probed_fraction looks too high for the recall it buys
                "padding_waste": round(1.0 - total / (nlist * cap_p), 4),
                "imbalance": (round(float(fills.max()) / mean, 2)
                              if mean > 0 else None),
                # 8 equal-width fill bins over [0, cap_p] — the skew
                # shape at a glance
                "fill_histogram": np.histogram(
                    fills, bins=8, range=(0, cap_p))[0].tolist(),
            }
        out["probes"] = self.ivf_stats()
        return out

    def health(self) -> dict:
        """Per-index introspection for ``GET /debug/index`` (server/
        rest.py): live/tombstone accounting, snapshot + staged generation
        lag, PQ family state, host-fallback-cache residency. Lock-free by
        design — fields are read racily and may be mutually one mutation
        apart (introspection, not an invariant); nothing here touches the
        device."""
        snap = self._snap
        n, live = self.n, self.live
        tombs = max(n - live, 0)
        cache = self._host_rows_cache
        out = {
            "type": "hnsw_tpu",
            "metric": self.metric,
            "dim": self.dim,
            "capacity": self.capacity,
            "slots": n,
            "live": live,
            "tombstones": tombs,
            "tombstone_fraction": round(tombs / n, 4) if n > 0 else 0.0,
            "pending_adds": len(self._pending),
            "pending_tombstones": len(self._pending_tombs),
            "snapshot_gen": snap.gen if snap is not None else 0,
            "staged_gen": self._staged_gen,
            "published_gen": self._published_gen,
            # staged writes not yet visible to lock-free readers (the
            # read-your-writes flush debt the next read pays)
            "staged_lag": max(self._staged_gen - self._published_gen, 0),
            "compressed": self.compressed,
            "pq": None,
            # the IVF partition layout's health: a skewed or
            # padding-wasteful layout is visible HERE before it costs
            # recall or HBM (the /debug/index satellite)
            "ivf": self._ivf_health(),
            # a resident copy is a full f32 store materialization held for
            # the breaker's fallback plane (or a recent degraded window);
            # bytes come from the ledger's shared sizing helper so this
            # surface and /debug/memory can never disagree
            "host_fallback_cache": {
                "resident": cache is not None,
                "gen": cache[0] if cache is not None else None,
                "bytes": memory.host_rows_cache_bytes(self),
            },
            # the device/host byte picture of THIS index, from the same
            # analytic accounting the ledger stamps (monitoring/memory.py)
            "memory": {
                "device_components": self._memory_components(),
                "host_components": memory.index_host_components(self),
            },
        }
        pq = self._pq
        if self.compressed and pq is not None:
            out["pq"] = {
                "segments": getattr(pq, "segments", None),
                "centroids": getattr(pq, "centroids", None),
                "rotation": bool(getattr(pq, "rotation", False)),
                "rescore": bool(self.config.pq.rescore
                                and self._rescore_dev is not None),
                "code_dtype": str(getattr(pq, "code_dtype", "")),
                # quantization-ladder state (the /debug/index satellite):
                # which bit depth serves, whether an OPQ rotation is
                # pinned, the controller-capped funnel budgets, and the
                # per-stage survivor accounting (racy leaf-lock counters,
                # same contract as the IVF probe stats)
                "bits": 4 if self._codes4 is not None else 8,
                "opq": self._opq_rot_dev is not None,
            }
            if self._codes4 is not None and self._pq4 is not None:
                k_ref = 10  # reference depth for the budget readout
                rg4, rc = self._funnel_budgets(k_ref, max(self.capacity, 1))
                with self._pq4_lock:
                    st = dict(self._pq4_stats)
                d = max(st["dispatches"], 1)
                out["pq"]["funnel"] = {
                    "stage1_c": rg4 * 16,
                    "stage2_rescore": rc,
                    "c_cap": controller.funnel_c_cap(
                        PQ4_FUNNEL_C_BUCKETS[-1]),
                    "rescore_cap": controller.funnel_rescore_cap(
                        PQ4_FUNNEL_RESCORE_BUCKETS[-1]),
                    "dispatches": st["dispatches"],
                    "mean_stage1_rows": round(st["stage1_rows"] / d, 1),
                    "mean_stage2_survivors": round(
                        st["stage2_survivors"] / d, 1),
                    "mean_stage3_survivors": round(
                        st["stage3_survivors"] / d, 1),
                }
        return out

    def search_by_vector(
        self, vector: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        ids, dists = self.search_by_vectors(np.asarray(vector)[None, :], k, allow_list)
        keep = dists[0] != np.inf
        return ids[0][keep], dists[0][keep]

    def search_by_vectors_async(self, vectors: np.ndarray, k: int,
                                allow_list: Optional[AllowList] = None):
        """Dispatch a batched kNN without blocking on the result.

        Returns finalize() -> (ids, dists). Covers EVERY read-path case —
        filtered searches, both PQ tiers, and the small-allowList gather —
        because dispatch runs on an immutable snapshot: there is no
        fully-locked sync fallback left. Dispatch (query upload + compute)
        overlaps with other in-flight batches — the serving loop and bench
        use a depth-2 pipeline so the PCIe/relay upload of batch i+1 hides
        behind the compute of batch i, and the coalescer's finalize runs on
        its dispatch pool without contending with the next enqueue.
        """
        snap = self._read_snapshot()
        return self._dispatch_search(snap, vectors, k, allow_list)

    def search_by_vector_distance(
        self,
        vector: np.ndarray,
        target_distance: float,
        max_limit: int,
        allow_list: Optional[AllowList] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Iteratively double the limit until past the target distance
        (search.go:90-157), except each round is one batched device call."""
        limit = 64
        while True:
            ids, dists = self.search_by_vector(vector, min(limit, max_limit), allow_list)
            if len(ids) == 0:
                return ids, dists
            beyond = dists > target_distance
            if beyond.any() or len(ids) >= min(max_limit, self.live):
                keep = dists <= target_distance
                return ids[keep][:max_limit], dists[keep][:max_limit]
            if limit >= max_limit:
                return ids[:max_limit], dists[:max_limit]
            limit *= 2

    def update_user_config(self, updated: vi.HnswUserConfig) -> None:
        with self._lock:
            vi.validate_config_update(self.config, updated)
            was_enabled = self.config.pq.enabled
            if updated.pq.enabled and not was_enabled and self.dim is not None \
                    and updated.pq.segments > 0 \
                    and self.dim % updated.pq.segments != 0:
                # dims are known: reject synchronously instead of deferring
                # the failure into the compression trigger
                raise vi.ConfigValidationError(
                    f"pq.segments ({updated.pq.segments}) must divide vector "
                    f"dims ({self.dim})")
            prev = self.config
            self.config = updated
            # pq.enabled flipped on by a config update triggers compression
            # (compress.go: "triggered by config update pq.enabled")
            if updated.pq.enabled and not was_enabled and not self.compressed:
                try:
                    self._flush_pending()
                    if self.n > 0:
                        self._compress_locked()
                except Exception:
                    # a failed pq-enable must not stick — config or runtime
                    # (an OOM'd kmeans fit): a committed-but-uncompressed
                    # config would re-run the full fit from _flush_pending's
                    # declarative trigger on every later add/search
                    self.config = prev
                    raise

    def flush(self) -> None:
        with self._lock:
            self._flush_pending()
            if self._log is not None:
                self._log.flush()

    def compact(self) -> None:
        """Condense: drop tombstoned slots, rewrite log (condensor.go analog).
        Under PQ the rebuild re-encodes against the existing codebook."""
        with self._lock:
            self._flush_pending()
            if self.n == 0:
                return
            live_slots = np.array(sorted(self._doc_to_slot.values()), dtype=np.int64)
            if live_slots.size == self.n:
                return
            t_compact0 = time.perf_counter()
            if self.compressed:
                store_host = self._host_vecs[: self.n]
            else:
                store_host = np.asarray(self._store[: self.n]).astype(np.float32)  # graftlint: disable=JGL008 compact is a stop-the-world rebuild: the lock must cover it and the materialized store IS the rebuild's input
            docs = self._slot_to_doc[live_slots]
            vecs = store_host[live_slots]
            if self._log is not None:
                self._log.rewrite(zip(docs.tolist(), vecs))
            # the slot->doc mapping is about to be rebuilt wholesale: any
            # packed-words cache keyed on the old mapping (same n/capacity
            # possible after re-adds) must never be served again
            self._allow_token = object()
            # rebuild device state (uncompressed rebuild, then re-encode);
            # the pq4 quantizer rides along with the 8-bit one so the
            # post-rebuild re-encode preserves BOTH ladders' codebooks
            pq, pq4, was_compressed = self._pq, self._pq4, self.compressed
            self.compressed = False
            self._pq = None
            self._codes = None
            self._rescore_dev = None
            self._rescore_sq_norms = None
            self._recon_norms = None
            self._pq4 = None
            self._codes4 = None
            self._recon_norms4 = None
            self._opq_rot_dev = None
            self._pq4_cb = None
            self._host_vecs = None
            self.dim = None
            self.capacity = 0
            self.n = 0
            self.live = 0
            self._doc_to_slot.clear()
            self._store = self._sq_norms = self._tombs = None
            self._s2d_dev = None
            # the partition layout indexes the OLD slot space — drop it
            # wholesale; the post-rebuild retrain below is the
            # "recluster on compact" half of the IVF lifecycle
            self._ivf_reset()
            self._slot_to_doc = np.zeros(0, dtype=np.int64)
            self._host_tombs = np.zeros(0, dtype=bool)
            # suppress the declarative compress trigger for the rebuild:
            # config.pq.enabled is true for ANY compressed index (compress
            # sets it), so _flush_pending would otherwise re-FIT a fresh
            # codebook mid-rebuild — changing the codes the re-encode
            # below is contracted to preserve, and leaving _store None
            # for it (the auditor's ground-truth parity test caught this)
            prev_restoring = self._restoring
            self._restoring = True
            try:
                for d, v in zip(docs.tolist(), vecs):
                    self._stage_add(int(d), v, log=False)
                self._flush_pending()
            finally:
                self._restoring = prev_restoring
            if was_compressed and self.n > 0:
                fresh = np.asarray(self._store[: self.n], dtype=np.float32)  # graftlint: disable=JGL008 compact is a stop-the-world rebuild: the lock must cover it and the materialized store IS the rebuild's input
                self._enable_pq(pq, fresh, save=False, pq4=pq4)
            # recluster on the compacted slot space (fresh k-means — the
            # densified layout is a different distribution than the
            # tombstone-riddled one); publish so readers see it
            self._maybe_ivf_train()
            if self._published_gen != self._staged_gen:
                self._publish_snapshot()
            led = memory.get_ledger()
            if led is not None:
                led.note_write(
                    "compact", "compact",
                    (time.perf_counter() - t_compact0) * 1000.0,
                    rows=self.live)
            incidents.emit(
                "write_phase", scope="compact", rows=self.live,
                ms=round((time.perf_counter() - t_compact0) * 1000.0, 1))

    def drop(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.close()
                try:
                    os.remove(self._log.path)
                except FileNotFoundError:
                    pass
                self._log = None
            self._store = self._sq_norms = self._tombs = None
            self._s2d_dev = None
            self._ivf_reset()
            self.dim = None
            self.capacity = 0
            self.n = 0
            self.live = 0
            self._slot_to_doc = np.zeros(0, dtype=np.int64)
            self._host_tombs = np.zeros(0, dtype=bool)
            with self._stage_lock:
                # parked staging buffers die with the data (a re-created
                # class may use a different dim; the ledger's
                # stage_buffers component must read 0 after drop)
                self._stage_free.clear()
            self._doc_to_slot.clear()
            self._pending.clear()
            self._pending_tombs.clear()
            self.compressed = False
            self._pq = None
            self._codes = None
            self._rescore_dev = None
            self._rescore_sq_norms = None
            self._recon_norms = None
            self._pq4 = None
            self._codes4 = None
            self._recon_norms4 = None
            self._opq_rot_dev = None
            self._pq4_cb = None
            self._host_vecs = None
            self._staged_gen += 1
            self._publish_snapshot()
            for path in (self._pq_path, self._pq4_path):
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass

    def shutdown(self) -> None:
        with self._lock:
            self._flush_pending()
            if self._log is not None:
                self._log.flush()
                self._log.close()

    def list_files(self) -> list[str]:
        files = [self._log.path] if self._log is not None else []
        for path in (self._pq_path, self._pq4_path):
            if os.path.exists(path):
                files.append(path)
        return files
