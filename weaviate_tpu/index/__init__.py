"""Vector indexes behind the VectorIndex seam.

Reference: adapters/repos/db/vector_index.go:23-40 — the interface through
which shard search reaches any index implementation. Implementations here:

- tpu.TpuVectorIndex  ("hnsw_tpu"/"flat"): HBM-resident batched exact / IVF
- hnsw.HnswIndex      ("hnsw"): native C++ graph engine (CPU parity index)
- noop.NoopIndex      ("noop"/skip=true)
- geo.GeoIndex        (per-geo-property haversine index)
"""

from weaviate_tpu.index.interface import VectorIndex

__all__ = ["VectorIndex", "new_vector_index"]


def new_vector_index(config, shard_path: str, shard_name: str = "", metrics=None,
                     class_name: str = ""):
    """Factory keyed on UserConfig.IndexType() (the discriminator,
    entities/vectorindex/hnsw/config.go:69-71; selection happens in
    shard.go:134 initVectorIndex in the reference). class_name feeds metric
    labels (the path-derived fallback is lowercased on disk)."""
    t = config.IndexType()
    if config.skip or t == "noop":
        from weaviate_tpu.index.noop import NoopIndex

        return NoopIndex(config)
    if t in ("hnsw_tpu", "flat"):
        from weaviate_tpu.index.tpu import TpuVectorIndex

        return TpuVectorIndex(config, shard_path, shard_name, metrics=metrics,
                              class_name=class_name)
    if t == "hnsw_tpu_mesh":
        from weaviate_tpu.index.mesh import MeshVectorIndex

        return MeshVectorIndex(config, shard_path, shard_name, metrics=metrics,
                               class_name=class_name)
    if t == "hnsw":
        try:
            from weaviate_tpu.index.hnsw import HnswIndex
        except ImportError as e:
            raise ValueError(
                "vectorIndexType 'hnsw' requires the native graph engine "
                f"(weaviate_tpu.index.hnsw): {e}"
            ) from e
        return HnswIndex(config, shard_path, shard_name, metrics=metrics,
                         class_name=class_name)
    raise ValueError(f"unknown vector index type {t!r}")
