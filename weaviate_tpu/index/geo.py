"""Per-geo-property index: batched haversine distance on device.

Reference: adapters/repos/db/vector/geo (geo.go:60 NewIndex) wraps the HNSW
core with a haversine distancer (distancer/geo_spatial.go) to answer
WithinGeoRange filters. A graph is the wrong shape for TPU; the equivalent
here is a flat [N, 2] coordinate store scanned with one vectorized haversine
evaluation per query — exact, batched, and trivially maskable.
"""

from __future__ import annotations

import os
import struct
import threading

import numpy as np

from weaviate_tpu.storage.bitmap import Bitmap

EARTH_RADIUS_M = 6_371_000.0
_MAGIC = b"WTGE"


def haversine_m(lat1, lon1, lat2, lon2):
    """Vectorized haversine distance in meters (geo_spatial.go parity)."""
    p1, p2 = np.radians(lat1), np.radians(lat2)
    dp = np.radians(lat2 - lat1)
    dl = np.radians(lon2 - lon1)
    a = np.sin(dp / 2.0) ** 2 + np.cos(p1) * np.cos(p2) * np.sin(dl / 2.0) ** 2
    return 2.0 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))


class GeoIndex:
    """Append-log-persisted flat coordinate index."""

    def __init__(self, path: str, persist: bool = True):
        self.path = path
        self._lock = threading.Lock()
        self._doc_ids: list[int] = []
        self._coords: list[tuple[float, float]] = []
        self._deleted: set[int] = set()
        self._log = None
        if persist:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            valid_end = self._replay()
            if os.path.exists(self._log_path):
                size = os.path.getsize(self._log_path)
                if valid_end < size:
                    # truncate a torn tail so future appends stay replayable
                    with open(self._log_path, "r+b") as f:
                        f.truncate(max(valid_end, 0))
            new = not os.path.exists(self._log_path) or os.path.getsize(self._log_path) < 4
            self._log = open(self._log_path, "ab")
            if new:
                self._log.write(_MAGIC)

    @property
    def _log_path(self) -> str:
        return self.path + ".log"

    def _replay(self) -> int:
        """-> byte offset of the last fully-valid record (for tail truncation)."""
        if not os.path.exists(self._log_path):
            return 0
        with open(self._log_path, "rb") as f:
            data = f.read()
        if data[:4] != _MAGIC:
            return 0
        off = 4
        while off + 1 <= len(data):
            op = data[off]
            if op == 1 and off + 25 <= len(data):
                did, lat, lon = struct.unpack_from("<Qdd", data, off + 1)
                self._doc_ids.append(did)
                self._coords.append((lat, lon))
                self._deleted.discard(did)
                off += 25
            elif op == 2 and off + 9 <= len(data):
                (did,) = struct.unpack_from("<Q", data, off + 1)
                self._deleted.add(did)
                off += 9
            else:
                break  # torn tail
        return off

    def add(self, doc_id: int, lat: float, lon: float) -> None:
        with self._lock:
            self._doc_ids.append(int(doc_id))
            self._coords.append((float(lat), float(lon)))
            self._deleted.discard(int(doc_id))
            if self._log is not None:
                self._log.write(struct.pack("<BQdd", 1, int(doc_id), float(lat), float(lon)))

    def delete(self, doc_id: int) -> None:
        with self._lock:
            self._deleted.add(int(doc_id))
            if self._log is not None:
                self._log.write(struct.pack("<BQ", 2, int(doc_id)))

    def __len__(self) -> int:
        return len(set(self._doc_ids) - self._deleted)

    def within_range(self, lat: float, lon: float, max_distance_m: float) -> Bitmap:
        with self._lock:
            if not self._doc_ids:
                return Bitmap()
            ids = np.asarray(self._doc_ids, dtype=np.uint64)
            coords = np.asarray(self._coords, dtype=np.float64)
        d = haversine_m(lat, lon, coords[:, 0], coords[:, 1])
        hits = ids[d <= max_distance_m]
        if self._deleted:
            dele = np.fromiter(self._deleted, dtype=np.uint64)
            hits = hits[~np.isin(hits, dele)]
        return Bitmap(hits)

    def knn(self, lat: float, lon: float, k: int) -> tuple[np.ndarray, np.ndarray]:
        with self._lock:
            if not self._doc_ids:
                return np.zeros(0, np.uint64), np.zeros(0, np.float32)
            ids = np.asarray(self._doc_ids, dtype=np.uint64)
            coords = np.asarray(self._coords, dtype=np.float64)
        d = haversine_m(lat, lon, coords[:, 0], coords[:, 1])
        if self._deleted:
            dele = np.fromiter(self._deleted, dtype=np.uint64)
            d = np.where(np.isin(ids, dele), np.inf, d)
        order = np.argsort(d)[:k]
        return ids[order], d[order].astype(np.float32)

    def flush(self) -> None:
        if self._log is not None:
            self._log.flush()
            os.fsync(self._log.fileno())

    def shutdown(self) -> None:
        if self._log is not None:
            self._log.flush()
            self._log.close()
            self._log = None

    def drop(self) -> None:
        self.shutdown()
        try:
            os.remove(self._log_path)
        except FileNotFoundError:
            pass

    def list_files(self) -> list[str]:
        return [self._log_path] if os.path.exists(self._log_path) else []
