"""The VectorIndex seam.

Reference: adapters/repos/db/vector_index.go:23-40. Everything above the
index (shard search, traverser, gRPC) passes (vector, k, allowList) down and
gets (ids, dists) back; nothing above sees index internals. Kept exactly so
here, with a batched twin (`search_by_vectors`) because the TPU path is
batch-first.
"""

from __future__ import annotations

import abc
from typing import Optional, Sequence

import numpy as np


class AllowList(abc.ABC):
    """Filter result container (reference helpers/allow_list.go:19-29)."""

    @abc.abstractmethod
    def contains(self, doc_id: int) -> bool: ...

    @abc.abstractmethod
    def __len__(self) -> int: ...

    @abc.abstractmethod
    def to_array(self) -> np.ndarray:
        """Sorted uint64 array of allowed doc ids."""

    @abc.abstractmethod
    def contains_array(self, doc_ids: np.ndarray) -> np.ndarray:
        """Vectorized membership test -> bool array (device mask building)."""


class VectorIndex(abc.ABC):
    """Per-shard vector index (vector_index.go:23-40)."""

    # -- metric plumbing shared by the concrete indexes (hnsw metrics.go
    # parity); relies on self.shard_path / self.shard_name / self.metrics,
    # which every persistent index sets in __init__ --------------------------

    def _metric_labels(self) -> tuple[str, str]:
        """(class_name, shard_name). The owning Shard sets `class_name`
        after construction so labels match the shard-level families exactly
        (the on-disk dir is lowercased and would mislabel); the path-derived
        value is only the standalone-index fallback."""
        import os

        path = getattr(self, "shard_path", "") or ""
        cls = getattr(self, "class_name", "") or (
            os.path.basename(os.path.dirname(path.rstrip("/"))) or "")
        return cls, getattr(self, "shard_name", "") or os.path.basename(path)

    def _obs_index(self, op: str, step: str, t0: float, ops: int = 0) -> None:
        import time

        m = getattr(self, "metrics", None)
        if m is None:
            return
        cls, shard = self._metric_labels()
        m.vector_index_durations.labels(op, step, cls, shard).observe(
            (time.perf_counter() - t0) * 1000.0)
        if ops:
            m.vector_index_ops.labels(op, cls, shard).inc(ops)

    @abc.abstractmethod
    def add(self, doc_id: int, vector: np.ndarray) -> None: ...

    def add_batch(self, doc_ids: Sequence[int], vectors: np.ndarray) -> None:
        for d, v in zip(doc_ids, vectors):
            self.add(int(d), v)

    @abc.abstractmethod
    def delete(self, *doc_ids: int) -> None: ...

    @abc.abstractmethod
    def search_by_vector(
        self, vector: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """-> (doc_ids uint64 [<=k], dists float32 [<=k]) sorted ascending."""

    def search_by_vectors(
        self, vectors: np.ndarray, k: int, allow_list: Optional[AllowList] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Batched kNN [B, D] -> ([B, k] ids, [B, k] dists); default loops."""
        ids, ds = [], []
        for v in vectors:
            i, d = self.search_by_vector(v, k, allow_list)
            pad = k - len(i)
            if pad:
                # sentinel = uint64 max (matches the TPU index's -1 cast);
                # consumers must treat dist==inf rows as absent
                i = np.concatenate([i, np.full(pad, np.iinfo(np.uint64).max, np.uint64)])
                d = np.concatenate([d, np.full(pad, np.inf, np.float32)])
            ids.append(i)
            ds.append(d)
        return np.stack(ids), np.stack(ds)

    @abc.abstractmethod
    def search_by_vector_distance(
        self,
        vector: np.ndarray,
        target_distance: float,
        max_limit: int,
        allow_list: Optional[AllowList] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """All results within target_distance (search.go:90-157 semantics:
        iteratively double the limit until past the target distance)."""

    @abc.abstractmethod
    def update_user_config(self, updated) -> None: ...

    @abc.abstractmethod
    def flush(self) -> None:
        """Flush WAL/commit-log state to disk (SwitchCommitLogs analog)."""

    @abc.abstractmethod
    def drop(self) -> None: ...

    @abc.abstractmethod
    def shutdown(self) -> None: ...

    def post_startup(self) -> None:
        """Prefill device/cache state after restore (startup.go:169-174)."""

    def list_files(self) -> list[str]:
        """Files to include in a backup (hnsw/backup.go ListFiles)."""
        return []

    def contains(self, doc_id: int) -> bool:
        return False

    def __len__(self) -> int:
        return 0

    def distancer_name(self) -> str:
        return "l2-squared"

    # multi-vector/compression stats surface
    def compressed(self) -> bool:
        return False
