"""Cross-request query coalescing: continuous micro-batching for kNN.

The shard read path is batch-first (`Shard.object_vector_search` scores a
whole [B, D] query block in one device dispatch), but that only batches the
vectors INSIDE one request: 256 concurrent single-query REST/GraphQL/gRPC
users cost 256 one-wide dispatches. The distance kernel only approaches
roofline at meaningful batch width, so under concurrent single-query load
the device spends its time on dispatch overhead instead of math.

This module closes that gap with an admission queue in front of the shard:
concurrent requests land in a *lane* keyed by everything that must match for
their rows to share one device dispatch — (shard, k, metric,
filter-signature, include_vector) — and a lane flushes as ONE padded
dispatch when either

  (a) its row count fills the configured batch-width bucket (`max_batch`,
      snapped DOWN to the same padding buckets the index's `_bucket_b`
      rounds query widths to, so a full lane hits the same jit cache as
      direct dispatches without exceeding the configured cap), or
  (b) the deadline window (default ~1.5 ms) since the lane's first arrival
      expires — the Orca/vLLM-style continuous-batching tradeoff: bounded
      added latency buys full-width dispatches.

Dispatch rides the existing two-phase path (`object_vector_search_async`):
the flush thread enqueues device work in dispatch order, while finalize +
hydration runs on a small dispatch pool so lanes overlap device compute
with hydration and with each other. FILTERED lanes ride the same two-phase
pipeline (snapshot-isolated indexes dispatch filtered searches, both PQ
tiers, and the small-allowList gather without a lock — index/tpu.py
IndexSnapshot and the multi-chip twin index/mesh.py MeshSnapshot); only
index types without snapshot dispatch (hnsw, noop) still run their whole
blocking search on the pool.
Results scatter back to per-request waiters. k is deliberately part of the
lane key — requests only share a dispatch at IDENTICAL k — because the
bit-identical contract (coalesced == direct, pinned by the tests) would
not survive dispatching at max-k and trimming: approximate k-selection
(lax.approx_min_k on TPU) is not prefix-stable across different k.

Bypass (the caller uses the direct path, counted per reason): requests
wider than `max_request_rows` (they already fill a dispatch on their own),
filters with no stable signature (a per-request allowList can never share a
lane), COLD filter signatures (first sighting within the recency TTL — a
unique per-tenant filter would otherwise pay the full window in a
singleton lane for zero merging; only filters proven hot by a recent
repeat are queued), multi-shard/remote layouts, a shut-down coalescer,
and a DEAD flush thread (`flusher_dead` — liveness: queueing into a lane
nobody will ever flush would strand every admitted request on its wait
bound).

The flush thread only ADMITS and ENQUEUES: each lane's blocking work
(async finalize + hydration, or the sync filtered search) runs on a small
dispatch pool, so one slow lane — an expensive allowList build, a big
hydration — cannot head-of-line-block other lanes' flushes.

Request-lifecycle robustness (serving/robustness.py):

  - ADMISSION CONTROL: the queue is bounded in ROWS (`max_queued_rows` —
    cost-aware: one 16-row request occupies 16 slots), and a request whose
    estimated queue wait (queued rows over the EWMA service rate) already
    exceeds its remaining deadline is shed at admission — both raise
    ``OverloadedError`` (-> 429/RESOURCE_EXHAUSTED + Retry-After) instead
    of silently stalling the whole client population.

Multi-tenant fairness (ROADMAP item 4 — the PR-6 tentpole). The bounds
above are GLOBAL: without tenant accounting one abusive tenant fills
`max_queued_rows` with its own requests and every other tenant starves
while each individual request stays under the row bound. Admission is
therefore tenant-aware end to end:

  - IDENTITY: every request resolves a tenant (`robustness.
    effective_tenant` — the REST/gRPC `X-Tenant-Id` identity when one
    rode in, else the queried class name) and the tenant is part of the
    lane key: a lane belongs to exactly ONE tenant, so fairness decisions
    and accounting operate on whole lanes.
  - BUDGET: no tenant may occupy more than `tenant_rows_fraction` of
    `max_queued_rows` while other tenants have work in the system
    (`tenant_budget` shed). Occupancy counts a tenant's rows from
    ADMISSION until its lane SETTLES (queued + in-flight): a queue-only
    bound refills the instant the flusher pops a lane, so an abusive
    tenant bounded to N queued rows still monopolizes the dispatch
    pipeline one popped lane at a time — the in-flight extension is
    what actually caps its share of dispatch slots. Alone, a tenant may
    still use the whole queue — the cap costs an only-tenant nothing.
  - DEFICIT ROUND-ROBIN: due lanes drain in weighted DRR order
    (configurable `tenant_weights`, default 1): each tenant's deficit
    grows by `weight * max_batch` rows per round and pays for its lanes
    in rotation, so under a saturated pipeline (depth-1 semaphore — the
    drain ORDER is the fairness lever) an abusive tenant cannot
    monopolize dispatch slots.
  - PER-TENANT SHED ESTIMATES: the deadline-unreachable estimate divides
    the TENANT'S OWN queued rows by its own EWMA drain rate — an abusive
    tenant sheds against its backlog while light tenants admit against
    theirs (a shared estimate would shed everyone for one tenant's
    queue).
  - ACCOUNTING: per-tenant shed/deadline/queue-depth metrics with
    BOUNDED label cardinality (metrics.TenantLabeler: top-K by traffic +
    "other"), tenant tags on dispatch trace records and the admission
    annotation on rider traces, and a `serving.coalescer.admit` fault
    point for abusive-tenant storm journeys.
  - DEADLINES: a waiter carries its request's deadline; the flush path
    fails deadline-expired waiters fast (they never occupy dispatch rows),
    and every waiter wait is bounded by min(remaining deadline, the
    `waiter_timeout_s` liveness cap) — a wedged flush thread can cost a
    client a bounded wait, never a hang.
  - NO ORPHANED LANES: every pool submission carries a done-callback
    (`_reap_lane_future`) that wakes the lane's waiters and frees its
    in-flight slot if the task was cancelled at shutdown or died outside
    its own error handling — waiters never depend on the 0.1 s inflight
    poll (that poll remains only as the flusher's shutdown check).

Error handling is all-or-nothing per lane: a dispatch exception (or
shutdown) propagates to EVERY queued waiter — no request may hang on a
dead batch. The flush loop itself is defended: any unexpected error fails
the affected lanes and the loop keeps serving. (A BaseException — the
fault harness's injected thread death — still kills the thread; the
bounded waits plus the `flusher_dead` bypass keep every client live.)
"""

from __future__ import annotations

import functools
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

# lane keys reuse the shard's filter-content key, so two requests share a
# lane exactly when they would resolve to the same cached allowList; batch
# caps snap to the index's query-padding buckets so coalesced shapes hit
# the same jit cache as direct dispatches. record_device_fallback hoisted
# to module scope (PR 1 pattern): failure paths must not die on an import.
from weaviate_tpu.db.shard import filter_signature
from weaviate_tpu.index.tpu import _B_BUCKETS
from weaviate_tpu.monitoring import incidents, perf, tracing
from weaviate_tpu.monitoring.metrics import record_device_fallback
# the self-tuning control plane (serving/controller.py): admission reads
# its leased knobs — flush window, admission margin, tenant-cap scale,
# Retry-After scale, tenant rate quotas — each a one-comparison no-op
# while the plane is off. controller never imports this module back
# (it receives the coalescer object at App wiring), so no cycle.
from weaviate_tpu.serving import controller, robustness
from weaviate_tpu.testing import faults, sanitizers


class CoalescerShutdownError(RuntimeError):
    """Raised to waiters whose lane was still queued at shutdown."""


class CoalescerTimeoutError(RuntimeError):
    """A waiter's liveness bound expired before its lane resolved (wedged
    or dead flush path). The serving thread retries on the direct path —
    this is NOT a deadline error (the request's own budget may be fine)."""


def _bucket_floor(n: int) -> int:
    """Largest index padding bucket <= n (the DOWN twin of tpu._bucket_b):
    a full lane then lands exactly on a bucket without ever exceeding the
    operator's configured cap. Beyond the largest bucket the index pads in
    multiples of it, so the floor follows the same rule."""
    top = _B_BUCKETS[-1]
    if n >= top:
        return (n // top) * top
    best = _B_BUCKETS[0]
    for s in _B_BUCKETS:
        if s <= n:
            best = s
    return best


class _Waiter:
    """One queued request: its rows plus the rendezvous the serving thread
    blocks on. `trace_span` is the submitter's active span, captured on the
    serving thread at admission — the explicit handoff that carries trace
    context across the flush-thread / dispatch-pool boundary (contextvars
    do not follow the lane). `deadline` is captured the same way: the
    flush path prunes expired waiters, and wait() is bounded by it."""

    __slots__ = ("vectors", "event", "result", "error", "enqueued_at",
                 "trace_span", "deadline", "max_wait_s", "tenant",
                 "tenant_label")

    def __init__(self, vectors: np.ndarray, max_wait_s: float = 30.0,
                 tenant: Optional[str] = None, tenant_label: str = ""):
        self.vectors = vectors
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        self.trace_span = tracing.current_span()
        self.deadline = robustness.current_deadline()
        self.max_wait_s = max_wait_s
        self.tenant = tenant
        self.tenant_label = tenant_label

    def wait(self):
        """Block until the lane resolves -> per-row result lists. BOUNDED:
        by the request's remaining deadline when one is set (plus a small
        grace for the scatter), and always by `max_wait_s` — a wedged
        flush thread can never hang a client forever. A deadline-bound
        timeout raises DeadlineExceededError (fail fast, no retry); a
        liveness-bound one raises CoalescerTimeoutError (the serving
        thread retries on the direct path)."""
        timeout = self.max_wait_s
        d = self.deadline
        if d is not None:
            timeout = min(timeout, max(d.remaining_s(), 0.0) + 0.05)
        if not self.event.wait(timeout):
            if d is not None and d.expired():
                robustness.count_deadline("coalescer.wait")
                robustness.count_tenant_deadline(self.tenant)
                raise robustness.DeadlineExceededError(
                    "request deadline expired waiting for a coalesced "
                    "dispatch")
            # degraded liveness path: the caller re-runs direct — make the
            # double device work countable, not invisible
            record_device_fallback("serving.coalescer", "waiter_timeout",
                                   note=f"waited {timeout:.1f}s")
            raise CoalescerTimeoutError(
                f"coalesced dispatch did not resolve within {timeout:.1f}s "
                "(wedged or dead flush path); retry direct")
        if self.error is not None:
            raise self.error
        return self.result


class _Lane:
    """Accumulating batch for one (tenant, shard, k, metric, filter-sig,
    inc_vec) key. Never touched outside the coalescer lock until popped
    for flush. `settled`/`released` (guarded by the coalescer lock) make
    waiter wakeup and in-flight-slot release idempotent across the normal
    path and the pool-future reaper. A lane belongs to exactly ONE tenant
    (the tenant is part of the key), so DRR drains whole lanes and the
    per-tenant row accounting is exact; `tenant_label` is the bounded
    metric label captured at lane creation — gauge inc/dec must use the
    SAME label even if the labeler's top-K churns in between."""

    __slots__ = ("key", "shard", "flt", "k", "include_vector", "items",
                 "rows", "deadline", "settled", "released", "dispatch_start",
                 "tenant", "tenant_label")

    def __init__(self, key, shard, flt, k: int, include_vector: bool,
                 deadline: float, tenant: str = "",
                 tenant_label: str = ""):
        self.key = key
        self.shard = shard
        self.flt = flt
        self.k = k
        self.include_vector = include_vector
        self.items: list[_Waiter] = []
        self.rows = 0
        self.deadline = deadline
        self.settled = False     # waiters woken (resolved or failed)
        self.released = False    # in-flight slot given back
        self.dispatch_start: Optional[float] = None
        self.tenant = tenant
        self.tenant_label = tenant_label


class _TenantState:
    """Per-tenant fairness bookkeeping, guarded by the coalescer lock:
    in-system rows (admission -> lane settle, the budget cap's
    numerator), the tenant's own EWMA drain rate (rows/s — feeds ITS
    deadline-unreachable estimate), and shed counts for stats()/bench.
    DRR deficits are deliberately NOT stored here: classic DRR forfeits
    credit when a queue empties, and every _drr_order call drains its
    whole input, so deficits are per-call locals — persistent fields
    would imply cross-flush carryover that does not exist."""

    __slots__ = ("tenant", "weight", "rows", "ewma_rows_per_s",
                 "shed", "last_seen")

    def __init__(self, tenant: str, weight: float = 1.0):
        self.tenant = tenant
        self.weight = max(float(weight), 0.001)
        self.rows = 0
        self.ewma_rows_per_s = 0.0
        self.shed: dict[str, int] = {}
        self.last_seen = time.monotonic()


class QueryCoalescer:
    def __init__(self, window_s: float = 0.0015, max_batch: int = 256,
                 max_request_rows: int = 16, metrics=None,
                 pipeline_depth: int = 1, max_queued_rows: int = 4096,
                 waiter_timeout_s: float = 30.0,
                 tenant_weights: Optional[dict] = None,
                 tenant_rows_fraction: float = 0.5):
        self.window_s = max(float(window_s), 0.0)
        # snap DOWN to the index's padding buckets: a full lane then
        # compiles/hits the exact shape a direct dispatch of that width
        # would, and the configured cap is never exceeded (snapping up
        # would silently inflate the operator's dispatch-size bound 4x)
        self.max_batch = max(_bucket_floor(max(int(max_batch), 2)), 2)
        if self.max_batch != int(max_batch):
            import logging

            # visible, or an operator watching the occupancy histogram top
            # out below their configured cap has nothing to explain it
            logging.getLogger(__name__).info(
                "query coalescer max_batch %d snapped DOWN to padding "
                "bucket %d (buckets: %s)", int(max_batch), self.max_batch,
                _B_BUCKETS)
        # re-clamp AFTER the snap: config validates against the unsnapped
        # cap, and a single admitted request must never overflow a dispatch
        self.max_request_rows = max(
            1, min(int(max_request_rows), self.max_batch))
        # admission bound in ROWS (cost-aware shedding: a 16-row request
        # costs 16 queue slots); overflow sheds with OverloadedError
        self.max_queued_rows = max(int(max_queued_rows), 1)
        self.waiter_timeout_s = max(float(waiter_timeout_s), 0.001)
        self.metrics = metrics
        self._lock = sanitizers.register_lock(
            threading.Lock(), "serving.coalescer")
        self._cv = threading.Condition(self._lock)
        self._lanes: dict[tuple, _Lane] = {}
        self._full: list[_Lane] = []  # popped at submit time, flush ASAP
        self._queued_rows = 0
        self._closed = False
        # filter-signature recency: a filtered request only queues when its
        # signature was seen within the TTL (someone to merge with is
        # plausible); a cold signature bypasses so one-off filters never
        # pay the window for an inevitable singleton lane
        self._sig_ttl = max(1.0, self.window_s * 100.0)
        self._recent_sigs: dict[str, float] = {}
        # cheap python-side counters (bench/tests read these without a
        # prometheus round trip; the histograms carry the same data)
        self._dispatches = 0
        self._dispatched_requests = 0
        self._dispatched_rows = 0
        self._bypass: dict[str, int] = {}
        self._shed: dict[str, int] = {}
        # multi-tenant fairness state (guarded by the coalescer lock):
        # per-tenant queued rows / DRR deficit / own-EWMA, the configured
        # weights, and the per-tenant slice of max_queued_rows no tenant
        # may exceed while others are waiting. The cap never falls below
        # max_request_rows: a budget smaller than one admissible request
        # would deadlock that tenant outright.
        self._tenant_weights = dict(tenant_weights or {})
        self.tenant_rows_fraction = min(max(float(tenant_rows_fraction),
                                            0.01), 1.0)
        self._tenant_row_cap = max(
            int(self.max_queued_rows * self.tenant_rows_fraction),
            self.max_request_rows)
        self._tenants: dict[str, _TenantState] = {}
        # sum of every tenant's in-system rows (admission -> settle);
        # "other tenants have work" is then one subtraction, not a scan
        self._pipeline_rows_total = 0
        self._drr_cursor = 0
        # EWMA of the PER-LANE dispatch service rate (rows/s), fed by
        # resolved lanes: the admission-time queue-wait estimate that
        # sheds requests whose deadline the queue can't meet. 0.0 =
        # unknown (no resolved dispatch yet) — only the hard row cap
        # sheds then. Up to `pipeline_depth` lanes drain CONCURRENTLY, so
        # the aggregate drain rate is ~depth x the per-lane EWMA — the
        # estimate divides by it, or shedding would over-fire by depth x
        # exactly under the load it protects.
        self._depth = max(int(pipeline_depth), 1)
        # pipeline-depth decrements can't forcibly reclaim a busy permit:
        # set_pipeline_depth records a deficit that _release_lane consumes
        # (the next lane completions simply don't give their slots back)
        self._depth_deficit = 0
        self._ewma_rows_per_s = 0.0
        # blocking per-lane work (finalize+hydration, sync filtered search)
        # runs on this pool; the flush thread only admits/enqueues, capped
        # at `pipeline_depth` lanes in flight. While every slot is busy the
        # flusher BLOCKS — that stall is the backpressure that lets the
        # next window's lanes accumulate to full width. Measured on the
        # CPU-JAX acceptance workload (64 clients, n=50k): depth 1 = 4.7x
        # the uncoalesced QPS at ~30 requests/dispatch; depth 2 = 2.7x at
        # ~13 (two in-flight scans contend for the same host cores);
        # unbounded = 1.3x at ~5 (no backpressure, every window flushes
        # thin). Depth 1 is therefore the default; a real TPU backend,
        # where finalize/hydration is host work that overlaps device
        # compute, is the case for raising it to 2.
        self._inflight = threading.Semaphore(max(int(pipeline_depth), 1))
        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=max(int(pipeline_depth), 1) + 2,
            thread_name_prefix="coalescer-dispatch")
        # front-door sheds (the tenant concurrency gate) hint with this
        # coalescer's per-tenant drain estimate instead of a constant.
        # The bound method is captured ONCE: `self.retry_hint` mints a
        # new object per access, and shutdown's still-ours clearing
        # compares by identity
        self._retry_hint_fn = self.retry_hint
        robustness.set_retry_hint_provider(self._retry_hint_fn)
        self._thread = threading.Thread(
            target=self._run, name="query-coalescer", daemon=True)
        self._thread.start()

    # -- admission -----------------------------------------------------------

    def submit(self, shard, vectors: np.ndarray, k: int, flt=None,
               include_vector: bool = False, tenant: Optional[str] = None):
        """Queue a request's rows for a coalesced dispatch.

        -> a blocking callable() -> list[list[SearchResult]] (one list per
        row), or None when the request must bypass to the direct path
        (reason counted). Raises DeadlineExceededError for an
        already-expired request (fail fast: it must not occupy queue
        rows), and OverloadedError when admission control sheds it
        (bounded queue full, the tenant's row budget exhausted while
        others wait, or the tenant's estimated queue wait exceeds the
        remaining deadline) — shed requests must NOT fall through to the
        direct path, or shedding would shed nothing.

        `tenant` is the request's accounting identity; None resolves via
        robustness.effective_tenant (explicit X-Tenant-Id, else the
        shard's class name)."""
        robustness.check_deadline("coalescer.admit")
        # fault-injection point: the abusive-tenant storm journeys inject
        # stalls/errors at ADMISSION — before any queue state is touched,
        # so an injected failure can never strand a half-admitted waiter
        faults.fire("serving.coalescer.admit")
        if tenant is None:
            cd = getattr(shard, "class_def", None)
            tenant = robustness.effective_tenant(
                getattr(cd, "name", None) or "default")
        q = np.asarray(vectors, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if q.shape[0] > self.max_request_rows:
            self.record_bypass("oversize")
            return None
        sig = filter_signature(flt)
        if sig is None:
            self.record_bypass("unique_allow_list")
            return None
        if not self._thread.is_alive():
            # liveness: a dead flush thread (fault-injected or real) must
            # not collect requests into lanes nobody will ever flush. A
            # normally-shut-down coalescer also has no flusher — keep that
            # counted as "shutdown", not as a liveness incident.
            with self._lock:
                closed_now = self._closed
            if not closed_now:
                # a DEAD flusher (not a clean shutdown) is an incident:
                # journal it (burst-coalesced — every admission attempt
                # lands here while it stays dead) and fire the flight
                # recorder so the thread's last state is preserved. Both
                # are one-comparison no-ops when the plane is off and
                # exception-guarded internally (monitoring/incidents.py).
                incidents.emit("flusher_dead", scope="serving.coalescer")
                incidents.trigger(
                    "flusher_dead",
                    reason="coalescer flush thread died; admissions "
                           "bypassing to the direct path")
            self.record_bypass("shutdown" if closed_now else "flusher_dead")
            return None
        # tenant rate quota (serving/controller.py token buckets —
        # TENANT_RATE_QPS x DRR weight): the PR-6 row budget bounds
        # OCCUPANCY, this bounds request RATE. Checked before any queue
        # state is touched; Retry-After = time-to-next-token, scaled up
        # while the brownout ladder is engaged. One comparison when the
        # control plane is off.
        ra_rate = controller.take_rate_token(tenant)
        if ra_rate is not None:
            self._record_shed("tenant_rate", tenant)
            raise robustness.OverloadedError(
                f"tenant {tenant!r} over its request-rate quota "
                "(TENANT_RATE_QPS)",
                retry_after_s=ra_rate * controller.retry_after_scale())
        d = robustness.current_deadline()
        # tenant first in the key: a lane belongs to one tenant (fair
        # drain + exact accounting); dim is part of the key so a
        # wrong-dim request lands in its own lane and fails ALONE, not
        # poisoning the concatenate of its lane-mates
        key = (tenant, id(shard), int(k),
               getattr(shard.vector_index, "metric", ""),
               sig, bool(include_vector), int(q.shape[1]))
        cold = False
        shed_reason: Optional[str] = None
        # cold-start fallback hint (no resolved dispatch yet => no drain
        # EWMA anywhere): a few flush windows is the only drain clock the
        # server has — every warmer path below replaces it with a
        # measured estimate
        retry_after = max(self.window_s * 4.0, 0.05)
        eff_cap = self._tenant_row_cap
        with self._cv:
            closed = self._closed
            if not closed and sig:
                # filtered request: queue only when this signature was seen
                # recently (a lane-mate is plausible); cold signatures go
                # direct — a one-off per-tenant filter must not pay the
                # window for a singleton lane
                now = time.monotonic()
                last = self._recent_sigs.get(sig)
                self._recent_sigs[sig] = now
                if len(self._recent_sigs) > 1024:
                    pruned = {s: t for s, t in self._recent_sigs.items()
                              if now - t <= self._sig_ttl}
                    # all-hot overflow (>1024 live signatures inside the
                    # TTL): pruning can't shrink, and rebuilding O(n) under
                    # the admission lock on EVERY submit would serialize the
                    # fast path — reset instead; hot filters re-warm with
                    # one direct request each, amortized O(1) per overflow
                    self._recent_sigs = (pruned if len(pruned) <= 896
                                         else {sig: now})
                cold = last is None or now - last > self._sig_ttl
            if not closed and not cold:
                st = self._tenant_state(tenant)
                # admission control BEFORE touching any lane: shed with a
                # retry hint instead of silently stalling. Cost-aware: the
                # bound is ROWS. Tenant-aware: the budget counts the
                # tenant's rows from admission to lane SETTLE and fires
                # only while OTHER tenants have work in the system
                # (alone, a tenant may use the whole queue), and the
                # deadline-unreachable estimate divides the tenant's OWN
                # backlog by its OWN drain rate — an abusive tenant sheds
                # against its queue, light tenants admit against theirs.
                rows = int(q.shape[0])
                rate = st.ewma_rows_per_s or self._ewma_rows_per_s
                est_wait = (st.rows / (rate * self._depth)
                            if rate > 0.0 else None)
                global_est = (
                    self._queued_rows / (self._ewma_rows_per_s * self._depth)
                    if self._ewma_rows_per_s > 0.0 else None)
                # control-plane knobs (one comparison each when off): the
                # brownout ladder inflates the wait estimate (shed
                # earlier) and shrinks the per-tenant cap under burn
                eff_cap = self._tenant_row_cap
                cap_scale = controller.tenant_cap_scale()
                if cap_scale != 1.0:
                    # never below one admissible request — a scaled cap
                    # must not deadlock a tenant the configured cap admits
                    eff_cap = max(int(eff_cap * cap_scale),
                                  self.max_request_rows)
                if self._queued_rows + rows > self.max_queued_rows:
                    shed_reason = "queue_full"
                    if global_est is not None:
                        retry_after = global_est
                elif (st.rows + rows > eff_cap
                      and self._pipeline_rows_total > st.rows):
                    shed_reason = "tenant_budget"
                    if est_wait is not None:
                        retry_after = est_wait
                elif (d is not None and est_wait is not None
                      and est_wait * controller.admission_margin()
                      > max(d.remaining_s(), 0.0)):
                    shed_reason = "deadline_unreachable"
                    retry_after = est_wait
            if not closed and not cold and shed_reason is None:
                # wake the flusher only when the picture it sleeps on
                # changes: a new lane (new earliest deadline) or a lane
                # popped to _full (new due work). Appending to an existing
                # lane changes neither — notifying there would wake/rescan
                # the flusher once per REQUEST on the hot path instead of
                # once per window.
                wake = False
                lane = self._lanes.get(key)
                if lane is not None and lane.rows + q.shape[0] > self.max_batch:
                    # this request would overflow the bucket: flush the lane
                    # as-is and start fresh — a dispatch must never exceed
                    # max_batch, or it pads to the NEXT bucket and compiles
                    # a shape the direct path never uses
                    del self._lanes[key]
                    self._full.append(lane)
                    lane = None
                    wake = True
                if lane is None:
                    # flush window: controller-steered (leased knob,
                    # clamped to the configured band; the configured
                    # default while the plane is off/stale). Read at lane
                    # creation so an actuation applies from the NEXT lane
                    # — in-flight lanes keep the deadline they promised.
                    lane = _Lane(key, shard, flt, int(k),
                                 bool(include_vector),
                                 time.monotonic()
                                 + controller.coalescer_window_s(
                                     self.window_s),
                                 tenant=tenant,
                                 tenant_label=self._tenant_label(tenant))
                    self._lanes[key] = lane
                    wake = True
                w = _Waiter(q, max_wait_s=self.waiter_timeout_s,
                            tenant=tenant, tenant_label=lane.tenant_label)
                lane.items.append(w)
                lane.rows += q.shape[0]
                self._queued_rows += q.shape[0]
                st.rows += q.shape[0]
                self._pipeline_rows_total += q.shape[0]
                st.last_seen = time.monotonic()
                if lane.rows >= self.max_batch:
                    # bucket full: pop now so later arrivals start fresh
                    del self._lanes[key]
                    self._full.append(lane)
                    wake = True
                self._set_depth_gauge()
                self._tenant_gauge(lane.tenant_label, q.shape[0])
                if wake:
                    self._cv.notify()
        if closed:
            # outside the lock: record_bypass takes it again
            self.record_bypass("shutdown")
            return None
        if cold:
            self.record_bypass("cold_filter")
            return None
        if shed_reason is not None:
            self._record_shed(shed_reason, tenant)
            if shed_reason == "queue_full":
                detail = (f"{self._queued_rows} rows queued, cap "
                          f"{self.max_queued_rows}")
            else:
                # tenant-scoped reasons cite the TENANT's numbers: a 429
                # naming a near-empty global queue would read as a bug to
                # the operator debugging it
                st_now = self._tenants.get(tenant)
                detail = (f"tenant {tenant!r}: "
                          f"{st_now.rows if st_now is not None else 0} "
                          f"rows in system, tenant cap {eff_cap}")
            # the hint scales up while the brownout ladder is engaged —
            # under burn, backing clients off harder IS the actuation
            raise robustness.OverloadedError(
                f"query admission queue overloaded ({shed_reason}: "
                f"{detail})",
                retry_after_s=retry_after * controller.retry_after_scale())
        # outside the lock: the tenant tag lands on the rider's trace at
        # admission (the slow-query log's join key), and the per-tenant
        # admitted-request counter moves through the bounded labeler
        tracing.annotate_current("tenant", tenant)
        m = self.metrics
        if m is not None:
            try:
                m.tenant_requests.labels(
                    m.tenant_labels.observe(tenant)).inc()
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass
        return w.wait

    def record_bypass(self, reason: str) -> None:
        """Count a request that took the direct path instead of the queue."""
        # always called on the bypassing request's own serving thread, so
        # the reason lands on ITS trace (the direct dispatch that follows
        # records its own spans there too)
        tracing.annotate_current("coalescer_bypass", reason)
        with self._lock:
            self._bypass[reason] = self._bypass.get(reason, 0) + 1
        m = self.metrics
        if m is not None:
            try:
                m.coalescer_bypass.labels(reason).inc()
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    def _record_shed(self, reason: str, tenant: Optional[str] = None) -> None:
        tracing.annotate_current("coalescer_shed", reason)
        if tenant:
            tracing.annotate_current("tenant", tenant)
        with self._lock:
            self._shed[reason] = self._shed.get(reason, 0) + 1
            if tenant:
                st = self._tenant_state(tenant)
                st.shed[reason] = st.shed.get(reason, 0) + 1
        robustness.count_shed(reason)
        robustness.count_tenant_shed(tenant, reason)

    # -- per-tenant fairness state (callers hold the coalescer lock unless
    # -- noted) ---------------------------------------------------------------

    def _tenant_state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            st = _TenantState(tenant, self._tenant_weights.get(tenant, 1.0))
            self._tenants[tenant] = st
            if len(self._tenants) > 1024:
                # a storm of invented tenant ids must not grow this dict
                # without bound: drop idle states (no queued rows), oldest
                # first — their deficit/EWMA re-warm on the next request
                idle = sorted((t for t, s in self._tenants.items()
                               if s.rows <= 0 and t != tenant),
                              key=lambda t: self._tenants[t].last_seen)
                for t in idle[: max(len(self._tenants) - 768, 0)]:
                    del self._tenants[t]
        return st

    def _tenant_label(self, tenant: str) -> str:
        """Bounded metric label for `tenant` (no lock needed — the labeler
        has its own)."""
        m = self.metrics
        if m is None:
            return tenant
        try:
            return m.tenant_labels.label_for(tenant)
        except Exception:  # noqa: BLE001 — metrics must not break serving
            return tenant

    def _tenant_gauge(self, label: str, delta: int) -> None:
        """Move the per-tenant queued-rows gauge by `delta` under the SAME
        label the lane captured at creation (labeler churn between inc
        and dec must not leak gauge value into another label)."""
        m = self.metrics
        if m is not None and label:
            try:
                m.tenant_queued_rows.labels(label).inc(delta)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    def _merge_due(self, due: "list[_Lane]") -> "list[_Lane]":
        """Coalesce due lanes that differ ONLY by tenant into one
        dispatch-ready lane (runs after _drr_order, flusher-owned lanes,
        no lock needed). The base key — (shard, k, metric, filter-sig,
        include_vector, dim) — is exactly the pre-tenancy lane key, so a
        merged dispatch is bit-identical to what the tenant-blind
        coalescer would have dispatched. DRR order is preserved: the
        accumulator lane keeps the earliest DRR position, and when a
        merged dispatch would exceed max_batch the overflow starts a new
        one in order — under contention the DRR-favored tenants' rows
        get the batch slots, which IS the weighted-fair drain."""
        groups: dict[tuple, _Lane] = {}
        out: list[_Lane] = []
        for ln in due:
            base = ln.key[1:] if isinstance(ln.key, tuple) else ln.key
            acc = groups.get(base)
            if acc is None or acc.rows + ln.rows > self.max_batch:
                groups[base] = ln
                out.append(ln)
                continue
            acc.items.extend(ln.items)
            acc.rows += ln.rows
            if acc.tenant != ln.tenant:
                # mixed riders: per-waiter accounting handles budgets and
                # gauges; the lane-level tag only labels traces
                acc.tenant = "multi"
                acc.tenant_label = ""
        return out

    def _drr_order(self, due: "list[_Lane]") -> "list[_Lane]":
        """Deficit-round-robin over the due lanes' tenants (caller holds
        the coalescer lock). Per round, each tenant's deficit grows by
        `weight * max_batch` rows and pays for its lanes (FIFO within the
        tenant) while the deficit covers them — a weight-2 tenant drains
        two full dispatches for a weight-1 tenant's one. Classic DRR
        discipline: a tenant whose queue empties forfeits its remaining
        deficit (credit must not accumulate while idle), and the rotation
        start advances every cycle so the same tenant never structurally
        goes first. Single-tenant input returns unchanged (FIFO — the
        anonymous same-class common case pays nothing)."""
        by_t: dict[str, deque] = {}
        for ln in due:
            by_t.setdefault(ln.tenant, deque()).append(ln)
        if len(by_t) <= 1:
            return due
        rotation = list(by_t.keys())
        start = self._drr_cursor % len(rotation)
        rotation = rotation[start:] + rotation[:start]
        self._drr_cursor += 1
        quantum = float(self.max_batch)
        deficits = {t: 0.0 for t in rotation}  # per-call: see _TenantState
        order: list[_Lane] = []
        while by_t:
            for t in rotation:
                q = by_t.get(t)
                if q is None:
                    continue
                deficits[t] += quantum * self._tenant_state(t).weight
                while q and q[0].rows <= deficits[t]:
                    ln = q.popleft()
                    deficits[t] -= ln.rows
                    order.append(ln)
                if not q:
                    del by_t[t]
        return order

    # -- flush loop ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            # fault-injection point: a `die` action here (BaseException)
            # kills the flush thread the way a real thread death would —
            # liveness then rests on bounded waiter waits + the
            # `flusher_dead` bypass, which the journey tests pin
            faults.fire("serving.coalescer.flush")
            due: list[_Lane] = []
            with self._cv:
                while not self._closed:
                    now = time.monotonic()
                    due = self._full
                    self._full = []
                    expired = [k for k, ln in self._lanes.items()
                               if ln.deadline <= now]
                    for k in expired:
                        due.append(self._lanes.pop(k))
                    if due:
                        break
                    timeout = None
                    if self._lanes:
                        timeout = max(
                            min(ln.deadline for ln in self._lanes.values())
                            - now, 0.0)
                    self._cv.wait(timeout)
                if self._closed:
                    due.extend(self._full)
                    due.extend(self._lanes.values())
                    self._full = []
                    self._lanes.clear()
                for ln in due:
                    # global queue bound releases at pop; the PER-TENANT
                    # budget holds until the lane SETTLES (_mark_settled)
                    # — a queue-only budget would refill the instant the
                    # flusher popped, letting one tenant monopolize the
                    # dispatch pipeline one popped lane at a time
                    self._queued_rows -= ln.rows
                if len(due) > 1:
                    # weighted-fair drain: under a saturated pipeline the
                    # in-flight semaphore serializes dispatches, so the
                    # ORDER lanes leave this loop is the fairness lever —
                    # deficit-round-robin across tenants replaces FIFO
                    due = self._drr_order(due)
                self._set_depth_gauge()
                closed = self._closed
            if closed:
                err = CoalescerShutdownError(
                    "query coalescer shut down with requests queued")
                for ln in due:
                    self._fail_lane(ln, err)
                return
            if len(due) > 1:
                # per-tenant lanes are the DRR sub-queues; compatible
                # ones MERGE back into one device dispatch here (the
                # issue's "sub-queues drained by DRR into lanes"):
                # isolation lives in admission budgets and drain order,
                # while the dispatch itself stays shared — an admitted
                # abusive rider widens a light tenant's batch instead of
                # serializing a whole dispatch ahead of it
                due = self._merge_due(due)
            try:
                self._flush(due)
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # anything _flush itself failed to contain: no waiter may
                # hang, and the next window must still be served
                for ln in due:
                    self._fail_lane(ln, e)

    def _settle_discard(self, done) -> None:
        """Settle an orphaned, already-enqueued dispatch (results
        discarded) WITHOUT blocking the flusher: done() is a blocking
        device fetch, and a wedged device must never pin the flush
        thread (shutdown joins it with a bounded timeout). Runs on the
        dispatch pool; if the pool is already torn down the dispatch is
        abandoned — the process is exiting and the index's in-flight
        gauge dies with it."""
        def run() -> None:
            try:
                done()
            except Exception:  # noqa: BLE001 — results already discarded
                pass

        try:
            self._dispatch_pool.submit(run)
        except Exception:  # noqa: BLE001 — pool shut down: abandon
            pass

    def _acquire_slot(self) -> bool:
        """Block until one of the `pipeline_depth` in-flight slots frees,
        or the coalescer closes (-> False). The 0.1 s poll is ONLY the
        flusher's shutdown check: a pool task that dies frees its slot
        via _reap_lane_future."""
        while not self._inflight.acquire(timeout=0.1):
            if self._closed:
                return False
        return True

    def _flush(self, due: list[_Lane]) -> None:
        """Pipelined flush. Async-capable unfiltered lanes ENQUEUE their
        device program on this thread FIRST and only then wait for an
        in-flight slot — so lane i+1's device compute is already queued
        behind lane i's program while lane i's blocking fetch/hydration
        is still in flight (the fused-dispatch host pipelining: the
        existing `pipeline_depth` cap still bounds concurrent finalizes,
        and the flusher's stall on a busy pipeline is still the
        backpressure that lets the next window's lanes fill). Sync and
        filtered lanes take their slot first as before — their whole
        search runs on the dispatch pool."""
        for i, ln in enumerate(due):
            if not self._prune_expired(ln):
                # every rider's deadline passed in the queue: the lane
                # must not occupy a dispatch slot (none acquired yet)
                self._mark_settled(ln)
                continue
            done = rec = None
            slot = False
            try:
                faults.fire("serving.coalescer.dispatch")
                vidx = ln.shard.vector_index
                async_plain = (hasattr(vidx, "search_by_vectors_async")
                               and ln.flt is None)
                if async_plain:
                    # enqueue BEFORE taking a slot: the device work of
                    # this lane overlaps the previous lane's fetch
                    q = (ln.items[0].vectors if len(ln.items) == 1
                         else np.concatenate([w.vectors for w in ln.items]))
                    self._observe_wait(ln)  # queue wait ends at dispatch
                    rec = self._trace_record(ln)
                    done = ln.shard.object_vector_search_async(
                        q, ln.k, include_vector=ln.include_vector)
                if not self._acquire_slot():
                    # shutdown while waiting: nothing may hang — fail
                    # EVERY waiter first (immediate wakeups), and only
                    # then settle the already-enqueued dispatch (results
                    # discarded): done() is a blocking fetch, and a
                    # wedged device must not stand between the remaining
                    # lanes' waiters and their shutdown error
                    err = CoalescerShutdownError(
                        "query coalescer shut down with requests queued")
                    self._fail_lane(ln, err)
                    for rest in due[i + 1:]:
                        self._fail_lane(rest, err)
                    if done is not None:
                        if rec is not None:
                            # a dispatch DID run: close the riders' spans
                            # (attribution spans never leak — the PR-3
                            # contract) even though the results are about
                            # to be discarded
                            try:
                                rec.finish()
                            except Exception:  # noqa: BLE001 — teardown
                                pass
                        self._settle_discard(done)
                    return
                slot = True
                if async_plain:
                    self._submit_lane_task(self._finalize_async, ln, done,
                                           rec)
                elif ln.flt is not None and hasattr(
                        vidx, "search_by_vectors_async"):
                    # filtered lanes: the allowList resolution (an
                    # inverted-index scan on a cache miss) must not
                    # head-of-line block the flusher — resolve, enqueue
                    # AND finalize on the pool. The search itself still
                    # rides the lock-free two-phase snapshot path inside
                    # object_vector_search_async (or the sync fallback
                    # for index types without filtered async).
                    self._submit_lane_task(self._dispatch_filtered, ln)
                else:
                    # indexes without true async dispatch (hnsw,
                    # noop): the whole blocking search runs on the pool —
                    # object_vector_search_async's sync fallback would
                    # otherwise execute it inline in THIS thread and
                    # head-of-line-block every other lane
                    self._submit_lane_task(self._dispatch_sync, ln)
            except Exception as e:  # noqa: BLE001 — propagate to all waiters
                # covers pool.submit after shutdown too: no waiter may hang
                self._fail_lane(ln, e)
                if slot:
                    self._release_lane(ln)
                if done is not None:
                    if rec is not None:
                        # a dispatch WAS enqueued and its finalize task
                        # never ran: close the riders' spans here (an
                        # enqueue that itself raised leaves rec unused —
                        # no dispatch happened, so no span is fabricated)
                        try:
                            rec.finish()
                        except Exception:  # noqa: BLE001 — failed lane
                            pass
                    # settle the enqueued dispatch so the index's
                    # in-flight gauge and any device work don't leak;
                    # results are discarded, and the blocking fetch stays
                    # off the flusher thread
                    self._settle_discard(done)

    def _submit_lane_task(self, fn, lane: _Lane, *args) -> None:
        """Pool submission with a reaper: if the task is cancelled at
        shutdown before running, or dies OUTSIDE its own error handling
        (BaseException, pool teardown), its waiters still wake and its
        in-flight slot still frees — nobody waits on the 0.1 s poll."""
        fut = self._dispatch_pool.submit(fn, lane, *args)
        fut.add_done_callback(functools.partial(self._reap_lane_future, lane))

    def _reap_lane_future(self, lane: _Lane, fut) -> None:
        if fut.cancelled():
            err: BaseException = CoalescerShutdownError(
                "dispatch task cancelled before running")
        else:
            err = fut.exception()
            if err is None:
                return  # the task ran its own settle/release path
            if not isinstance(err, Exception):
                # a BaseException must not propagate into a serving thread
                err = RuntimeError(
                    f"coalescer dispatch task died: {err!r}")
        self._fail_lane(lane, err)
        self._release_lane(lane)

    # -- lane lifecycle (idempotent under the coalescer lock) ----------------

    def _release_rows_locked(self, waiters) -> "list[tuple[str, int]]":
        """Release `waiters`' per-tenant budget rows (caller holds the
        coalescer lock). Accounting is PER WAITER, not per lane — a
        flush-merged dispatch carries several tenants' riders in one
        lane. -> [(gauge label, rows)] for the metric moves the caller
        makes OFF-lock."""
        out = []
        for w in waiters:
            rows = int(w.vectors.shape[0])
            st = self._tenants.get(w.tenant or "")
            if st is not None:
                st.rows = max(st.rows - rows, 0)
            self._pipeline_rows_total = max(
                self._pipeline_rows_total - rows, 0)
            out.append((w.tenant_label, rows))
        return out

    def _mark_settled(self, lane: _Lane) -> bool:
        """First-caller-wins claim on waking the lane's waiters. The
        claim also RELEASES the waiters' per-tenant budget rows
        (admission -> settle is the occupancy the tenant_budget cap
        bounds)."""
        with self._lock:
            if lane.settled:
                return False
            lane.settled = True
            released = self._release_rows_locked(lane.items)
        for label, rows in released:
            self._tenant_gauge(label, -rows)
        return True

    def _release_lane(self, lane: _Lane) -> None:
        """Give the lane's in-flight slot back exactly once. A pending
        pipeline-depth decrement (set_pipeline_depth) consumes the slot
        instead of returning it — depth shrinks as lanes complete, never
        by forcing an in-flight dispatch."""
        with self._lock:
            if lane.released:
                return
            lane.released = True
            if self._depth_deficit > 0:
                self._depth_deficit -= 1
                return
        self._inflight.release()

    def set_pipeline_depth(self, depth: int) -> int:
        """Adjust the in-flight lane cap at runtime (the control plane's
        lane controller; serving/controller.py is the only caller
        outside tests — graftlint JGL014). Increases release permits
        immediately; decreases queue a deficit that completing lanes
        absorb. -> the depth now in effect for the shed estimator."""
        depth = max(int(depth), 1)
        to_release = 0
        with self._lock:
            delta = depth - self._depth
            self._depth = depth
            if delta > 0:
                consumed = min(self._depth_deficit, delta)
                self._depth_deficit -= consumed
                to_release = delta - consumed
            elif delta < 0:
                self._depth_deficit += -delta
        for _ in range(to_release):
            self._inflight.release()
        return depth

    def retry_hint(self, tenant: Optional[str]) -> Optional[float]:
        """Estimated seconds until `tenant` could be served again — the
        Retry-After basis for front-door sheds
        (robustness.drain_retry_hint). Two drain clocks, whichever is
        slower: the tenant's own in-system backlog at ITS drain rate
        (a gate slot frees when one of its own requests finishes), and
        the SHARED queue backlog at the global rate — a gate-capped
        tenant holds almost no rows of its own, so under congestion the
        shared clock is the honest one; hinting from the tenant clock
        alone told a storm's abuser "retry in 50 ms" while every request
        was taking 500, and the refusal churn starved the light tenants.
        None while nothing has resolved yet (the caller keeps its
        cold-start default)."""
        with self._lock:
            st = self._tenants.get(tenant or "")
            t_rate = (st.ewma_rows_per_s
                      if st is not None and st.ewma_rows_per_s > 0.0
                      else self._ewma_rows_per_s)
            rows = st.rows if st is not None else 0
            g_rate = self._ewma_rows_per_s
            queued = self._queued_rows
            depth = self._depth
        if t_rate <= 0.0 and g_rate <= 0.0:
            return None
        own = (max(rows, 1.0) / (t_rate * depth)) if t_rate > 0.0 else 0.0
        shared = (queued / (g_rate * depth)) if g_rate > 0.0 else 0.0
        return max(own, shared, 0.01)

    def _prune_expired(self, lane: _Lane) -> bool:
        """Fail the lane's deadline-expired waiters fast (they must not
        occupy dispatch rows) -> True when live riders remain. Runs on the
        flusher AND again on the pool thread right before the dispatch —
        time passes between the two."""
        live: list[_Waiter] = []
        expired: list[_Waiter] = []
        for w in lane.items:
            (expired if w.deadline is not None and w.deadline.expired()
             else live).append(w)
        if not expired:
            return True
        for w in expired:
            robustness.count_deadline("coalescer.queue")
            robustness.count_tenant_deadline(w.tenant)
            tracing.annotate_span(w.trace_span, "coalescer_deadline",
                                  "expired in admission queue")
            w.error = robustness.DeadlineExceededError(
                "request deadline expired in the coalescer admission queue")
            w.event.set()
        lane.items = live
        lane.rows = sum(w.vectors.shape[0] for w in live)
        # expired waiters leave the lane before settle: release their
        # share of the tenant budget now (settle only releases the
        # waiters still aboard)
        released = []
        with self._lock:
            if not lane.settled:
                released = self._release_rows_locked(expired)
        for label, rows in released:
            self._tenant_gauge(label, -rows)
        return bool(live)

    def _dispatch_filtered(self, lane: _Lane) -> None:
        """Pool-side twin of the flusher's async enqueue for FILTERED
        lanes: allowList build + two-phase enqueue + finalize, all off the
        flusher thread. Enqueue ordering across filtered lanes is pool
        order (exactly the pre-snapshot behavior); the win vs the old
        sync path is that the search holds no index lock."""
        try:
            if not self._prune_expired(lane):
                self._mark_settled(lane)
                self._release_lane(lane)
                return
            q = (lane.items[0].vectors if len(lane.items) == 1
                 else np.concatenate([w.vectors for w in lane.items]))
            self._observe_wait(lane)
            rec = self._trace_record(lane)
            # record pushed around the enqueue too: an index without
            # filtered async runs the WHOLE sync search eagerly inside
            # this call, and its phases must land on the lane's record.
            # The tenant scope rides along explicitly: contextvars do not
            # follow the flush-thread/pool handoff, and the shard's
            # allowList cache attributes entries by the ACTIVE tenant —
            # without this, every coalesced filtered entry would land on
            # the class-name bucket and the per-tenant share bound would
            # bound nothing ("multi" for merged cross-tenant lanes: a
            # shared filter belongs to no single tenant's share).
            tok = tracing.push_dispatch(rec)
            try:
                with robustness.tenant_scope(lane.tenant or None):
                    done = lane.shard.object_vector_search_async(
                        q, lane.k, include_vector=lane.include_vector,
                        flt=lane.flt)
            finally:
                tracing.pop_dispatch(tok)
        except Exception as e:  # noqa: BLE001 — propagate to all waiters
            self._fail_lane(lane, e)
            self._release_lane(lane)
            return
        self._finalize_async(lane, done, rec)

    def _dispatch_sync(self, lane: _Lane) -> None:
        try:
            if not self._prune_expired(lane):
                self._mark_settled(lane)
                return
            q = np.concatenate([w.vectors for w in lane.items]) \
                if len(lane.items) > 1 else lane.items[0].vectors
            self._observe_wait(lane)
            rec = self._trace_record(lane)
            tok = tracing.push_dispatch(rec)
            try:
                # the shard's phase recording lands in `rec` via the
                # dispatch contextvar set for THIS pool thread; the
                # tenant scope is the same explicit handoff as
                # _dispatch_filtered (allowList-cache attribution)
                with robustness.tenant_scope(lane.tenant or None):
                    res = lane.shard.object_vector_search(
                        q, lane.k, lane.flt, None, lane.include_vector)
            finally:
                tracing.pop_dispatch(tok)
            if rec is not None:
                # attribution completes BEFORE waiters wake: a request
                # thread reading its own trace after wait() must see its
                # dispatch span already attached
                rec.finish()
            self._resolve_lane(lane, res)
        except Exception as e:  # noqa: BLE001 — propagate to all waiters
            self._fail_lane(lane, e)
        finally:
            self._release_lane(lane)

    def _finalize_async(self, lane: _Lane, done, rec=None) -> None:
        try:
            tok = tracing.push_dispatch(rec)
            try:
                res = done()
            finally:
                tracing.pop_dispatch(tok)
            if rec is not None:
                rec.finish()  # before waiters wake — see _dispatch_sync
            self._resolve_lane(lane, res)
        except Exception as e:  # noqa: BLE001 — propagate to all waiters
            self._fail_lane(lane, e)
        finally:
            self._release_lane(lane)

    def _trace_record(self, lane: _Lane):
        """DispatchRecord for this lane's traced riders (span + rows +
        queue wait per rider), or None when tracing is off or no rider was
        sampled. Unowned: finish() runs here in the coalescer, after the
        device work and before the waiters wake."""
        if tracing.get_tracer() is None:
            return None
        now = time.monotonic()
        riders = [(w.trace_span, int(w.vectors.shape[0]),
                   (now - w.enqueued_at) * 1000.0)
                  for w in lane.items if w.trace_span is not None]
        if not riders:
            return None
        return tracing.DispatchRecord(
            riders, owned=False, actual_rows=lane.rows, coalesced=True,
            lane_requests=len(lane.items), k=lane.k, tenant=lane.tenant)

    def _observe_wait(self, lane: _Lane) -> None:
        """Admission-queue wait per request, observed AT dispatch start —
        observing at resolution would fold the search+hydration latency in
        and make the histogram useless for tuning the window. Also stamps
        `dispatch_start` for the EWMA service-rate estimate."""
        now = time.monotonic()
        lane.dispatch_start = now
        m = self.metrics
        if m is not None:
            try:
                for w in lane.items:
                    m.coalescer_wait.observe((now - w.enqueued_at) * 1000.0)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass
        pw = perf.get_window()
        if pw is not None:
            # queue_wait feeds the host-overhead ledger window per admitted
            # request — full coverage, independent of trace sampling (the
            # perf window exists only while the tracer is up, so the
            # disabled path is the one comparison above)
            try:
                for w in lane.items:
                    pw.note_phase("queue_wait",
                                  (now - w.enqueued_at) * 1000.0)
            except Exception:  # noqa: BLE001 — must not break serving
                pass

    def _resolve_lane(self, lane: _Lane, res) -> None:
        """Scatter [rows] result lists back to the lane's waiters. No k
        trimming is needed: k is part of the lane key (see submit), so every
        waiter here asked for exactly the k the dispatch ran at. Under the
        fused dispatch the per-row ids/distances inside `res` are views
        into the lane's ONE packed device fetch (index/tpu.py fused
        finalize) — this scatter's row slices are the only per-waiter
        work between the fetch and the reply."""
        if not self._mark_settled(lane):
            return  # reaper/failure path won the race; results discarded
        pw = perf.get_window()
        scatter_t0 = time.perf_counter() if pw is not None else 0.0
        pos = 0
        try:
            for w in lane.items:
                r = w.vectors.shape[0]
                w.result = res[pos: pos + r]
                pos += r
                w.event.set()
        finally:
            # a scatter bug must not leave later waiters hanging
            for w in lane.items:
                if not w.event.is_set():
                    w.error = RuntimeError(
                        "coalescer failed to scatter batch results")
                    w.event.set()
        if pw is not None:
            # the ledger's final stage: result scatter back to the waiters
            try:
                pw.note_phase(
                    "scatter", (time.perf_counter() - scatter_t0) * 1000.0)
            except Exception:  # noqa: BLE001 — must not break serving
                pass
        now = time.monotonic()
        with self._lock:
            self._dispatches += 1
            self._dispatched_requests += len(lane.items)
            self._dispatched_rows += lane.rows
            if lane.dispatch_start is not None and lane.rows > 0:
                dur = max(now - lane.dispatch_start, 1e-4)
                rate = lane.rows / dur
                self._ewma_rows_per_s = (
                    rate if self._ewma_rows_per_s <= 0.0
                    else 0.3 * rate + 0.7 * self._ewma_rows_per_s)
                # each rider tenant's OWN drain-rate estimate: feeds ITS
                # deadline-unreachable shedding, so one tenant's slow
                # lanes never shed another tenant's requests (a merged
                # dispatch drains every rider at the lane's rate)
                for t in {w.tenant for w in lane.items if w.tenant}:
                    st = self._tenants.get(t)
                    if st is not None:
                        st.ewma_rows_per_s = (
                            rate if st.ewma_rows_per_s <= 0.0
                            else 0.3 * rate + 0.7 * st.ewma_rows_per_s)
        m = self.metrics
        if m is not None:
            try:
                m.coalescer_batch_requests.observe(len(lane.items))
                m.coalescer_batch_rows.observe(lane.rows)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    def _fail_lane(self, lane: _Lane, err: BaseException) -> None:
        if not self._mark_settled(lane):
            return
        # a failed lane means every waiter silently re-runs on the direct
        # path (coalesce window + dead dispatch + duplicate search): make
        # that degradation COUNTABLE, not invisible — the JGL004 rule
        if not isinstance(err, CoalescerShutdownError):
            record_device_fallback("serving.coalescer", "lane_dispatch_failed",
                                   err)
        key = ("coalescer_shutdown"
               if isinstance(err, CoalescerShutdownError)
               else "coalescer_error")
        for w in lane.items:
            # error/shutdown paths close out the trace side too: the rider
            # trace gets the failure reason (annotation, not an open span —
            # nothing to leak), BEFORE the waiter wakes and possibly
            # re-runs direct
            tracing.annotate_span(w.trace_span, key,
                                  f"{type(err).__name__}: {err}")
            w.error = err
            w.event.set()

    def _set_depth_gauge(self) -> None:
        m = self.metrics
        if m is not None:
            try:
                m.coalescer_queue_depth.set(self._queued_rows)
            except Exception:  # noqa: BLE001
                pass

    # -- introspection / lifecycle -------------------------------------------

    def stats(self) -> dict:
        # the front-door concurrency gate sheds BEFORE admission ever sees
        # the request; its refusals belong in the same operator view as the
        # queue's (the ROADMAP item-4 follow-up) — read through the
        # process-wide global, like the serving paths do
        gate = robustness.get_tenant_gate()
        gate_stats = gate.stats() if gate is not None else None
        with self._lock:
            d = self._dispatches
            return {
                "tenant_gate": gate_stats,
                "dispatches": d,
                "requests": self._dispatched_requests,
                "rows": self._dispatched_rows,
                "mean_requests_per_dispatch":
                    (self._dispatched_requests / d) if d else 0.0,
                "mean_rows_per_dispatch":
                    (self._dispatched_rows / d) if d else 0.0,
                "bypass": dict(self._bypass),
                "shed": dict(self._shed),
                "ewma_rows_per_s": self._ewma_rows_per_s,
                "tenant_row_cap": self._tenant_row_cap,
                "pipeline_depth": self._depth,
                "pipeline_depth_deficit": self._depth_deficit,
                "tenants": {
                    t: {"rows_in_system": s.rows, "weight": s.weight,
                        "shed": dict(s.shed),
                        "ewma_rows_per_s": s.ewma_rows_per_s}
                    for t, s in self._tenants.items()
                },
            }

    def shutdown(self) -> None:
        robustness.clear_retry_hint_provider(self._retry_hint_fn)
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)
        # in-flight dispatch tasks run to completion (each wakes its own
        # waiters, success or failure); nothing new can be submitted —
        # tasks cancelled before running are reaped by _reap_lane_future
        self._dispatch_pool.shutdown(wait=False)
