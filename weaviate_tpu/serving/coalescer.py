"""Cross-request query coalescing: continuous micro-batching for kNN.

The shard read path is batch-first (`Shard.object_vector_search` scores a
whole [B, D] query block in one device dispatch), but that only batches the
vectors INSIDE one request: 256 concurrent single-query REST/GraphQL/gRPC
users cost 256 one-wide dispatches. The distance kernel only approaches
roofline at meaningful batch width, so under concurrent single-query load
the device spends its time on dispatch overhead instead of math.

This module closes that gap with an admission queue in front of the shard:
concurrent requests land in a *lane* keyed by everything that must match for
their rows to share one device dispatch — (shard, k, metric,
filter-signature, include_vector) — and a lane flushes as ONE padded
dispatch when either

  (a) its row count fills the configured batch-width bucket (`max_batch`,
      snapped DOWN to the same padding buckets the index's `_bucket_b`
      rounds query widths to, so a full lane hits the same jit cache as
      direct dispatches without exceeding the configured cap), or
  (b) the deadline window (default ~1.5 ms) since the lane's first arrival
      expires — the Orca/vLLM-style continuous-batching tradeoff: bounded
      added latency buys full-width dispatches.

Dispatch rides the existing two-phase path (`object_vector_search_async`):
the flush thread enqueues device work in dispatch order, while finalize +
hydration runs on a small dispatch pool so lanes overlap device compute
with hydration and with each other. FILTERED lanes ride the same two-phase
pipeline (snapshot-isolated indexes dispatch filtered searches, both PQ
tiers, and the small-allowList gather without a lock — index/tpu.py
IndexSnapshot); only index types without snapshot dispatch (hnsw, noop,
mesh) still run their whole blocking search on the pool.
Results scatter back to per-request waiters. k is deliberately part of the
lane key — requests only share a dispatch at IDENTICAL k — because the
bit-identical contract (coalesced == direct, pinned by the tests) would
not survive dispatching at max-k and trimming: approximate k-selection
(lax.approx_min_k on TPU) is not prefix-stable across different k.

Bypass (the caller uses the direct path, counted per reason): requests
wider than `max_request_rows` (they already fill a dispatch on their own),
filters with no stable signature (a per-request allowList can never share a
lane), COLD filter signatures (first sighting within the recency TTL — a
unique per-tenant filter would otherwise pay the full window in a
singleton lane for zero merging; only filters proven hot by a recent
repeat are queued), multi-shard/remote layouts, and a shut-down coalescer.

The flush thread only ADMITS and ENQUEUES: each lane's blocking work
(async finalize + hydration, or the sync filtered search) runs on a small
dispatch pool, so one slow lane — an expensive allowList build, a big
hydration — cannot head-of-line-block other lanes' flushes.

Error handling is all-or-nothing per lane: a dispatch exception (or
shutdown) propagates to EVERY queued waiter — no request may hang on a
dead batch. The flush loop itself is defended: any unexpected error fails
the affected lanes and the loop keeps serving.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

# lane keys reuse the shard's filter-content key, so two requests share a
# lane exactly when they would resolve to the same cached allowList; batch
# caps snap to the index's query-padding buckets so coalesced shapes hit
# the same jit cache as direct dispatches. record_device_fallback hoisted
# to module scope (PR 1 pattern): failure paths must not die on an import.
from weaviate_tpu.db.shard import filter_signature
from weaviate_tpu.index.tpu import _B_BUCKETS
from weaviate_tpu.monitoring import tracing
from weaviate_tpu.monitoring.metrics import record_device_fallback


class CoalescerShutdownError(RuntimeError):
    """Raised to waiters whose lane was still queued at shutdown."""


def _bucket_floor(n: int) -> int:
    """Largest index padding bucket <= n (the DOWN twin of tpu._bucket_b):
    a full lane then lands exactly on a bucket without ever exceeding the
    operator's configured cap. Beyond the largest bucket the index pads in
    multiples of it, so the floor follows the same rule."""
    top = _B_BUCKETS[-1]
    if n >= top:
        return (n // top) * top
    best = _B_BUCKETS[0]
    for s in _B_BUCKETS:
        if s <= n:
            best = s
    return best


class _Waiter:
    """One queued request: its rows plus the rendezvous the serving thread
    blocks on. `trace_span` is the submitter's active span, captured on the
    serving thread at admission — the explicit handoff that carries trace
    context across the flush-thread / dispatch-pool boundary (contextvars
    do not follow the lane)."""

    __slots__ = ("vectors", "event", "result", "error", "enqueued_at",
                 "trace_span")

    def __init__(self, vectors: np.ndarray):
        self.vectors = vectors
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        self.trace_span = tracing.current_span()

    def wait(self):
        """Block until the lane resolves -> per-row result lists."""
        self.event.wait()
        if self.error is not None:
            raise self.error
        return self.result


class _Lane:
    """Accumulating batch for one (shard, k, metric, filter-sig, inc_vec)
    key. Never touched outside the coalescer lock until popped for flush."""

    __slots__ = ("key", "shard", "flt", "k", "include_vector", "items",
                 "rows", "deadline")

    def __init__(self, key, shard, flt, k: int, include_vector: bool,
                 deadline: float):
        self.key = key
        self.shard = shard
        self.flt = flt
        self.k = k
        self.include_vector = include_vector
        self.items: list[_Waiter] = []
        self.rows = 0
        self.deadline = deadline


class QueryCoalescer:
    def __init__(self, window_s: float = 0.0015, max_batch: int = 256,
                 max_request_rows: int = 16, metrics=None,
                 pipeline_depth: int = 1):
        self.window_s = max(float(window_s), 0.0)
        # snap DOWN to the index's padding buckets: a full lane then
        # compiles/hits the exact shape a direct dispatch of that width
        # would, and the configured cap is never exceeded (snapping up
        # would silently inflate the operator's dispatch-size bound 4x)
        self.max_batch = max(_bucket_floor(max(int(max_batch), 2)), 2)
        if self.max_batch != int(max_batch):
            import logging

            # visible, or an operator watching the occupancy histogram top
            # out below their configured cap has nothing to explain it
            logging.getLogger(__name__).info(
                "query coalescer max_batch %d snapped DOWN to padding "
                "bucket %d (buckets: %s)", int(max_batch), self.max_batch,
                _B_BUCKETS)
        # re-clamp AFTER the snap: config validates against the unsnapped
        # cap, and a single admitted request must never overflow a dispatch
        self.max_request_rows = max(
            1, min(int(max_request_rows), self.max_batch))
        self.metrics = metrics
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._lanes: dict[tuple, _Lane] = {}
        self._full: list[_Lane] = []  # popped at submit time, flush ASAP
        self._queued_rows = 0
        self._closed = False
        # filter-signature recency: a filtered request only queues when its
        # signature was seen within the TTL (someone to merge with is
        # plausible); a cold signature bypasses so one-off filters never
        # pay the window for an inevitable singleton lane
        self._sig_ttl = max(1.0, self.window_s * 100.0)
        self._recent_sigs: dict[str, float] = {}
        # cheap python-side counters (bench/tests read these without a
        # prometheus round trip; the histograms carry the same data)
        self._dispatches = 0
        self._dispatched_requests = 0
        self._dispatched_rows = 0
        self._bypass: dict[str, int] = {}
        # blocking per-lane work (finalize+hydration, sync filtered search)
        # runs on this pool; the flush thread only admits/enqueues, capped
        # at `pipeline_depth` lanes in flight. While every slot is busy the
        # flusher BLOCKS — that stall is the backpressure that lets the
        # next window's lanes accumulate to full width. Measured on the
        # CPU-JAX acceptance workload (64 clients, n=50k): depth 1 = 4.7x
        # the uncoalesced QPS at ~30 requests/dispatch; depth 2 = 2.7x at
        # ~13 (two in-flight scans contend for the same host cores);
        # unbounded = 1.3x at ~5 (no backpressure, every window flushes
        # thin). Depth 1 is therefore the default; a real TPU backend,
        # where finalize/hydration is host work that overlaps device
        # compute, is the case for raising it to 2.
        self._inflight = threading.Semaphore(max(int(pipeline_depth), 1))
        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=max(int(pipeline_depth), 1) + 2,
            thread_name_prefix="coalescer-dispatch")
        self._thread = threading.Thread(
            target=self._run, name="query-coalescer", daemon=True)
        self._thread.start()

    # -- admission -----------------------------------------------------------

    def submit(self, shard, vectors: np.ndarray, k: int, flt=None,
               include_vector: bool = False):
        """Queue a request's rows for a coalesced dispatch.

        -> a blocking callable() -> list[list[SearchResult]] (one list per
        row), or None when the request must bypass to the direct path
        (reason counted)."""
        q = np.asarray(vectors, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if q.shape[0] > self.max_request_rows:
            self.record_bypass("oversize")
            return None
        sig = filter_signature(flt)
        if sig is None:
            self.record_bypass("unique_allow_list")
            return None
        # dim is part of the key: a wrong-dim request must land in its own
        # lane and fail ALONE, not poison the concatenate of its lane-mates
        key = (id(shard), int(k), getattr(shard.vector_index, "metric", ""),
               sig, bool(include_vector), int(q.shape[1]))
        cold = False
        with self._cv:
            closed = self._closed
            if not closed and sig:
                # filtered request: queue only when this signature was seen
                # recently (a lane-mate is plausible); cold signatures go
                # direct — a one-off per-tenant filter must not pay the
                # window for a singleton lane
                now = time.monotonic()
                last = self._recent_sigs.get(sig)
                self._recent_sigs[sig] = now
                if len(self._recent_sigs) > 1024:
                    pruned = {s: t for s, t in self._recent_sigs.items()
                              if now - t <= self._sig_ttl}
                    # all-hot overflow (>1024 live signatures inside the
                    # TTL): pruning can't shrink, and rebuilding O(n) under
                    # the admission lock on EVERY submit would serialize the
                    # fast path — reset instead; hot filters re-warm with
                    # one direct request each, amortized O(1) per overflow
                    self._recent_sigs = (pruned if len(pruned) <= 896
                                         else {sig: now})
                cold = last is None or now - last > self._sig_ttl
            if not closed and not cold:
                # wake the flusher only when the picture it sleeps on
                # changes: a new lane (new earliest deadline) or a lane
                # popped to _full (new due work). Appending to an existing
                # lane changes neither — notifying there would wake/rescan
                # the flusher once per REQUEST on the hot path instead of
                # once per window.
                wake = False
                lane = self._lanes.get(key)
                if lane is not None and lane.rows + q.shape[0] > self.max_batch:
                    # this request would overflow the bucket: flush the lane
                    # as-is and start fresh — a dispatch must never exceed
                    # max_batch, or it pads to the NEXT bucket and compiles
                    # a shape the direct path never uses
                    del self._lanes[key]
                    self._full.append(lane)
                    lane = None
                    wake = True
                if lane is None:
                    lane = _Lane(key, shard, flt, int(k),
                                 bool(include_vector),
                                 time.monotonic() + self.window_s)
                    self._lanes[key] = lane
                    wake = True
                w = _Waiter(q)
                lane.items.append(w)
                lane.rows += q.shape[0]
                self._queued_rows += q.shape[0]
                if lane.rows >= self.max_batch:
                    # bucket full: pop now so later arrivals start fresh
                    del self._lanes[key]
                    self._full.append(lane)
                    wake = True
                self._set_depth_gauge()
                if wake:
                    self._cv.notify()
        if closed:
            # outside the lock: record_bypass takes it again
            self.record_bypass("shutdown")
            return None
        if cold:
            self.record_bypass("cold_filter")
            return None
        return w.wait

    def record_bypass(self, reason: str) -> None:
        """Count a request that took the direct path instead of the queue."""
        # always called on the bypassing request's own serving thread, so
        # the reason lands on ITS trace (the direct dispatch that follows
        # records its own spans there too)
        tracing.annotate_current("coalescer_bypass", reason)
        with self._lock:
            self._bypass[reason] = self._bypass.get(reason, 0) + 1
        m = self.metrics
        if m is not None:
            try:
                m.coalescer_bypass.labels(reason).inc()
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    # -- flush loop ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            due: list[_Lane] = []
            with self._cv:
                while not self._closed:
                    now = time.monotonic()
                    due = self._full
                    self._full = []
                    expired = [k for k, ln in self._lanes.items()
                               if ln.deadline <= now]
                    for k in expired:
                        due.append(self._lanes.pop(k))
                    if due:
                        break
                    timeout = None
                    if self._lanes:
                        timeout = max(
                            min(ln.deadline for ln in self._lanes.values())
                            - now, 0.0)
                    self._cv.wait(timeout)
                if self._closed:
                    due.extend(self._full)
                    due.extend(self._lanes.values())
                    self._full = []
                    self._lanes.clear()
                for ln in due:
                    self._queued_rows -= ln.rows
                self._set_depth_gauge()
                closed = self._closed
            if closed:
                err = CoalescerShutdownError(
                    "query coalescer shut down with requests queued")
                for ln in due:
                    self._fail_lane(ln, err)
                return
            try:
                self._flush(due)
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # anything _flush itself failed to contain: no waiter may
                # hang, and the next window must still be served
                for ln in due:
                    self._fail_lane(ln, e)

    def _flush(self, due: list[_Lane]) -> None:
        """Depth-2 pipelined flush: each lane takes an in-flight slot (the
        flusher BLOCKS when both are busy — that stall is what lets the
        next window's lanes fill to full width), has its device dispatch
        enqueued here in order, and finalizes on the dispatch pool so
        hydration overlaps the next lane's device compute."""
        for i, ln in enumerate(due):
            while not self._inflight.acquire(timeout=0.1):
                if self._closed:
                    # a wedged in-flight dispatch must not strand the rest
                    err = CoalescerShutdownError(
                        "query coalescer shut down with requests queued")
                    for rest in due[i:]:
                        self._fail_lane(rest, err)
                    return
            done = None
            try:
                vidx = ln.shard.vector_index
                if not hasattr(vidx, "search_by_vectors_async"):
                    # indexes without true async dispatch (hnsw, noop,
                    # mesh): the whole blocking search runs on the pool —
                    # object_vector_search_async's sync fallback would
                    # otherwise execute it inline in THIS thread and
                    # head-of-line-block every other lane
                    self._dispatch_pool.submit(self._dispatch_sync, ln)
                    continue
                if ln.flt is not None:
                    # filtered lanes: the allowList resolution (an
                    # inverted-index scan on a cache miss) must not
                    # head-of-line block the flusher either — resolve,
                    # enqueue AND finalize on the pool. The search itself
                    # still rides the lock-free two-phase snapshot path
                    # inside object_vector_search_async (or the sync
                    # fallback for index types without filtered async).
                    self._dispatch_pool.submit(self._dispatch_filtered, ln)
                    continue
                q = (ln.items[0].vectors if len(ln.items) == 1
                     else np.concatenate([w.vectors for w in ln.items]))
                self._observe_wait(ln)  # queue wait ends as dispatch starts
                rec = self._trace_record(ln)
                done = ln.shard.object_vector_search_async(
                    q, ln.k, include_vector=ln.include_vector)
                self._dispatch_pool.submit(self._finalize_async, ln, done,
                                           rec)
            except Exception as e:  # noqa: BLE001 — propagate to all waiters
                # covers pool.submit after shutdown too: no waiter may hang
                self._inflight.release()
                self._fail_lane(ln, e)
                if done is not None:
                    # the dispatch WAS enqueued (submit itself failed):
                    # settle it so the index's in-flight gauge and any
                    # device work don't leak; results are discarded
                    try:
                        done()
                    except Exception:  # noqa: BLE001 — already failed lane
                        pass

    def _dispatch_filtered(self, lane: _Lane) -> None:
        """Pool-side twin of the flusher's async enqueue for FILTERED
        lanes: allowList build + two-phase enqueue + finalize, all off the
        flusher thread. Enqueue ordering across filtered lanes is pool
        order (exactly the pre-snapshot behavior); the win vs the old
        sync path is that the search holds no index lock."""
        try:
            q = (lane.items[0].vectors if len(lane.items) == 1
                 else np.concatenate([w.vectors for w in lane.items]))
            self._observe_wait(lane)
            rec = self._trace_record(lane)
            # record pushed around the enqueue too: an index without
            # filtered async runs the WHOLE sync search eagerly inside
            # this call, and its phases must land on the lane's record
            tok = tracing.push_dispatch(rec)
            try:
                done = lane.shard.object_vector_search_async(
                    q, lane.k, include_vector=lane.include_vector,
                    flt=lane.flt)
            finally:
                tracing.pop_dispatch(tok)
        except Exception as e:  # noqa: BLE001 — propagate to all waiters
            self._fail_lane(lane, e)
            self._inflight.release()
            return
        self._finalize_async(lane, done, rec)

    def _dispatch_sync(self, lane: _Lane) -> None:
        try:
            q = np.concatenate([w.vectors for w in lane.items]) \
                if len(lane.items) > 1 else lane.items[0].vectors
            self._observe_wait(lane)
            rec = self._trace_record(lane)
            tok = tracing.push_dispatch(rec)
            try:
                # the shard's phase recording lands in `rec` via the
                # dispatch contextvar set for THIS pool thread
                res = lane.shard.object_vector_search(
                    q, lane.k, lane.flt, None, lane.include_vector)
            finally:
                tracing.pop_dispatch(tok)
            if rec is not None:
                # attribution completes BEFORE waiters wake: a request
                # thread reading its own trace after wait() must see its
                # dispatch span already attached
                rec.finish()
            self._resolve_lane(lane, res)
        except Exception as e:  # noqa: BLE001 — propagate to all waiters
            self._fail_lane(lane, e)
        finally:
            self._inflight.release()

    def _finalize_async(self, lane: _Lane, done, rec=None) -> None:
        try:
            tok = tracing.push_dispatch(rec)
            try:
                res = done()
            finally:
                tracing.pop_dispatch(tok)
            if rec is not None:
                rec.finish()  # before waiters wake — see _dispatch_sync
            self._resolve_lane(lane, res)
        except Exception as e:  # noqa: BLE001 — propagate to all waiters
            self._fail_lane(lane, e)
        finally:
            self._inflight.release()

    def _trace_record(self, lane: _Lane):
        """DispatchRecord for this lane's traced riders (span + rows +
        queue wait per rider), or None when tracing is off or no rider was
        sampled. Unowned: finish() runs here in the coalescer, after the
        device work and before the waiters wake."""
        if tracing.get_tracer() is None:
            return None
        now = time.monotonic()
        riders = [(w.trace_span, int(w.vectors.shape[0]),
                   (now - w.enqueued_at) * 1000.0)
                  for w in lane.items if w.trace_span is not None]
        if not riders:
            return None
        return tracing.DispatchRecord(
            riders, owned=False, actual_rows=lane.rows, coalesced=True,
            lane_requests=len(lane.items), k=lane.k)

    def _observe_wait(self, lane: _Lane) -> None:
        """Admission-queue wait per request, observed AT dispatch start —
        observing at resolution would fold the search+hydration latency in
        and make the histogram useless for tuning the window."""
        m = self.metrics
        if m is not None:
            try:
                now = time.monotonic()
                for w in lane.items:
                    m.coalescer_wait.observe((now - w.enqueued_at) * 1000.0)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    def _resolve_lane(self, lane: _Lane, res) -> None:
        """Scatter [rows] result lists back to the lane's waiters. No k
        trimming is needed: k is part of the lane key (see submit), so every
        waiter here asked for exactly the k the dispatch ran at."""
        pos = 0
        try:
            for w in lane.items:
                r = w.vectors.shape[0]
                w.result = res[pos: pos + r]
                pos += r
                w.event.set()
        finally:
            # a scatter bug must not leave later waiters hanging
            for w in lane.items:
                if not w.event.is_set():
                    w.error = RuntimeError(
                        "coalescer failed to scatter batch results")
                    w.event.set()
        with self._lock:
            self._dispatches += 1
            self._dispatched_requests += len(lane.items)
            self._dispatched_rows += lane.rows
        m = self.metrics
        if m is not None:
            try:
                m.coalescer_batch_requests.observe(len(lane.items))
                m.coalescer_batch_rows.observe(lane.rows)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    @staticmethod
    def _fail_lane(lane: _Lane, err: BaseException) -> None:
        # a failed lane means every waiter silently re-runs on the direct
        # path (coalesce window + dead dispatch + duplicate search): make
        # that degradation COUNTABLE, not invisible — the JGL004 rule
        if not isinstance(err, CoalescerShutdownError):
            record_device_fallback("serving.coalescer", "lane_dispatch_failed",
                                   err)
        key = ("coalescer_shutdown"
               if isinstance(err, CoalescerShutdownError)
               else "coalescer_error")
        for w in lane.items:
            # error/shutdown paths close out the trace side too: the rider
            # trace gets the failure reason (annotation, not an open span —
            # nothing to leak), BEFORE the waiter wakes and possibly
            # re-runs direct
            tracing.annotate_span(w.trace_span, key,
                                  f"{type(err).__name__}: {err}")
            w.error = err
            w.event.set()

    def _set_depth_gauge(self) -> None:
        m = self.metrics
        if m is not None:
            try:
                m.coalescer_queue_depth.set(self._queued_rows)
            except Exception:  # noqa: BLE001
                pass

    # -- introspection / lifecycle -------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            d = self._dispatches
            return {
                "dispatches": d,
                "requests": self._dispatched_requests,
                "rows": self._dispatched_rows,
                "mean_requests_per_dispatch":
                    (self._dispatched_requests / d) if d else 0.0,
                "mean_rows_per_dispatch":
                    (self._dispatched_rows / d) if d else 0.0,
                "bypass": dict(self._bypass),
            }

    def shutdown(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)
        # in-flight dispatch tasks run to completion (each wakes its own
        # waiters, success or failure); nothing new can be submitted
        self._dispatch_pool.shutdown(wait=False)
