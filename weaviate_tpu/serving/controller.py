"""Self-tuning degradation control plane: the observability loop, closed.

PRs 7-10 built six observability layers that measure every dispatch —
tracing/perf attribution, the shadow recall auditor, the memory ledger,
SLO burn rates, the incident journal — but nothing *acts* on them: the
serving plane degrades on static knobs while the sensors watch (ROADMAP
item 4). This module hosts the four controllers that turn those sensors
into actuators, each a clamped sense -> decide -> actuate -> journal
loop on one supervised tick thread:

**Brownout** (``SloEngine`` fast/slow burn -> a staged degradation
ladder): instead of alerting and cliff-edge shedding, rising burn walks
serving DOWN a ladder — stage 1 tightens admission margins (the
deadline-unreachable estimate is multiplied, shedding earlier), stage 2
shrinks per-tenant budgets, scales Retry-After hints up, and halves
tenant rate quotas, stage 3 pauses optional work (shadow-audit and
trace sampling). Recovery walks back DOWN one stage at a time only
after ``hold_ticks`` consecutive clean ticks — hysteresis, so a burn
oscillating around the threshold cannot flap the ladder.

**Recall-guarded candidate budget** (the PR-8 recall EWMA -> the PQ
fast-scan ``rescore_r`` cap in index/tpu.py): while every audited
tier's recall EWMA holds ``recall_slack`` above the configured floor,
the cap steps DOWN one jit bucket (speed bought with *measured* slack —
AQR-HNSW parameterizes this budget statically; here it is a measured
quantity); the moment the EWMA nears the floor it steps back UP
immediately (safety is asymmetric: cuts are held, restores are not).
Cap values come only from ``R_BUCKETS`` so jit shapes stay cached, and
the knob is inert without a live auditor — no signal, no actuation.

**Coalescer lanes** (the PR-7 duty-cycle / queue-wait split -> the
flush window and pipeline depth): queue-dominated (requests wait while
the device is busy) widens the window so dispatches fill; a starved
device with waiting work deepens the pipeline; a quiet system walks
both back to their configured defaults.

**Tenant rate quotas** (``TENANT_RATE_QPS`` x DRR weights -> token
buckets): the open PR-6 fairness follow-up — the row budget bounds
occupancy, this bounds request RATE. Enforcement rides coalescer
admission (``take_rate_token``), shedding ``tenant_rate`` with
Retry-After = time-to-next-token; brownout stage 2 scales the refill.

Fail-static safety — the control plane may never degrade serving:

- every knob is CLAMPED in ``_set_knob`` (the one actuate helper;
  graftlint JGL014 statically pins that nothing outside this module
  writes a controller-owned knob) and journaled as a
  ``controller_actuation`` ops event;
- knob values carry a LEASE: readers (coalescer admission, the index's
  ``_rescore_r``) fall back to the configured default once a value goes
  ``lease_s`` stale, so a STALLED tick thread reverts the module-read
  knobs in bounded time without any watchdog;
- a DYING tick thread (``serving.controller.tick`` fault point, action
  ``die``) reverts every knob — including the object-state ones
  (pipeline depth, paused sampling) — in its ``finally`` and journals a
  ``controller_revert`` before the thread exits;
- per-controller config gates plus ``CONTROL_PLANE_ENABLED`` kill the
  whole plane: disabled, the module global stays None and every reader
  on the serving path is a one-comparison no-op that constructs nothing
  (spy-pinned in tests/test_controller.py).

Exposure: ``GET /debug/controllers`` (same authorizer as the other
debug planes), ``weaviate_controller_*`` gauges/counters, a
``controllers`` section in every flight-recorder bundle, and the
``--controllers on|off|both`` bench rows. See docs/control.md.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Optional

from weaviate_tpu.config import ControllerConfig
from weaviate_tpu.config.config import (IVF_TOP_P_BUCKETS,
                                        PQ4_FUNNEL_C_BUCKETS,
                                        PQ4_FUNNEL_RESCORE_BUCKETS,
                                        RESCORE_R_BUCKETS)
from weaviate_tpu.monitoring import incidents
from weaviate_tpu.testing import faults, sanitizers

_LOG = logging.getLogger(__name__)

# the PQ fast-scan candidate-budget cap may take ONLY these values:
# rescore_r is a jit static argument, so an unconstrained cap would mint
# one compiled kernel per distinct value — bucketed, the cache stays as
# bounded as the index's own query-padding buckets. The top bucket (128)
# is index/tpu.py's built-in maximum, i.e. "controller inactive".
# The table itself lives in config (ONE source of truth): index/tpu.py's
# static-arg snapping imports the same tuple, so a controller cut can
# never mint a jit shape the index wouldn't also compile.
R_BUCKETS = RESCORE_R_BUCKETS

# the IVF probe-count cap's bucket ladder (config.IVF_TOP_P_BUCKETS —
# the same one-source-of-truth discipline as R_BUCKETS: index/tpu.py
# snaps every effective top_p to this table, so a controller cut can
# never mint a jit shape the static path wouldn't also compile). The
# top bucket means "controller inactive": the index's own configured
# probe count applies unchanged.
P_BUCKETS = IVF_TOP_P_BUCKETS

# the 4-bit funnel's two stage budgets (config.PQ4_FUNNEL_*_BUCKETS —
# the same one-source-of-truth discipline again: index/tpu.py
# _funnel_budgets snaps both jit statics to these tables). Top bucket =
# "controller inactive": the funnel's built-in maxima apply.
FC_BUCKETS = PQ4_FUNNEL_C_BUCKETS
FR_BUCKETS = PQ4_FUNNEL_RESCORE_BUCKETS

# brownout ladder stages (stage 0 = normal serving)
STAGE_NORMAL = 0
STAGE_MARGIN = 1      # tighten admission margins (shed earlier)
STAGE_BUDGET = 2      # shrink tenant budgets, scale Retry-After + rates
STAGE_SHED_OPTIONAL = 3  # pause audit/trace sampling

# knob names: a FIXED set — these are also the bounded label values of
# weaviate_controller_knob{knob}. Values live in the plane's leased
# store; object-state actuations (pipeline depth, paused sampling) are
# reverted by the run loop's finally instead of a lease.
KNOB_WINDOW_S = "coalescer_window_s"
KNOB_MARGIN = "admission_margin"
KNOB_CAP_SCALE = "tenant_cap_scale"
KNOB_RETRY_SCALE = "retry_after_scale"
KNOB_RESCORE_CAP = "rescore_r_cap"
KNOB_RATE_SCALE = "rate_scale"
KNOB_IVF_TOP_P = "ivf_top_p"
KNOB_FUNNEL_C = "funnel_c_cap"
KNOB_FUNNEL_RESCORE = "funnel_rescore_cap"
KNOB_NAMES = (KNOB_WINDOW_S, KNOB_MARGIN, KNOB_CAP_SCALE,
              KNOB_RETRY_SCALE, KNOB_RESCORE_CAP, KNOB_RATE_SCALE,
              KNOB_IVF_TOP_P, KNOB_FUNNEL_C, KNOB_FUNNEL_RESCORE)


def _snap_bucket(value: float, buckets=R_BUCKETS) -> int:
    """Largest bucket <= value (floor snap; below the smallest bucket ->
    the smallest — the clamp floor)."""
    best = buckets[0]
    for b in buckets:
        if b <= value:
            best = b
    return int(best)


class _TokenBuckets:
    """Per-tenant token buckets metering request RATE at coalescer
    admission. Refill = TENANT_RATE_QPS x the tenant's DRR weight x the
    brownout ``rate_scale``; burst = rate x burst_s (>= 1 token, so a
    quota can never deadlock a tenant outright). ``take`` -> None when a
    token was spent, else seconds until the next token accrues — the
    Retry-After hint, proportional to how far over rate the tenant is."""

    _MAX_TENANTS = 1024

    def __init__(self, rate_qps: float, burst_s: float,
                 weights: Optional[dict] = None):
        self.rate_qps = max(float(rate_qps), 0.0)
        self.burst_s = max(float(burst_s), 0.001)
        self.weights = dict(weights or {})
        self._lock = threading.Lock()
        # tenant -> [tokens, last_refill_monotonic]
        self._buckets: dict[str, list] = {}
        self.shed = 0
        self.taken = 0

    def _rate_for(self, tenant: str, scale: float) -> float:
        w = self.weights.get(tenant, 1.0)
        return self.rate_qps * max(float(w), 0.001) * scale

    def take(self, tenant: str, scale: float = 1.0) -> Optional[float]:
        rate = self._rate_for(tenant, scale)
        if rate <= 0.0:
            return None  # quota off (or scaled to nothing — never block)
        now = time.monotonic()
        burst = max(rate * self.burst_s, 1.0)
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = [burst, now]
                if len(self._buckets) > self._MAX_TENANTS:
                    # a storm of invented tenant ids must not grow this
                    # dict without bound: drop the stalest entries (their
                    # buckets re-warm FULL on the next request — erring
                    # toward admission, never toward a phantom quota)
                    stale = sorted(self._buckets, key=lambda t:
                                   self._buckets[t][1])
                    for t in stale[: self._MAX_TENANTS // 4]:
                        if t != tenant:
                            del self._buckets[t]
            tokens = min(b[0] + (now - b[1]) * rate, burst)
            b[1] = now
            if tokens >= 1.0:
                b[0] = tokens - 1.0
                self.taken += 1
                return None
            b[0] = tokens
            self.shed += 1
            return max((1.0 - tokens) / rate, 0.001)

    def prune(self, idle_s: float = 60.0) -> None:
        now = time.monotonic()
        with self._lock:
            dead = [t for t, b in self._buckets.items()
                    if now - b[1] > idle_s]
            for t in dead:
                del self._buckets[t]

    def stats(self) -> dict:
        with self._lock:
            return {"rate_qps": self.rate_qps, "burst_s": self.burst_s,
                    "tenants": len(self._buckets),
                    "taken": self.taken, "shed": self.shed}


class ControlPlane:
    """The supervised control plane: four clamped controllers on one
    exception-guarded tick thread. Constructed ONLY when
    CONTROL_PLANE_ENABLED is set (App wiring) — the disabled serving
    path reads module globals that stay None."""

    def __init__(self, config=None, coalescer=None, metrics=None,
                 tenant_weights: Optional[dict] = None, start: bool = True,
                 **overrides):
        cfg = _ControllerSettings(config, overrides)
        self.cfg = cfg
        self.coalescer = coalescer
        self.metrics = metrics
        self.tick_s = cfg.tick_s
        # module-read knobs go stale (revert to defaults at the reader)
        # after this long without a tick refresh: a stalled thread
        # fail-statics in bounded time without any watchdog thread
        self.lease_s = max(self.tick_s * 8.0, 2.0)
        self._lock = sanitizers.register_lock(
            threading.Lock(), "serving.controller")
        # knob name -> (value, stamp). Read lock-free on the serving path
        # (tuple replacement is atomic; a torn read is impossible);
        # written only by _set_knob / the lease refresh under _lock.
        self._knobs: dict[str, tuple] = {}
        # configured defaults, captured once: what revert restores
        self._defaults = {
            KNOB_WINDOW_S: (coalescer.window_s if coalescer is not None
                            else 0.0015),
            KNOB_MARGIN: 1.0,
            KNOB_CAP_SCALE: 1.0,
            KNOB_RETRY_SCALE: 1.0,
            KNOB_RESCORE_CAP: float(R_BUCKETS[-1]),
            KNOB_RATE_SCALE: 1.0,
            KNOB_IVF_TOP_P: float(P_BUCKETS[-1]),
            KNOB_FUNNEL_C: float(FC_BUCKETS[-1]),
            KNOB_FUNNEL_RESCORE: float(FR_BUCKETS[-1]),
        }
        self._depth_default = (coalescer._depth if coalescer is not None
                               else 1)
        # clamp ranges — the actuate helper enforces these on EVERY write
        w_def = self._defaults[KNOB_WINDOW_S]
        self._clamps = {
            KNOB_WINDOW_S: (min(cfg.window_min_ms / 1000.0, w_def),
                            max(cfg.window_max_ms / 1000.0, w_def)),
            KNOB_MARGIN: (1.0, 4.0),
            KNOB_CAP_SCALE: (0.25, 1.0),
            KNOB_RETRY_SCALE: (1.0, 8.0),
            KNOB_RESCORE_CAP: (float(R_BUCKETS[0]), float(R_BUCKETS[-1])),
            KNOB_RATE_SCALE: (0.25, 1.0),
            KNOB_IVF_TOP_P: (float(P_BUCKETS[0]), float(P_BUCKETS[-1])),
            KNOB_FUNNEL_C: (float(FC_BUCKETS[0]), float(FC_BUCKETS[-1])),
            KNOB_FUNNEL_RESCORE: (float(FR_BUCKETS[0]),
                                  float(FR_BUCKETS[-1])),
        }
        # token buckets (controller 4); rate 0 = quota off
        self.rate_buckets = _TokenBuckets(
            cfg.tenant_rate_qps, cfg.tenant_rate_burst_s, tenant_weights)
        # brownout ladder state
        self.brownout_stage = STAGE_NORMAL
        self._stage_clean_ticks = 0
        self._sampling_paused = False
        self._saved_audit = None   # (auditor, rate) while paused
        self._saved_trace = None   # (tracer, rate) while paused
        # recall-budget state: index into R_BUCKETS (top = inactive)
        self._r_idx = len(R_BUCKETS) - 1
        self._r_hold = 0
        # the second recall-guarded budget (ROADMAP item-4 follow-up,
        # landed with the IVF plane): index into P_BUCKETS for the IVF
        # probe-count cap (top = inactive)
        self._p_idx = len(P_BUCKETS) - 1
        self._p_hold = 0
        # the third and fourth recall-guarded budgets (the 4-bit funnel's
        # stage-C and stage-c depths, index/tpu.py _funnel_budgets):
        # indices into FC_/FR_BUCKETS (top = inactive)
        self._fc_idx = len(FC_BUCKETS) - 1
        self._fc_hold = 0
        self._fr_idx = len(FR_BUCKETS) - 1
        self._fr_hold = 0
        # lane-controller state: hysteresis counts CONSECUTIVE qualifying
        # ticks in ONE direction — the paired _dir resets the counter when
        # the qualifying branch flips, so mixed evidence never actuates
        self._win_hold = 0
        self._win_dir = 0
        self._depth_hold = 0
        self._depth_dir = 0
        self._depth = self._depth_default
        # bookkeeping
        self._ticks = 0
        self._actuations: dict[str, int] = {}
        self._recent: deque = deque(maxlen=32)  # last actuations, for /debug
        self._reverted = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._run, name="serving-controller", daemon=True)
            self._thread.start()

    # -- the leased knob store (serving-path reads are lock-free) -------------

    def _read(self, name: str, default):
        entry = self._knobs.get(name)
        if entry is None:
            return default
        value, stamp = entry
        if time.monotonic() - stamp > self.lease_s:
            # stale lease: the tick thread stalled or died without its
            # finally running — fail static at the reader
            return default
        return value

    def _set_knob(self, name: str, value: float, controller: str,
                  reason: str = "") -> float:
        """THE clamped actuate helper (graftlint JGL014 pins that knob
        writes happen nowhere else): clamp to the knob's configured
        range (bucket-snapped for the jit-static rescore cap), store
        under a fresh lease, journal the change, count it. -> the value
        actually applied."""
        lo, hi = self._clamps[name]
        v = min(max(float(value), lo), hi)
        if name == KNOB_RESCORE_CAP:
            v = float(_snap_bucket(v))
        elif name == KNOB_IVF_TOP_P:
            v = float(_snap_bucket(v, P_BUCKETS))
        elif name == KNOB_FUNNEL_C:
            v = float(_snap_bucket(v, FC_BUCKETS))
        elif name == KNOB_FUNNEL_RESCORE:
            v = float(_snap_bucket(v, FR_BUCKETS))
        prev = self._read(name, self._defaults[name])
        now = time.monotonic()
        with self._lock:
            if v == self._defaults[name]:
                self._knobs.pop(name, None)  # default = absent = fast read
            else:
                self._knobs[name] = (v, now)
        if v != prev:
            self._journal_actuation(name, prev, v, controller, reason)
        return v

    def _journal_actuation(self, knob: str, prev, value, controller: str,
                           reason: str) -> None:
        """One actuation record, everywhere it surfaces: the /debug deque,
        the ops journal, the per-controller counter + metric. Both actuate
        paths (_set_knob and the object-state _actuate_depth) feed this,
        so the record shape cannot drift between them. The deque/counter
        writes take the lock: summary() snapshots them from debug/bundle
        threads while the tick thread actuates."""
        with self._lock:
            self._reverted = False  # an actuation re-arms revert_all
            self._actuations[controller] = \
                self._actuations.get(controller, 0) + 1
            self._recent.append({"ts": round(time.time(), 3), "knob": knob,
                                 "from": prev, "to": value,
                                 "controller": controller, "reason": reason})
        incidents.emit("controller_actuation", scope=knob,
                       controller=controller, prev=prev, value=value,
                       reason=reason)
        m = self.metrics
        if m is not None:
            try:
                m.controller_actuations.labels(controller).inc()
            except Exception:  # noqa: BLE001 — metrics must not break the tick
                pass

    def _refresh_leases(self) -> None:
        """Re-stamp every live knob (called each tick): an ACTIVE thread
        keeps its actuations fresh; a stalled/dead one lets them lapse."""
        now = time.monotonic()
        with self._lock:
            for name, (v, _) in list(self._knobs.items()):
                self._knobs[name] = (v, now)

    # -- the supervised tick thread -------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.wait(self.tick_s):
                # fault point: `die` (a BaseException) escapes the tick
                # guard below and kills this thread the way a real thread
                # death would — the finally then proves fail-static
                faults.fire("serving.controller.tick")
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the control loop must survive
                    _LOG.warning("controller tick failed", exc_info=True)
        finally:
            # dying WITHOUT a clean shutdown: revert every actuated knob
            # so a dead controller can never leave serving degraded. On a
            # clean stop this performs the shutdown revert (idempotent —
            # shutdown()'s own call then no-ops), and a STRAGGLING tick
            # that re-actuated after a timed-out join re-armed the flag,
            # so its exit path reverts what it re-applied.
            self.revert_all("controller thread died"
                            if not self._stop.is_set()
                            else "control plane shutdown")

    def tick(self) -> None:
        """One sense -> decide -> actuate -> journal pass (public so
        tests drive it deterministically with start=False)."""
        self._ticks += 1
        self._refresh_leases()
        if self.cfg.brownout_enabled:
            self._tick_brownout()
        if self.cfg.budget_enabled:
            self._tick_budget()
        if self.cfg.lanes_enabled:
            self._tick_lanes()
        self.rate_buckets.prune()
        self._publish_gauges()

    # -- controller 1: burn-rate brownout -------------------------------------

    def _sense_burn(self) -> tuple:
        """(max fast burn, max slow burn) across availability SLOs, or
        (None, None) when the SLO engine is off/cold."""
        eng = incidents.get_engine()
        if eng is None:
            return None, None
        try:
            return eng.burn_rates()
        except Exception:  # noqa: BLE001 — a broken sensor reads as "no signal"
            return None, None

    def _tick_brownout(self) -> None:
        fast, slow = self._sense_burn()
        cfg = self.cfg
        burning_fast = fast is not None and fast >= cfg.fast_burn_threshold
        burning_slow = slow is not None and slow >= cfg.slow_burn_threshold
        if burning_fast:
            self._stage_clean_ticks = 0
            if self.brownout_stage < STAGE_SHED_OPTIONAL:
                self._enter_stage(self.brownout_stage + 1, fast, slow)
        elif burning_slow:
            # a smolder justifies stage 1 — no more: it lights stage 1
            # from normal serving, and it lets the AGGRESSIVE stages a
            # past cliff ratcheted up decay back to 1 on the same
            # hysteresis clock. Without the decay, a 5-minute storm's
            # residue in the 1 h window would pin stage 3 (sampling
            # paused, caps halved, budget frozen) for the better part of
            # an hour after the fast burn cleared.
            if self.brownout_stage == STAGE_NORMAL:
                self._stage_clean_ticks = 0
                self._enter_stage(STAGE_MARGIN, fast, slow)
            elif self.brownout_stage > STAGE_MARGIN:
                self._stage_clean_ticks += 1
                if self._stage_clean_ticks >= cfg.hold_ticks:
                    self._stage_clean_ticks = 0
                    self._enter_stage(self.brownout_stage - 1, fast, slow)
            else:
                self._stage_clean_ticks = 0  # at stage 1: hold
        else:
            self._stage_clean_ticks += 1
            if self.brownout_stage > STAGE_NORMAL \
                    and self._stage_clean_ticks >= cfg.hold_ticks:
                # hysteresis: one stage down per hold_ticks clean ticks —
                # a square-wave burn cannot flap the ladder
                self._stage_clean_ticks = 0
                self._enter_stage(self.brownout_stage - 1, fast, slow)

    def _enter_stage(self, stage: int, fast, slow) -> None:
        prev, self.brownout_stage = self.brownout_stage, stage
        cfg = self.cfg
        self._set_knob(KNOB_MARGIN,
                       cfg.brownout_margin if stage >= STAGE_MARGIN else 1.0,
                       "brownout", reason=f"stage {stage}")
        deep = stage >= STAGE_BUDGET
        self._set_knob(KNOB_CAP_SCALE,
                       cfg.brownout_cap_scale if deep else 1.0,
                       "brownout", reason=f"stage {stage}")
        self._set_knob(KNOB_RETRY_SCALE,
                       cfg.brownout_retry_scale if deep else 1.0,
                       "brownout", reason=f"stage {stage}")
        self._set_knob(KNOB_RATE_SCALE,
                       cfg.brownout_rate_scale if deep else 1.0,
                       "brownout", reason=f"stage {stage}")
        if stage >= STAGE_SHED_OPTIONAL:
            self._pause_sampling()
        else:
            self._resume_sampling()
        incidents.emit("controller_brownout", scope="serving",
                       stage=stage, prev=prev,
                       fast_burn=round(fast, 2) if fast is not None else None,
                       slow_burn=round(slow, 2) if slow is not None else None)
        _LOG.warning(
            "brownout ladder %s: stage %d -> %d (fast burn %s, slow burn "
            "%s) — admission margin x%.2g, tenant cap x%.2g, Retry-After "
            "x%.2g, sampling %s",
            "escalated" if stage > prev else "recovered",
            prev, stage,
            f"{fast:.2f}" if fast is not None else "n/a",
            f"{slow:.2f}" if slow is not None else "n/a",
            self._read(KNOB_MARGIN, 1.0), self._read(KNOB_CAP_SCALE, 1.0),
            self._read(KNOB_RETRY_SCALE, 1.0),
            "paused" if stage >= STAGE_SHED_OPTIONAL else "on")
        m = self.metrics
        if m is not None:
            try:
                m.controller_brownout_stage.set(stage)
            except Exception:  # noqa: BLE001 — metrics must not break the tick
                pass

    def _pause_sampling(self) -> None:
        """Stage 3: optional work yields to serving — shadow audits and
        trace sampling pause (their workers stay up; the sample gates go
        to zero). The pre-pause rates are saved for the resume/revert."""
        if self._sampling_paused:
            return
        from weaviate_tpu.monitoring import quality, tracing

        a = quality.get_auditor()
        if a is not None:
            self._saved_audit = (a, a.sample_rate)
            a.set_sample_rate(0.0)
        t = tracing.get_tracer()
        if t is not None:
            self._saved_trace = (t, t.sample_rate)
            t.set_sample_rate(0.0)
        self._sampling_paused = True

    def _resume_sampling(self) -> None:
        if not self._sampling_paused:
            return
        if self._saved_audit is not None:
            a, rate = self._saved_audit
            try:
                a.set_sample_rate(rate)
            except Exception:  # noqa: BLE001 — a torn-down auditor is fine
                pass
            self._saved_audit = None
        if self._saved_trace is not None:
            t, rate = self._saved_trace
            try:
                t.set_sample_rate(rate)
            except Exception:  # noqa: BLE001 — a torn-down tracer is fine
                pass
            self._saved_trace = None
        self._sampling_paused = False

    # -- controller 2: recall-guarded candidate budget ------------------------

    def _sense_recall(self) -> Optional[float]:
        """Min recall EWMA across audited tiers with enough samples, or
        None when the auditor is off/cold — no signal, no actuation."""
        from weaviate_tpu.monitoring import quality

        a = quality.get_auditor()
        if a is None:
            return None
        # a zeroed sample gate (brownout stage 3 paused it, or the operator
        # configured it off) means the EWMA is FROZEN, not fresh: the
        # QualityWindow never decays, so tier_ewmas() would keep vouching
        # with pre-pause numbers while actual recall is unmeasured
        if getattr(a, "sample_rate", 0.0) <= 0.0:
            return None
        try:
            ewmas = a.tier_ewmas()
        except Exception:  # noqa: BLE001 — a broken sensor reads as "no signal"
            return None
        vals = [ew for ew, n in ewmas.values()
                if n >= self.cfg.recall_min_samples]
        return min(vals) if vals else None

    def _ladder_step(self, knob: str, buckets, idx: int, hold: int,
                     ewma) -> tuple[int, int]:
        """The ONE recall-guarded cut/backoff/dead-band state machine,
        shared by both budgets (the rescore cap and the IVF probe cap —
        their only legitimate divergence is what a paused sample gate
        means, which the CALLERS decide by what they pass as `ewma`).
        -> (new bucket index, new hold count)."""
        cfg = self.cfg
        top = len(buckets) - 1
        if ewma is None:
            # signal gone: fail static — a budget may only stay cut
            # while the recall meter actively vouches for it
            if idx != top:
                self._set_knob(knob, buckets[top], "budget",
                               reason="no recall signal")
            return top, 0
        if ewma < cfg.recall_floor + cfg.recall_backoff_margin:
            # near (or under) the floor: back off IMMEDIATELY — restores
            # are never held behind hysteresis, only cuts are
            if idx < top:
                idx = min(idx + 1, top)
                self._set_knob(knob, buckets[idx], "budget",
                               reason=f"ewma {ewma:.4f} near floor "
                                      f"{cfg.recall_floor}")
            return idx, 0
        if ewma >= cfg.recall_floor + cfg.recall_slack:
            hold += 1
            if hold >= cfg.hold_ticks and idx > 0:
                idx -= 1
                self._set_knob(knob, buckets[idx], "budget",
                               reason=f"ewma {ewma:.4f} holds slack over "
                                      f"floor {cfg.recall_floor}")
                return idx, 0
            return idx, hold
        return idx, 0  # dead band: hold position

    def _tick_budget(self) -> None:
        self._tick_ivf_budget()
        if self._sampling_paused:
            # brownout stage 3 silenced the meter ITSELF: hold the cap at
            # its last vouched-for value — restoring to the 128 maximum
            # would 4x per-query device work exactly while the SLO burns,
            # and cutting further would act on a frozen EWMA. The lease
            # keeps the held value alive only while this thread ticks, so
            # a stalled/dead plane still fail-statics at the readers.
            self._r_hold = 0
            self._fc_hold = 0
            self._fr_hold = 0
            return
        ewma = self._sense_recall()
        self._r_idx, self._r_hold = self._ladder_step(
            KNOB_RESCORE_CAP, R_BUCKETS, self._r_idx, self._r_hold, ewma)
        # The funnel's two stage budgets ride the same ladder with the
        # same paused-gate semantics as the rescore cap: both caps only
        # ever CUT device work (index/tpu.py floors them against k and
        # falls back to the built-in maxima when a cut would starve
        # top-k), so restoring to maximum mid-brownout would multiply
        # stage-2/3 re-rank work exactly while the SLO burns.
        self._fc_idx, self._fc_hold = self._ladder_step(
            KNOB_FUNNEL_C, FC_BUCKETS, self._fc_idx, self._fc_hold, ewma)
        self._fr_idx, self._fr_hold = self._ladder_step(
            KNOB_FUNNEL_RESCORE, FR_BUCKETS, self._fr_idx, self._fr_hold,
            ewma)

    def _tick_ivf_budget(self) -> None:
        """The SECOND recall-guarded budget (ROADMAP item 3/4): the IVF
        probe-count cap on the same shared ladder. The one divergence
        from the rescore cap is what a brownout-paused sample gate
        means: here it reads as NO SIGNAL -> revert — unlike the
        rescore cap (where restoring to maximum 4x's per-query work
        mid-burn and the last vouched-for value is held), restoring
        top_p to the configured probe count is the recall-safe
        direction and the index's own configured value bounds its cost,
        so a silenced meter may not keep vouching for probe cuts."""
        ewma = None if self._sampling_paused else self._sense_recall()
        self._p_idx, self._p_hold = self._ladder_step(
            KNOB_IVF_TOP_P, P_BUCKETS, self._p_idx, self._p_hold, ewma)

    # -- controller 3: coalescer window / pipeline depth ----------------------

    def _sense_lanes(self) -> Optional[dict]:
        from weaviate_tpu.monitoring import perf

        pw = perf.get_window()
        if pw is None:
            return None
        try:
            return pw.control_signals()
        except Exception:  # noqa: BLE001 — a broken sensor reads as "no signal"
            return None

    def _tick_lanes(self) -> None:
        if self.coalescer is None:
            return
        sig = self._sense_lanes()
        if sig is None or sig.get("dispatches", 0) < 4:
            return  # too little traffic to steer on
        cfg = self.cfg
        duty = sig["duty_cycle"]
        qw_ms = sig["queue_wait_mean_ms"]
        win = self._read(KNOB_WINDOW_S, self._defaults[KNOB_WINDOW_S])
        win_ms = win * 1000.0
        # window: queue-dominated (waits dwarf the window while the
        # device stays busy) -> widen so dispatches fill and per-dispatch
        # overhead amortizes; a starved device with short waits -> walk
        # back toward the configured default for latency
        if qw_ms > 2.0 * win_ms and duty >= cfg.duty_hi:
            self._win_hold = self._win_hold + 1 if self._win_dir == 1 else 1
            self._win_dir = 1
            if self._win_hold >= cfg.hold_ticks:
                self._win_hold = 0
                self._set_knob(KNOB_WINDOW_S, win * 1.5, "lanes",
                               reason=f"queue-dominated (wait {qw_ms:.2f}ms"
                                      f", duty {duty:.2f})")
        elif duty <= cfg.duty_lo and qw_ms < 0.5 * win_ms:
            self._win_hold = self._win_hold + 1 if self._win_dir == -1 else 1
            self._win_dir = -1
            if self._win_hold >= cfg.hold_ticks:
                self._win_hold = 0
                target = max(win / 1.5, self._defaults[KNOB_WINDOW_S])
                self._set_knob(KNOB_WINDOW_S, target, "lanes",
                               reason=f"device-starved (duty {duty:.2f})")
        else:
            self._win_hold = self._win_dir = 0
        # pipeline depth: a starved device WITH waiting work is a
        # pipeline bubble (enqueue and finalize serialize) -> deepen;
        # a saturated device gains nothing from extra in-flight lanes ->
        # walk back to the configured default
        if duty <= cfg.duty_lo and qw_ms > win_ms \
                and self._depth < cfg.depth_max:
            self._depth_hold = \
                self._depth_hold + 1 if self._depth_dir == 1 else 1
            self._depth_dir = 1
            if self._depth_hold >= cfg.hold_ticks:
                self._depth_hold = 0
                self._actuate_depth(self._depth + 1,
                                    f"pipeline bubble (duty {duty:.2f}, "
                                    f"wait {qw_ms:.2f}ms)")
        elif duty >= cfg.duty_hi and self._depth > self._depth_default:
            self._depth_hold = \
                self._depth_hold + 1 if self._depth_dir == -1 else 1
            self._depth_dir = -1
            if self._depth_hold >= cfg.hold_ticks:
                self._depth_hold = 0
                self._actuate_depth(self._depth - 1,
                                    f"device saturated (duty {duty:.2f})")
        else:
            self._depth_hold = self._depth_dir = 0

    def _actuate_depth(self, depth: int, reason: str) -> None:
        depth = min(max(int(depth), 1), max(self.cfg.depth_max,
                                            self._depth_default))
        if depth == self._depth or self.coalescer is None:
            return
        prev = self._depth
        applied = self.coalescer.set_pipeline_depth(depth)
        self._depth = applied
        self._journal_actuation("pipeline_depth", prev, applied, "lanes",
                                reason)

    # -- controller 4: tenant rate quotas (enforcement entry) -----------------

    def take_rate_token(self, tenant: Optional[str]) -> Optional[float]:
        """Spend one token of `tenant`'s rate quota. -> None (admitted)
        or the Retry-After hint in seconds (time to the next token)."""
        if not tenant or self.rate_buckets.rate_qps <= 0.0:
            return None
        return self.rate_buckets.take(
            tenant, self._read(KNOB_RATE_SCALE, 1.0))

    # -- revert / lifecycle ----------------------------------------------------

    def revert_all(self, reason: str) -> None:
        """Restore EVERY actuated knob to its configured default: the
        leased store empties, pipeline depth and paused sampling restore,
        the ladder resets. Called by unconfigure (clean shutdown) and by
        the run loop's finally (thread death) — fail static, journaled.
        IDEMPOTENT until the next actuation: _journal_actuation clears
        the reverted flag, so a straggling tick that completes AFTER a
        timed-out shutdown join re-arms the revert its own finally then
        performs — shutdown() and the thread can both call this without
        double-journaling, and neither ordering leaks an actuation."""
        with self._lock:
            if self._reverted:
                return
            self._reverted = True
            had = {n: v for n, (v, _) in self._knobs.items()}
            self._knobs.clear()
        self._resume_sampling()
        if self.coalescer is not None and self._depth != self._depth_default:
            try:
                self.coalescer.set_pipeline_depth(self._depth_default)
            except Exception:  # noqa: BLE001 — revert must never raise
                pass
        self._depth = self._depth_default
        self.brownout_stage = STAGE_NORMAL
        self._stage_clean_ticks = 0
        self._r_idx = len(R_BUCKETS) - 1
        self._p_idx = len(P_BUCKETS) - 1
        self._fc_idx = len(FC_BUCKETS) - 1
        self._fr_idx = len(FR_BUCKETS) - 1
        self._r_hold = self._p_hold = self._win_hold = self._depth_hold = 0
        self._fc_hold = self._fr_hold = 0
        self._win_dir = self._depth_dir = 0
        incidents.emit("controller_revert", scope="serving",
                       reason=reason, knobs=sorted(had))
        if had:
            _LOG.warning(
                "control plane reverted %d knob(s) to configured defaults "
                "(%s): %s", len(had), reason, sorted(had))
        m = self.metrics
        if m is not None:
            try:
                m.controller_brownout_stage.set(0)
                for name in KNOB_NAMES:
                    m.controller_knob.labels(name).set(self._defaults[name])
            except Exception:  # noqa: BLE001 — revert must never raise
                pass

    def _publish_gauges(self) -> None:
        m = self.metrics
        if m is None:
            return
        try:
            m.controller_brownout_stage.set(self.brownout_stage)
            for name in KNOB_NAMES:
                m.controller_knob.labels(name).set(
                    self._read(name, self._defaults[name]))
        except Exception:  # noqa: BLE001 — metrics must not break the tick
            pass

    def summary(self) -> dict:
        """The /debug/controllers body (and the flight-recorder bundle
        section)."""
        knobs = {}
        for name in KNOB_NAMES:
            default = self._defaults[name]
            value = self._read(name, default)
            knobs[name] = {"value": value, "default": default,
                           "actuated": value != default}
        knobs["pipeline_depth"] = {
            "value": self._depth, "default": self._depth_default,
            "actuated": self._depth != self._depth_default}
        fast, slow = self._sense_burn()
        return {
            "tick_s": self.tick_s,
            "lease_s": round(self.lease_s, 3),
            "ticks": self._ticks,
            "thread_alive": (self._thread.is_alive()
                            if self._thread is not None else False),
            "controllers": {
                "brownout": {"enabled": self.cfg.brownout_enabled,
                             "stage": self.brownout_stage,
                             "clean_ticks": self._stage_clean_ticks,
                             "fast_burn": fast, "slow_burn": slow,
                             "sampling_paused": self._sampling_paused},
                "budget": {"enabled": self.cfg.budget_enabled,
                           "rescore_r_cap": R_BUCKETS[self._r_idx],
                           "ivf_top_p_cap": P_BUCKETS[self._p_idx],
                           "funnel_c_cap": FC_BUCKETS[self._fc_idx],
                           "funnel_rescore_cap": FR_BUCKETS[self._fr_idx],
                           "recall_floor": self.cfg.recall_floor,
                           "recall_ewma_min": self._sense_recall()},
                "lanes": {"enabled": self.cfg.lanes_enabled,
                          "pipeline_depth": self._depth,
                          "signals": self._sense_lanes()},
                "rate": {"enabled": self.rate_buckets.rate_qps > 0.0,
                         **self.rate_buckets.stats()},
            },
            "knobs": knobs,
            **self._actuation_snapshot(),
            "reverted": self._reverted,
        }

    def _actuation_snapshot(self) -> dict:
        # under the lock: the tick thread appends/inserts concurrently,
        # and copying a mutating deque/dict raises RuntimeError
        with self._lock:
            return {"actuations": dict(self._actuations),
                    "recent_actuations": list(self._recent)}

    def shutdown(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(self.tick_s * 4, 2.0))
        self.revert_all("control plane shutdown")


class _ControllerSettings:
    """Resolved controller settings: a ControllerConfig dataclass (config/
    config.py), overridden by explicit kwargs (tests). The field set and
    defaults are DERIVED from the dataclass (config imports nothing from
    serving/, so no cycle) — one source of truth, no drift between a
    test-constructed plane and a config-built one."""

    _FIELDS = {
        f.name: f.default
        for f in dataclasses.fields(ControllerConfig)
        if f.name != "enabled"  # App wiring's gate, not a plane setting
    }

    def __init__(self, config=None, overrides: Optional[dict] = None):
        overrides = overrides or {}
        for name, default in self._FIELDS.items():
            if name in overrides:
                value = overrides[name]
            elif config is not None and hasattr(config, name):
                value = getattr(config, name)
            else:
                value = default
            setattr(self, name, value)
        unknown = set(overrides) - set(self._FIELDS)
        if unknown:
            raise TypeError(f"unknown controller settings: {sorted(unknown)}")
        self.tick_s = max(float(self.tick_s), 0.01)
        self.hold_ticks = max(int(self.hold_ticks), 1)


# -- module state + zero-hop accessors ----------------------------------------

_plane: Optional[ControlPlane] = None

# final summaries of recently-unconfigured planes (CI failure artifact:
# tests/conftest.py dumps these to debug_control.json beside the other
# plane stashes). Guarded by its own lock — concurrent App teardowns
# share it (the perf.py pattern).
_final_summaries: deque = deque(maxlen=8)
_summaries_lock = threading.Lock()


def configure(plane: Optional[ControlPlane]) -> Optional[ControlPlane]:
    """Install (or clear, with None) the process-wide control plane."""
    global _plane
    _plane = plane
    return plane


def unconfigure(plane: ControlPlane) -> None:
    """Clear the global only if it is still `plane` (App shutdown must
    not tear down a newer App's plane); stop the tick thread and revert
    every knob to its configured default; stash the final summary for
    the CI artifact dump when it ever ticked."""
    global _plane
    if _plane is plane:
        _plane = None
    try:
        if plane._ticks or plane._actuations:
            doc = plane.summary()
            with _summaries_lock:
                _final_summaries.append(doc)
    except Exception:  # noqa: BLE001 — teardown must never fail shutdown
        pass
    plane.shutdown()


def get_plane() -> Optional[ControlPlane]:
    return _plane


def recent_summaries() -> list:
    """Final summaries of planes torn down this process (newest last),
    plus the live plane's current summary when one is installed."""
    with _summaries_lock:
        out = list(_final_summaries)
    p = _plane
    if p is not None:
        try:
            out.append(p.summary())
        except Exception:  # noqa: BLE001
            pass
    return out


# -- serving-path knob readers (disabled => one comparison, no work) ----------


def coalescer_window_s(default: float) -> float:
    """The coalescer's flush window (seconds), controller-steered."""
    p = _plane
    if p is None:
        return default
    return p._read(KNOB_WINDOW_S, default)


def admission_margin() -> float:
    """Multiplier on the deadline-unreachable queue-wait estimate —
    brownout tightens admission by inflating it (shed earlier)."""
    p = _plane
    if p is None:
        return 1.0
    return p._read(KNOB_MARGIN, 1.0)


def tenant_cap_scale() -> float:
    """Scale on the per-tenant in-system row cap (brownout shrinks it)."""
    p = _plane
    if p is None:
        return 1.0
    return p._read(KNOB_CAP_SCALE, 1.0)


def retry_after_scale() -> float:
    """Scale on shed Retry-After hints (brownout backs clients off
    harder while the ladder is engaged)."""
    p = _plane
    if p is None:
        return 1.0
    return p._read(KNOB_RETRY_SCALE, 1.0)


def rescore_r_cap(default: int) -> int:
    """Cap on the PQ fast-scan candidate budget (index/tpu.py
    ``_rescore_r``); the recall-guarded budget controller steps it down
    bucket-by-bucket while measured recall slack exists. Never exceeds
    `default` (the index's own maximum)."""
    p = _plane
    if p is None:
        return default
    return min(int(p._read(KNOB_RESCORE_CAP, default)), int(default))


def ivf_top_p_cap(default: int) -> int:
    """Cap on the IVF probe count (index/tpu.py ``_ivf_plan``) — the
    second recall-guarded budget: while the shadow auditor's recall
    EWMA holds measured slack over the floor, probes step down the
    P_BUCKETS ladder; signal loss (including a brownout-paused sample
    gate) reverts to `default` (the index's own configured probe
    count). Never exceeds `default` — the budget may only cut."""
    p = _plane
    if p is None:
        return default
    return min(int(p._read(KNOB_IVF_TOP_P, default)), int(default))


def funnel_c_cap(default: int) -> int:
    """Cap on the 4-bit funnel's stage-1 survivor count C (index/tpu.py
    ``_funnel_budgets``) — the third recall-guarded budget, stepping the
    FC_BUCKETS ladder with the rescore cap's pause semantics (a silenced
    meter holds the last vouched-for value; every cut is journaled via
    ``_set_knob``). Never exceeds `default` — the budget may only cut,
    and the index floors the result against k so a cut can narrow the
    funnel but never starve top-k."""
    p = _plane
    if p is None:
        return default
    return min(int(p._read(KNOB_FUNNEL_C, default)), int(default))


def funnel_rescore_cap(default: int) -> int:
    """Cap on the 4-bit funnel's stage-3 exact-rescore depth c
    (index/tpu.py ``_funnel_budgets``) — the fourth recall-guarded
    budget, same FR_BUCKETS ladder discipline as ``funnel_c_cap``.
    Never exceeds `default`."""
    p = _plane
    if p is None:
        return default
    return min(int(p._read(KNOB_FUNNEL_RESCORE, default)), int(default))


def take_rate_token(tenant: Optional[str]) -> Optional[float]:
    """Tenant rate-quota gate (coalescer admission). -> None when
    admitted (or the quota is off), else the Retry-After hint in
    seconds: the time until the tenant's next token accrues."""
    p = _plane
    if p is None:
        return None
    return p.take_rate_token(tenant)
