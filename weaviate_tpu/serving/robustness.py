"""Request-lifecycle robustness: deadlines, overload shedding, breaker.

The serving stack before this module had three failure modes unbecoming of
a system meant to serve heavy traffic: a request admitted to the coalescer
had no deadline (a wedged flush thread could hang a client forever), a full
pipeline applied backpressure by silently stalling rather than shedding
(every queued client eventually timed out instead of a few failing fast
with a retry hint), and a device error mid-dispatch had no engineered
recovery beyond per-kernel fallbacks. This module supplies the three
primitives; the wiring lives where the requests flow:

  Deadline        REST ``X-Request-Timeout-Ms`` / gRPC deadline / config
                  ``QUERY_TIMEOUT_MS`` -> a monotonic expiry carried in a
                  ContextVar through usecases/traverser into
                  serving/coalescer lanes and db/shard dispatches. Expired
                  requests fail fast (``DeadlineExceededError`` -> 504 /
                  DEADLINE_EXCEEDED) instead of occupying a dispatch slot,
                  and every waiter wait on the serving path is bounded by
                  the remaining deadline.

  OverloadedError the shed signal (-> 429 / RESOURCE_EXHAUSTED with a
                  Retry-After hint). Raised by the coalescer's bounded
                  admission queue when the queue is full (cost-aware:
                  queued ROWS, not requests) or the estimated queue wait
                  already exceeds the request's remaining deadline.

  CircuitBreaker  trips OPEN after N consecutive device dispatch failures;
                  while open the shard serves reads from the index's host
                  fallback plane (``search_by_vectors_host``) instead of
                  queueing doomed device work; after a cooldown it
                  HALF-OPENs and lets a bounded number of probe dispatches
                  through — one success closes it, one failure re-opens.

  Tenant scope    WHO a request belongs to (multi-tenant fairness): a
                  ContextVar riding the same plumbing as the deadline.
                  Defaults to the queried class name when no explicit
                  identity arrives; REST ``X-Tenant-Id`` / gRPC
                  ``x-tenant-id`` metadata override it (validated against
                  header injection like ``X-Request-Id`` — an invalid
                  value is REJECTED, not cleaned, because a tenant id is
                  an accounting key, not an echo). The coalescer's
                  weighted-fair admission, the per-tenant shed/deadline
                  metrics, the allowList cache's share bound, and the
                  tenant tags on traces all read it through
                  ``effective_tenant``.

Like monitoring/tracing.py, the module state is process-wide globals with
one-comparison disabled fast paths: no deadline set => ``check_deadline``
is a ContextVar read and a None compare; breaker disabled => ``get_breaker``
returns None and the shard gate is one comparison. The module imports only
the stdlib, so every layer (db, index, usecases, server) can import it
without cycles.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import re
import threading
import time
from typing import Any, Iterator, Optional

_LOG = logging.getLogger(__name__)


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed — mapped to HTTP 504 / gRPC
    DEADLINE_EXCEEDED by the frontends. Fail-fast by design: the holder
    must NOT retry on the direct path (the budget is already spent)."""


class OverloadedError(RuntimeError):
    """The request was shed by admission control — mapped to HTTP 429 (+
    Retry-After) / gRPC RESOURCE_EXHAUSTED. ``retry_after_s`` is the
    server's drain estimate; clients should back off at least that long."""

    def __init__(self, message: str, retry_after_s: float = 0.1):
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.001)


class Deadline:
    """Monotonic expiry for one request. Immutable; cheap to test."""

    __slots__ = ("expires_at",)

    def __init__(self, timeout_s: float):
        self.expires_at = time.monotonic() + max(float(timeout_s), 0.0)

    def remaining_s(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at


# the active request's deadline (None = unbounded). Rides contextvars like
# the trace span, so it follows the request through the graphql executor,
# batch pool slots, and into coalescer admission on the serving thread.
_DEADLINE: contextvars.ContextVar = contextvars.ContextVar(
    "weaviate_deadline", default=None)


@contextlib.contextmanager
def deadline_scope(timeout_ms: float) -> Iterator[Optional[Deadline]]:
    """Install a deadline for the enclosed request. timeout_ms <= 0 is the
    unbounded no-op (yields None, touches nothing)."""
    if timeout_ms is None or timeout_ms <= 0:
        yield None
        return
    d = Deadline(timeout_ms / 1000.0)
    token = _DEADLINE.set(d)
    try:
        yield d
    finally:
        _DEADLINE.reset(token)


def current_deadline() -> Optional[Deadline]:
    return _DEADLINE.get()


def remaining_s() -> Optional[float]:
    """Seconds until the current deadline (clamped >= 0), or None when the
    request is unbounded."""
    d = _DEADLINE.get()
    if d is None:
        return None
    return max(d.remaining_s(), 0.0)


def check_deadline(where: str) -> None:
    """Raise (and count) if the current request's deadline already passed.
    The fail-fast gate at every stage boundary: an expired request must
    not occupy a dispatch slot, a gate permit, or a coalescer lane."""
    d = _DEADLINE.get()
    if d is None or not d.expired():
        return
    count_deadline(where)
    raise DeadlineExceededError(f"request deadline expired at {where}")


# -- tenant identity ----------------------------------------------------------

# the active request's tenant (None = not explicitly set; consumers fall
# back to the queried class name via effective_tenant). Rides contextvars
# exactly like the deadline: installed by the REST/gRPC frontends, copied
# into batch pool slots, read at coalescer admission and in the shard's
# allowList cache.
_TENANT: contextvars.ContextVar = contextvars.ContextVar(
    "weaviate_tenant", default=None)

# printable ASCII, no separators that could smuggle into a header or a
# metric label, bounded length. Deliberately stricter than the request-id
# cleaner: a tenant id keys ACCOUNTING (queues, budgets, metrics), so an
# invalid one is rejected with a 4xx instead of silently rewritten — two
# spellings of one tenant must never split its budget.
_TENANT_ID_RE = re.compile(r"^[\x21-\x7e]{1,64}$")


# identities the SYSTEM emits: "other" is the TenantLabeler's aggregate
# metric bucket, "multi" tags merged cross-tenant dispatches in traces. A
# client claiming either would hide its accounting inside the aggregate.
_RESERVED_TENANT_IDS = frozenset({"other", "multi"})


def validate_tenant_id(value: Optional[str]) -> Optional[str]:
    """Parse an inbound tenant header/metadata value. None/empty -> None
    (the class-name default applies). Invalid (injection bytes, blanks,
    over-long, a reserved system identity) -> ValueError — the frontends
    map it to 400 / INVALID_ARGUMENT; it is never cleaned-and-echoed."""
    if value is None:
        return None
    v = value.strip()
    if not v:
        return None
    if not _TENANT_ID_RE.match(v):
        raise ValueError(
            "invalid tenant id: printable ASCII without spaces, "
            "at most 64 chars")
    if v.lower() in _RESERVED_TENANT_IDS:
        raise ValueError(
            f"invalid tenant id: {v!r} is reserved (system aggregate "
            "bucket)")
    return v


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]) -> Iterator[Optional[str]]:
    """Install the request's explicit tenant identity. None is the no-op
    scope (consumers fall back to the class-name default)."""
    if not tenant:
        yield None
        return
    token = _TENANT.set(tenant)
    try:
        yield tenant
    finally:
        _TENANT.reset(token)


def current_tenant() -> Optional[str]:
    return _TENANT.get()


def effective_tenant(default: Optional[str] = None) -> Optional[str]:
    """The accounting identity for the current request: the explicitly
    installed tenant when one rode in on the request, else `default`
    (callers pass the queried class name — per-class isolation is the
    sane default when clients send no identity at all)."""
    t = _TENANT.get()
    if t is not None:
        return t
    return default


def count_tenant_shed(tenant: Optional[str], reason: str) -> None:
    """Per-tenant shed accounting, cardinality-bounded by the metrics
    registry's TenantLabeler (top-K by traffic + 'other')."""
    m = _metrics
    if m is not None and tenant:
        try:
            m.tenant_shed.labels(m.tenant_labels.observe(tenant),
                                 reason).inc()
        except Exception:  # noqa: BLE001 — metrics must not break serving
            pass


def count_tenant_deadline(tenant: Optional[str]) -> None:
    """Per-tenant deadline-expired accounting (same bounded labels)."""
    m = _metrics
    if m is not None and tenant:
        try:
            m.tenant_deadline.labels(m.tenant_labels.observe(tenant)).inc()
        except Exception:  # noqa: BLE001 — metrics must not break serving
            pass


class TenantConcurrencyGate:
    """Front-door bound on one tenant's CONCURRENT in-server requests.

    The admission queue bounds a tenant's rows, but the Python work a
    request costs BEFORE admission (transport, parse, traverse) is paid
    per concurrent request — a tenant opening hundreds of connections
    starves every other tenant's handler threads on the host CPU no
    matter how hard the queue sheds it. This gate is the cheapest
    possible refusal: one dict increment at the frontend, before any
    per-request work, shedding the excess with the same
    429/RESOURCE_EXHAUSTED + Retry-After contract as the queue. Applied
    to requests carrying an EXPLICIT tenant identity (anonymous traffic
    resolves its class-name tenant too deep for a front-door check).
    """

    # per-tenant shed counters kept at most this many distinct keys; a
    # storm of invented tenant ids overflows into the "other" bucket (the
    # TenantLabeler discipline, without the traffic-ranking machinery)
    _SHED_KEYS_MAX = 256

    def __init__(self, max_concurrent: int, metrics=None):
        self.max_concurrent = max(int(max_concurrent), 1)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._inflight_total = 0
        self._shed_total = 0
        self._shed: dict[str, int] = {}

    def enter(self, tenant: str) -> bool:
        with self._lock:
            c = self._counts.get(tenant, 0)
            if c >= self.max_concurrent:
                # refusal accounting lives ON the gate (coalescer.stats()
                # surfaces it; the caller still counts the per-tenant shed
                # vecs) — ROADMAP item-4 follow-up
                self._shed_total += 1
                key = (tenant if tenant in self._shed
                       or len(self._shed) < self._SHED_KEYS_MAX else "other")
                self._shed[key] = self._shed.get(key, 0) + 1
                self._gate_metrics(shed=True)
                return False
            self._counts[tenant] = c + 1
            self._inflight_total += 1
            total = self._inflight_total
        self._set_inflight_gauge(total)
        return True

    def leave(self, tenant: str) -> None:
        with self._lock:
            c = self._counts.get(tenant, 0) - 1
            if c <= 0:
                # drop zeros so a storm of invented tenant ids cannot
                # grow the dict without bound
                self._counts.pop(tenant, None)
            else:
                self._counts[tenant] = c
            self._inflight_total = max(self._inflight_total - 1, 0)
            total = self._inflight_total
        self._set_inflight_gauge(total)

    def _gate_metrics(self, shed: bool = False) -> None:
        m = self.metrics
        if m is not None and shed:
            try:
                m.tenant_gate_shed.inc()
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    def _set_inflight_gauge(self, total: int) -> None:
        m = self.metrics
        if m is not None:
            try:
                m.tenant_gate_inflight.set(total)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    def in_flight(self, tenant: str) -> int:
        with self._lock:
            return self._counts.get(tenant, 0)

    def stats(self) -> dict:
        """The gate's operator view (surfaced in coalescer.stats())."""
        with self._lock:
            return {
                "max_concurrent": self.max_concurrent,
                "in_flight_total": self._inflight_total,
                "tenants_in_flight": len(self._counts),
                "shed_total": self._shed_total,
                "shed": dict(self._shed),
            }


_tenant_gate: Optional[TenantConcurrencyGate] = None

# drain-rate Retry-After hints (PR-11 satellite): the coalescer registers
# its per-tenant drain-rate estimator here so FRONT-DOOR sheds (the
# concurrency gate below) hint with the tenant's measured queue-drain
# time instead of a fixed constant — a protocol-conformant abuser then
# backs off proportionally to how backed up it actually is.
_retry_hint_provider = None


def set_retry_hint_provider(fn) -> None:
    """Install the per-tenant drain estimator (fn(tenant) -> seconds or
    None). The coalescer owns it; None-clearing goes through
    clear_retry_hint_provider (still-ours discipline)."""
    global _retry_hint_provider
    _retry_hint_provider = fn


def clear_retry_hint_provider(fn) -> None:
    global _retry_hint_provider
    if _retry_hint_provider is fn:
        _retry_hint_provider = None


def drain_retry_hint(tenant: Optional[str], default: float) -> float:
    """Retry-After for `tenant` from the registered drain estimator,
    clamped to a sane band; `default` when no estimator (or no signal
    yet) — never raises (a broken estimator must not break a shed)."""
    fn = _retry_hint_provider
    if fn is None:
        return default
    try:
        h = fn(tenant)
    except Exception:  # noqa: BLE001 — a shed path must always produce a hint
        return default
    if h is None:
        return default
    return min(max(float(h), 0.05), 30.0)


def configure_tenant_gate(
        gate: Optional[TenantConcurrencyGate]
) -> Optional[TenantConcurrencyGate]:
    """Install (or clear, with None) the process-wide concurrency gate."""
    global _tenant_gate
    _tenant_gate = gate
    return gate


def unconfigure_tenant_gate(gate: TenantConcurrencyGate) -> None:
    global _tenant_gate
    if _tenant_gate is gate:
        _tenant_gate = None


def get_tenant_gate() -> Optional[TenantConcurrencyGate]:
    return _tenant_gate


@contextlib.contextmanager
def tenant_concurrency(tenant: Optional[str]) -> Iterator[None]:
    """Hold one slot of `tenant`'s concurrent-request budget for the
    enclosed request. No gate configured or no explicit tenant => no-op
    (one comparison). Over budget => OverloadedError, counted per tenant
    under reason ``concurrency`` — shed BEFORE any per-request work."""
    gate = _tenant_gate
    if gate is None or not tenant:
        yield
        return
    if not gate.enter(tenant):
        count_shed("tenant_concurrency")
        count_tenant_shed(tenant, "concurrency")
        # the hint is the tenant's MEASURED queue-drain estimate when the
        # coalescer has one (a slot frees when one of the tenant's own
        # in-flight requests finishes — its drain rate is the right
        # clock); the 1 s fallback stays deliberately generous for the
        # cold case, because fast retries from its other connections
        # would just burn frontend CPU on more refusals. The 0.25 s floor
        # covers the gate-specific blind spot: a tenant whose slots are
        # held by DIRECT-path requests puts no rows in the coalescer, so
        # an idle queue would hint the generic 0.05 s shed floor against
        # slots that free on a request-duration cadence
        raise OverloadedError(
            f"tenant {tenant!r} exceeds its concurrent-request budget "
            f"({gate.max_concurrent})",
            retry_after_s=max(drain_retry_hint(tenant, 1.0), 0.25))
    try:
        yield
    finally:
        gate.leave(tenant)


# -- circuit breaker ----------------------------------------------------------

STATE_CLOSED = 0
STATE_OPEN = 1
STATE_HALF_OPEN = 2

_STATE_NAMES = {STATE_CLOSED: "closed", STATE_OPEN: "open",
                STATE_HALF_OPEN: "half_open"}


class CircuitBreaker:
    """Device-dispatch circuit breaker (three-state, consecutive-failure
    trip). One instance guards the process's device: dispatch failures are
    a property of the accelerator, not of one shard. This scoping holds
    for the multi-chip mesh too — a mesh dispatch is ONE SPMD program
    spanning every chip, so any chip failing fails the whole program and
    the mesh is one failure domain, not eight (docs/mesh_serving.md);
    per-chip breakers would just trip in lockstep.

    CLOSED     normal serving; ``allow()`` is lock-free. N consecutive
               device errors (``record_failure``) trip to OPEN.
    OPEN       ``allow()`` returns False — callers serve from the host
               fallback plane instead of dispatching doomed device work.
               After ``reset_timeout_s`` the next ``allow()`` moves to
               HALF_OPEN.
    HALF_OPEN  up to ``half_open_probes`` callers get True (probe
               dispatches); the first probe success closes the breaker,
               the first failure re-opens it for another cooldown.
    """

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 2.0, half_open_probes: int = 1,
                 metrics=None, name: str = "device"):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.reset_timeout_s = max(float(reset_timeout_s), 0.0)
        self.half_open_probes = max(int(half_open_probes), 1)
        self.metrics = metrics
        self.name = name
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._consecutive = 0
        self._open_until = 0.0
        self._probes_out = 0
        self._half_open_since = 0.0
        self._publish_state()

    # -- gate ---------------------------------------------------------------

    def allow(self) -> bool:
        """May this dispatch go to the device? False => host fallback. The
        CLOSED read is deliberately lockless (a stale read during a
        transition admits/rejects one extra dispatch, which the next
        record_* call corrects)."""
        if self._state == STATE_CLOSED:
            return True
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            now = time.monotonic()
            if self._state == STATE_OPEN:
                if now < self._open_until:
                    return False
                self._transition(STATE_HALF_OPEN)
                self._probes_out = 0
                self._half_open_since = now
            # HALF_OPEN: bounded probe admission. Probe slots EXPIRE: a
            # probe whose dispatch died without reaching record_success/
            # record_failure (a non-device exception, an abandoned lane)
            # must not wedge the breaker in HALF_OPEN forever — after one
            # cooldown with no verdict, the slots recycle
            if self._probes_out >= self.half_open_probes \
                    and now - self._half_open_since > self.reset_timeout_s:
                self._probes_out = 0
                self._half_open_since = now
            if self._probes_out < self.half_open_probes:
                self._probes_out += 1
                return True
            return False

    def record_success(self) -> bool:
        """-> True when this success RECOVERED the breaker (a transition
        back to CLOSED) — callers use it to release degraded-mode
        resources (e.g. the index's host fallback copy) exactly once."""
        # hot-path fast exit: a healthy breaker pays one attr compare
        if self._state == STATE_CLOSED and self._consecutive == 0:
            return False
        with self._lock:
            self._consecutive = 0
            if self._state != STATE_CLOSED:
                self._transition(STATE_CLOSED)
                return True
        return False

    def record_failure(self, err: Optional[BaseException] = None) -> None:
        with self._lock:
            if self._state == STATE_HALF_OPEN:
                # the probe failed: straight back to OPEN for a cooldown
                self._reopen(err)
                return
            self._consecutive += 1
            if self._state == STATE_CLOSED \
                    and self._consecutive >= self.failure_threshold:
                self._reopen(err)

    def _reopen(self, err: Optional[BaseException]) -> None:
        self._open_until = time.monotonic() + self.reset_timeout_s
        self._transition(STATE_OPEN, err)

    def state(self) -> int:
        return self._state

    # -- observability -------------------------------------------------------

    def _transition(self, state: int, err: Optional[BaseException] = None) -> None:
        """Caller holds the lock (or is __init__). Gauge + counter + one log
        line per transition — transitions are rare by construction."""
        prev, self._state = self._state, state
        if state != prev:
            detail = f" ({type(err).__name__}: {err})" if err is not None else ""
            _LOG.warning(
                "%s circuit breaker %s -> %s after %d consecutive "
                "failure(s)%s", self.name, _STATE_NAMES[prev],
                _STATE_NAMES[state], self._consecutive, detail)
        self._publish_state()
        m = self.metrics
        if m is not None and state != prev:
            try:
                m.breaker_transitions.labels(_STATE_NAMES[state]).inc()
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass
        if state != prev:
            # ops-event journal + flight recorder (monitoring/incidents.py):
            # a transition is exactly the fire-once semantics the journal
            # wants, and OPEN is THE canonical incident trigger. Lazy
            # import keeps this module stdlib-only at import time; both
            # entries are one-comparison no-ops when the plane is off and
            # exception-guarded internally. The journal/recorder locks
            # never take the breaker lock, so emitting under it is safe.
            try:
                from weaviate_tpu.monitoring import incidents

                cause = f"{type(err).__name__}: {err}" if err is not None \
                    else ""
                if state == STATE_OPEN:
                    incidents.emit("breaker_open", scope=self.name,
                                   consecutive=self._consecutive,
                                   error=cause)
                    incidents.trigger(
                        "breaker_open",
                        reason=f"{self.name} breaker tripped OPEN after "
                               f"{self._consecutive} consecutive device "
                               "failure(s)",
                        detail={"error": cause})
                elif state == STATE_HALF_OPEN:
                    incidents.emit("breaker_half_open", scope=self.name)
                else:
                    incidents.emit("breaker_closed", scope=self.name)
            except Exception:  # noqa: BLE001 — observability must not break serving
                pass

    def _publish_state(self) -> None:
        m = self.metrics
        if m is not None:
            try:
                m.breaker_state.set(self._state)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass


def is_device_error(exc: BaseException) -> bool:
    """Does this exception mean the DEVICE dispatch failed (vs. a logic
    error in the request)? Only device errors feed the breaker — tripping
    on a caller's ValueError would take a healthy accelerator out of
    service. Recognized: jaxlib's XlaRuntimeError family (by name/module —
    the class path moved across jaxlib versions), and anything carrying a
    truthy ``device_error`` attribute (the fault harness's injected errors
    use it; a custom backend can too). Deliberately NOT any jax.* error:
    tracer/concretization errors are deterministic PROGRAMMING bugs —
    tripping on one would mask it behind 'device incident' metrics while
    the host plane quietly serves around it."""
    if getattr(exc, "device_error", False):
        return True
    t = type(exc)
    if t.__name__ in ("XlaRuntimeError", "XlaError"):
        return True
    mod = getattr(t, "__module__", "") or ""
    return mod.startswith("jaxlib")


# -- module state + accessors (the tracing.py pattern) ------------------------

_breaker: Optional[CircuitBreaker] = None
_metrics: Optional[Any] = None


def configure_breaker(breaker: Optional[CircuitBreaker]) -> Optional[CircuitBreaker]:
    """Install (or clear, with None) the process-wide device breaker."""
    global _breaker
    _breaker = breaker
    return breaker


def unconfigure_breaker(breaker: CircuitBreaker) -> None:
    """Clear the global only if still `breaker` (an App shutdown must not
    tear down a newer App's breaker)."""
    global _breaker
    if _breaker is breaker:
        _breaker = None


def get_breaker() -> Optional[CircuitBreaker]:
    return _breaker


def set_metrics(metrics) -> None:
    """Metrics registry for the shed/deadline counters (None to clear)."""
    global _metrics
    _metrics = metrics


def unset_metrics(metrics) -> None:
    global _metrics
    if _metrics is metrics:
        _metrics = None


def count_shed(reason: str) -> None:
    m = _metrics
    if m is not None:
        try:
            m.requests_shed.labels(reason).inc()
        except Exception:  # noqa: BLE001 — metrics must not break serving
            pass
    # journal the shed (monitoring/incidents.py): this is the one
    # chokepoint every shed reason funnels through (queue_full /
    # deadline_unreachable / tenant_budget / tenant_concurrency), so the
    # journal sees every burst; the burst-coalescing ring folds a storm
    # into one counted entry per reason. Lazy import keeps this module
    # stdlib-only at import time; emit() is a one-comparison no-op when
    # the plane is off and exception-guarded internally.
    try:
        from weaviate_tpu.monitoring import incidents

        incidents.emit("shed_burst", scope=reason)
    except Exception:  # noqa: BLE001 — observability must not break serving
        pass


def count_deadline(where: str) -> None:
    m = _metrics
    if m is not None:
        try:
            m.deadline_expired.labels(where).inc()
        except Exception:  # noqa: BLE001 — metrics must not break serving
            pass
    # deadline-miss chokepoint, same contract as the shed journal above
    try:
        from weaviate_tpu.monitoring import incidents

        incidents.emit("deadline_burst", scope=where)
    except Exception:  # noqa: BLE001 — observability must not break serving
        pass
