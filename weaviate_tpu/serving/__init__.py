"""Serving-layer subsystems that sit between the API frontends and the
shard read path: the cross-request query coalescer and the
request-lifecycle robustness primitives (deadlines, shedding, breaker).

The package re-exports are LAZY (PEP 562): ``db/shard.py`` imports
``weaviate_tpu.serving.robustness`` (stdlib-only) for its breaker gate,
and an eager ``from .coalescer import ...`` here would close an import
cycle back through the coalescer's own ``db.shard`` import."""

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover — type-checker convenience only
    from weaviate_tpu.serving.coalescer import (  # noqa: F401
        CoalescerShutdownError,
        QueryCoalescer,
    )

__all__ = ["CoalescerShutdownError", "QueryCoalescer"]


def __getattr__(name: str):
    if name in __all__:
        from weaviate_tpu.serving import coalescer

        return getattr(coalescer, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
