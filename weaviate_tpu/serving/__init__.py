"""Serving-layer subsystems that sit between the API frontends and the
shard read path (currently: the cross-request query coalescer)."""

from weaviate_tpu.serving.coalescer import (
    CoalescerShutdownError,
    QueryCoalescer,
)

__all__ = ["CoalescerShutdownError", "QueryCoalescer"]
