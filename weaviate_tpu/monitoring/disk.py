"""Disk-pressure failure detection: warn, then flip shards READONLY.

Reference: entities/storagestate + shard_status.go — when the data volume
crosses DISK_USE_WARNING_PERCENTAGE the node logs a warning; crossing
DISK_USE_READONLY_PERCENTAGE flips every local shard to READONLY so writes
fail fast instead of filling the disk and corrupting WALs. Recovery is an
operator action (PUT /v1/schema/{class}/shards/{shard} status=READY),
matching the reference's manual re-activation.
"""

from __future__ import annotations

import shutil
import sys
import threading
from typing import Optional


class DiskMonitor:
    def __init__(self, db, warning_pct: float, readonly_pct: float,
                 interval: float = 10.0):
        self.db = db
        self.warning_pct = warning_pct
        self.readonly_pct = readonly_pct
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._warned = False
        self.readonly_triggered = False

    def usage_pct(self) -> float:
        u = shutil.disk_usage(self.db.root_path)
        return 100.0 * u.used / u.total if u.total else 0.0

    def check_once(self) -> None:
        pct = self.usage_pct()
        if self.readonly_pct and pct >= self.readonly_pct:
            if not self.readonly_triggered:
                self.readonly_triggered = True
                print(
                    f"disk usage {pct:.1f}% >= readonly threshold "
                    f"{self.readonly_pct}%: marking all shards READONLY",
                    file=sys.stderr, flush=True,
                )
            for idx in list(self.db.indexes.values()):
                for shard in idx.shards.values():
                    if shard.status != "READONLY":
                        shard.set_status("READONLY")
        elif self.warning_pct and pct >= self.warning_pct and not self._warned:
            self._warned = True
            print(
                f"disk usage {pct:.1f}% >= warning threshold {self.warning_pct}%",
                file=sys.stderr, flush=True,
            )

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.check_once()
                except Exception:  # noqa: BLE001 — the monitor must survive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True, name="disk-monitor")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
