"""Incident flight recorder + SLO burn-rate engine: the capstone layer.

The four observability planes — tracing/perf (PR 3/7), quality (PR 8),
and the memory ledger (PR 9) — each answer "what is happening" on their
own axis, but nothing connects a *symptom* (breaker OPEN, recall
degradation, headroom alert, SLO burn) to a *preserved, correlated
diagnostic bundle*: BENCH_r02-r05 chip sessions all died on an
unreachable device with their evidence lost (an opaque rc=3), and the
north star ("heavy traffic from millions of users") had no SLO
definition to alert against. This module is the layer that turns the
planes into an incident-response system, with three cooperating pieces:

**Ops-event journal** (``OpsJournal``): a bounded, lock-cheap ring of
structured events the existing planes emit at their state transitions —
breaker CLOSED/OPEN/HALF_OPEN, shed bursts, quality degradation
fire/recover, memory exhaustion alert/recover, jit-shape first
sightings, device fallbacks, flusher death, write-path compress/compact,
fault-injection firings, SLO budget crossings. Each event is a typed
record ``{ts, kind, scope, tenant?, detail}`` under a **bounded kind
taxonomy** (``EVENT_KINDS``; foreign kinds fold to ``other`` — the
JGL010 discipline applied to event kinds, with graftlint JGL013 as the
static twin: every ``emit()`` call site outside this module must pass a
literal registered kind). High-frequency kinds (sheds, fallbacks, jit
compiles) are **burst-coalesced**: within ``BURST_WINDOW_S`` the ring
entry's count increments instead of appending, so a 10k-QPS shed storm
reads as one event with a count, not a ring wipe.

**SLO engine** (``SloEngine``): config-declared objectives
(``SLO_AVAILABILITY_TARGET``, ``SLO_LATENCY_P99_MS``, optional
per-tenant availability overrides under bounded labels) evaluated
continuously from the request outcomes the serving frontends already
classify (ok / shed / deadline / error — the same taxonomy the shed and
deadline counters use) into the standard fast-burn/slow-burn
multi-window pair (5m / 1h): ``burn = bad_fraction / error_budget``.
Exposed as ``weaviate_slo_burn_rate{slo,window}`` and
``weaviate_slo_error_budget_remaining{slo}``; budget-exhaustion
crossings are themselves journal events AND incident triggers, with
fire-once-per-transition + rate-limited-log semantics (the
quality/memory alert idiom).

**Flight recorder** (``FlightRecorder``): on an incident trigger
(breaker OPEN, SLO fast/slow burn, quality degradation, memory
exhaustion, flusher death, SIGTERM/atexit teardown with a live server,
explicit ``POST /debug/incidents/dump``), atomically capture a
correlated bundle — perf/quality/memory window summaries, breaker +
coalescer + tenant-gate stats, the ``/debug/traces`` tail, the journal
tail, a config fingerprint — to ``INCIDENT_DIR`` as one JSON file.
Rate-limited per incident class (``INCIDENT_RATE_LIMIT_S``) and
disk-budgeted (oldest bundles pruned against ``INCIDENT_DIR_MAX_BYTES``,
the directory accounted as an ``incident_bundles`` component in the
memory ledger's disk scope). Captures run on a lazily-started worker
thread (exception-guarded run loop — JGL011) so a serving thread that
trips the breaker never does file IO; the teardown and bench paths dump
synchronously (``dump_now``) because the process is about to die.

Exposure: ``GET /debug/incidents`` (bundle index + journal tail),
``GET /debug/slo``, both behind the pprof authorizer and listed on the
``/debug`` index page. See docs/incidents.md.

Lifecycle mirrors the tracer/perf/quality/memory planes: process-wide
module globals installed by App (``INCIDENTS_ENABLED``, default on) and
cleared on shutdown; disabled, every serving-path entry point
(``emit``/``note_request``/``trigger``) returns after one comparison
and constructs nothing (spy-pinned in tests/test_incidents.py). Every
module-level entry point is exception-guarded internally, so a journal
or recorder fault can never take down a serving path.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Callable, Optional

from weaviate_tpu.testing import sanitizers

_LOG = logging.getLogger(__name__)

# -- the bounded event-kind taxonomy ------------------------------------------
# This tuple IS the journal's kind set and the weaviate_ops_events_total
# label set: a foreign kind folds into "other" at emit time (runtime
# bound), and graftlint JGL013 statically requires every emit() call site
# outside this module to pass one of these as a literal (static twin).

EVENT_KINDS = (
    "breaker_open", "breaker_half_open", "breaker_closed",
    "shed_burst", "deadline_burst",
    "quality_degraded", "quality_recovered",
    "memory_alert", "memory_recovered",
    "jit_compile", "device_fallback", "flusher_dead",
    "write_phase", "fault_injected",
    "slo_burn", "slo_recovered",
    "incident_dump", "teardown",
    # the control plane (serving/controller.py): every knob actuation,
    # brownout-ladder stage transition, and fail-static revert
    "controller_actuation", "controller_brownout", "controller_revert",
)
OTHER = "other"

# kinds that arrive per-request/per-dispatch under load: coalesced per
# (kind, scope) into one ring entry with a count within this window, so a
# storm cannot wipe the ring's low-frequency transition events
BURST_KINDS = frozenset({
    "shed_burst", "deadline_burst", "jit_compile", "device_fallback",
    "write_phase", "fault_injected", "flusher_dead",
    # a controller re-actuating one knob every tick under a sustained
    # signal must read as one counted entry per (kind, knob), not a wipe
    "controller_actuation",
})
BURST_WINDOW_S = 5.0

# incident classes (bundle file names, rate-limit buckets, and the
# weaviate_incident_bundles_total label set; foreign classes fold)
INCIDENT_CLASSES = (
    "breaker_open", "slo_fast_burn", "slo_slow_burn", "quality_degraded",
    "memory_exhaustion", "flusher_dead", "teardown", "manual", "bench",
)

# the standard fast-burn/slow-burn window pair; label values are the
# literal window names on weaviate_slo_burn_rate{slo,window}
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
_SLO_BUCKET_S = 5.0  # per-bucket tally resolution inside the windows

# request outcomes the frontends classify (REST _dispatch / gRPC
# servicer); "bad" ones spend availability error budget. "client" (a 4xx
# caller mistake) counts toward totals but never against the budget.
BAD_OUTCOMES = frozenset({"shed", "deadline", "error"})
REQUEST_OUTCOMES = ("ok", "client", "shed", "deadline", "error")

# seconds between SLO-burn log lines per slo (the counter/journal event
# always fires once per transition; the log is what gets rate-limited)
ALERT_LOG_INTERVAL_S = 60.0


# -- the ops-event journal ----------------------------------------------------


class OpsJournal:
    """Bounded ring of structured ops events. ``emit`` is the serving-path
    entry: one small lock, a dict build, a deque append (or, for burst
    kinds, a count bump on the ring's most recent (kind, scope) entry).
    ``tail``/``summary`` are the on-demand introspection bodies."""

    def __init__(self, size: int = 512, metrics=None,
                 burst_window_s: float = BURST_WINDOW_S):
        self.size = max(int(size), 1)
        self.metrics = metrics
        self.burst_window_s = float(burst_window_s)
        self._lock = sanitizers.register_lock(
            threading.Lock(), "monitoring.incidents.journal")
        self._ring: deque = deque(maxlen=self.size)
        # (kind, scope) -> the live ring dict a burst is coalescing into
        self._burst: dict = {}
        self._counts: dict[str, int] = {}  # lifetime, per folded kind

    def emit(self, kind: str, scope: str = "", tenant: Optional[str] = None,
             **detail) -> None:
        k = kind if kind in EVENT_KINDS else OTHER
        now = time.time()
        with self._lock:
            self._counts[k] = self._counts.get(k, 0) + 1
            if k in BURST_KINDS:
                key = (k, scope)
                evt = self._burst.get(key)
                if evt is not None and now - evt["ts_last"] \
                        <= self.burst_window_s:
                    evt["count"] += 1
                    evt["ts_last"] = now
                    return
            evt = {"ts": round(now, 3), "ts_last": now, "kind": k,
                   "scope": scope, "count": 1}
            if tenant:
                evt["tenant"] = tenant
            if detail:
                evt["detail"] = detail
            if len(self._ring) == self.size:
                # the append below evicts the oldest entry — drop its burst
                # mapping, else an ongoing storm keeps coalescing into the
                # evicted dict and never reappears in the ring
                old = self._ring[0]
                okey = (old["kind"], old["scope"])
                if self._burst.get(okey) is old:
                    del self._burst[okey]
            self._ring.append(evt)
            if k in BURST_KINDS:
                self._burst[(k, scope)] = evt
                if len(self._burst) > 4 * self.size:
                    # a scope-churning storm must not grow the burst map
                    # without bound; dropping it only ends coalescing early
                    self._burst.clear()
        m = self.metrics
        if m is not None:
            try:
                m.ops_events.labels(k).inc()
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    def tail(self, n: int = 128) -> list:
        """The most recent events, oldest first (ts_last dropped from the
        copies only where it equals ts)."""
        with self._lock:
            events = list(self._ring)[-max(int(n), 1):]
        out = []
        for e in events:
            d = dict(e)
            if d.get("count", 1) == 1:
                d.pop("ts_last", None)
            else:
                d["ts_last"] = round(d["ts_last"], 3)
            out.append(d)
        return out

    def counts(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def summary(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            n = len(self._ring)
        return {
            "size": self.size,
            "events_buffered": n,
            "events_total": sum(counts.values()),
            "counts": dict(sorted(counts.items(), key=lambda kv: -kv[1])),
            "tail": self.tail(64),
        }

    def clear(self) -> None:
        """Reset the ring (bench measurement slices); lifetime counts
        survive, like the perf window's dispatch counter."""
        with self._lock:
            self._ring.clear()
            self._burst.clear()


# -- the SLO engine -----------------------------------------------------------


class _Slo:
    """One objective's state: bucketed (total, bad) tallies over the slow
    window, the config target, and the fire-once alert latch."""

    __slots__ = ("name", "kind", "target", "budget", "tenant", "latency_ms",
                 "buckets", "alerting", "alert_window", "fired")

    def __init__(self, name: str, kind: str, target: float,
                 budget: float, tenant: Optional[str] = None,
                 latency_ms: float = 0.0):
        self.name = name
        self.kind = kind            # "availability" | "latency"
        self.target = target
        self.budget = max(budget, 1e-9)
        self.tenant = tenant
        self.latency_ms = latency_ms
        # deque[[bucket_epoch, total, bad]] spanning <= SLOW_WINDOW_S
        self.buckets: deque = deque()
        self.alerting = False       # fire-once latch (either window)
        self.alert_window = ""      # "fast"/"slow" while alerting
        self.fired = 0


class SloEngine:
    """Config-declared SLOs evaluated continuously from request outcomes.

    ``note`` is the per-request entry (one lock, O(1) bucket updates);
    burn rates are evaluated at most once per second under traffic (no
    background thread — a request-driven system's SLO only moves when
    requests do) and on every ``summary()``. Burn math: over a window,
    ``bad_fraction = bad / total``; the burn rate is
    ``bad_fraction / (1 - target)`` — burn 1.0 spends the budget exactly
    at the sustainable rate, the fast threshold (default 14.4, the
    SRE-workbook 5m pair) catches a cliff, the slow threshold (default
    3.0) catches a smolder."""

    def __init__(self, availability_target: float = 0.999,
                 latency_p99_ms: float = 0.0,
                 fast_burn_threshold: float = 14.4,
                 slow_burn_threshold: float = 3.0,
                 min_events: int = 20,
                 tenant_targets: Optional[dict] = None,
                 metrics=None):
        self.fast_burn_threshold = float(fast_burn_threshold)
        self.slow_burn_threshold = float(slow_burn_threshold)
        self.min_events = max(int(min_events), 1)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._slos: list[_Slo] = [
            _Slo("availability", "availability", float(availability_target),
                 1.0 - float(availability_target)),
        ]
        if latency_p99_ms > 0:
            # p99 objective: 1% of completed requests may run over target
            self._slos.append(_Slo(
                "latency_p99", "latency", 0.99, 0.01,
                latency_ms=float(latency_p99_ms)))
        # per-tenant availability overrides: label values are built ONCE
        # here from config (bounded by the config's own size — JGL010's
        # no-construction-at-the-call-site rule holds at .labels() time)
        for t, target in sorted((tenant_targets or {}).items()):
            self._slos.append(_Slo(
                "availability:" + t, "availability", float(target),
                1.0 - float(target), tenant=t))
        self._last_eval = 0.0
        self._alert_last_log: dict[str, float] = {}
        self._requests_total = 0  # lifetime, never evicted
        self._outcomes: dict[str, int] = {}

    # -- the per-request entry -----------------------------------------------

    def note(self, outcome: str, dur_ms: float = 0.0,
             tenant: Optional[str] = None) -> None:
        """Fold one completed request in. ``outcome`` is the frontend's
        classification (REQUEST_OUTCOMES); foreign values count as
        ``error`` (an unclassifiable request is not a good one)."""
        if outcome not in REQUEST_OUTCOMES:
            outcome = "error"
        now = time.monotonic()
        bucket = int(now // _SLO_BUCKET_S)
        bad = outcome in BAD_OUTCOMES
        with self._lock:
            self._requests_total += 1
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
            for slo in self._slos:
                if slo.tenant is not None and slo.tenant != tenant:
                    continue
                if slo.kind == "latency":
                    # the latency objective judges COMPLETED requests;
                    # sheds/errors are availability's problem
                    if outcome not in ("ok", "client"):
                        continue
                    self._bucket_add(slo, bucket,
                                     bad=dur_ms > slo.latency_ms)
                else:
                    self._bucket_add(slo, bucket, bad=bad)
        self._maybe_evaluate(now)

    @staticmethod
    def _bucket_add(slo: _Slo, bucket: int, bad: bool) -> None:
        b = slo.buckets
        if b and b[-1][0] == bucket:
            b[-1][1] += 1
            b[-1][2] += 1 if bad else 0
        else:
            b.append([bucket, 1, 1 if bad else 0])
            horizon = bucket - int(SLOW_WINDOW_S / _SLO_BUCKET_S) - 1
            while b and b[0][0] < horizon:
                b.popleft()

    # -- burn evaluation ------------------------------------------------------

    def _window_tally(self, slo: _Slo, window_s: float, now: float) -> tuple:
        """(total, bad) over the trailing window (caller holds the lock)."""
        first = int((now - window_s) // _SLO_BUCKET_S)
        total = bad = 0
        for bucket, t, b in reversed(slo.buckets):
            if bucket < first:
                break
            total += t
            bad += b
        return total, bad

    def _burn(self, slo: _Slo, window_s: float, now: float) -> Optional[float]:
        total, bad = self._window_tally(slo, window_s, now)
        if total < self.min_events:
            return None  # a cold window over two requests is noise
        return (bad / total) / slo.budget

    def _maybe_evaluate(self, now: float, force: bool = False) -> None:
        with self._lock:
            if not force and now - self._last_eval < 1.0:
                return
            self._last_eval = now
            rows = []
            for slo in self._slos:
                fast = self._burn(slo, FAST_WINDOW_S, now)
                slow = self._burn(slo, SLOW_WINDOW_S, now)
                burning = ((fast is not None
                            and fast >= self.fast_burn_threshold)
                           or (slow is not None
                               and slow >= self.slow_burn_threshold))
                transitioned = burning != slo.alerting
                slo.alerting = burning
                if burning:
                    slo.alert_window = ("fast" if fast is not None
                                        and fast >= self.fast_burn_threshold
                                        else "slow")
                    if transitioned:
                        slo.fired += 1
                rows.append((slo, fast, slow, burning, transitioned))
        for slo, fast, slow, burning, transitioned in rows:
            self._publish(slo, fast, slow, now)
            if burning:
                self._alert(slo, fast, slow, transitioned)
            elif transitioned:
                _LOG.info("SLO burn recovered: slo=%s", slo.name)
                emit("slo_recovered", scope=slo.name)

    def _alert(self, slo: _Slo, fast, slow, transitioned: bool) -> None:
        cls = ("slo_fast_burn" if slo.alert_window == "fast"
               else "slo_slow_burn")
        if transitioned:
            emit("slo_burn", scope=slo.name, window=slo.alert_window,
                 fast_burn=round(fast, 2) if fast is not None else None,
                 slow_burn=round(slow, 2) if slow is not None else None)
            trigger(cls, reason=f"slo {slo.name} {slo.alert_window}-burn",
                    detail={"slo": slo.name, "fast_burn": fast,
                            "slow_burn": slow, "target": slo.target})
        now = time.monotonic()
        last = self._alert_last_log.get(slo.name)
        if transitioned or last is None \
                or now - last >= ALERT_LOG_INTERVAL_S:
            self._alert_last_log[slo.name] = now
            _LOG.warning(
                "SLO error budget burning: slo=%s window=%s fast=%.2fx "
                "slow=%.2fx (thresholds %.1f/%.1f, target %.4g) — journaled "
                "as slo_burn; further lines rate-limited to one per %.0fs",
                slo.name, slo.alert_window,
                fast if fast is not None else float("nan"),
                slow if slow is not None else float("nan"),
                self.fast_burn_threshold, self.slow_burn_threshold,
                slo.target, ALERT_LOG_INTERVAL_S)

    def _publish(self, slo: _Slo, fast, slow, now: float) -> None:
        m = self.metrics
        if m is None:
            return
        try:
            if fast is not None:
                m.slo_burn_rate.labels(slo.name, "5m").set(round(fast, 4))
            if slow is not None:
                m.slo_burn_rate.labels(slo.name, "1h").set(round(slow, 4))
            remaining = self._budget_remaining(slo, now)
            if remaining is not None:
                m.slo_budget_remaining.labels(slo.name).set(remaining)
        except Exception:  # noqa: BLE001 — metrics must not break serving
            pass

    def _budget_remaining(self, slo: _Slo, now: float) -> Optional[float]:
        """Error budget left over the slow (1h) window, 0..1 — 1.0 = no
        budget spent, 0.0 = the hour's budget is gone."""
        with self._lock:
            total, bad = self._window_tally(slo, SLOW_WINDOW_S, now)
        if total == 0:
            return None
        spent = (bad / total) / slo.budget
        return round(min(max(1.0 - spent, 0.0), 1.0), 4)

    def burn_rates(self) -> tuple:
        """(max fast burn, max slow burn) across the AVAILABILITY SLOs —
        the control plane's brownout sensor (serving/controller.py). A
        cold window (under min_events) contributes None; both None when
        nothing qualifies. Per-tenant overrides are deliberately
        included: one tenant's SLO burning is a real burn."""
        now = time.monotonic()
        fast_max = slow_max = None
        with self._lock:
            for slo in self._slos:
                if slo.kind != "availability":
                    continue
                fast = self._burn(slo, FAST_WINDOW_S, now)
                slow = self._burn(slo, SLOW_WINDOW_S, now)
                if fast is not None and (fast_max is None or fast > fast_max):
                    fast_max = fast
                if slow is not None and (slow_max is None or slow > slow_max):
                    slow_max = slow
        return fast_max, slow_max

    # -- introspection --------------------------------------------------------

    def summary(self) -> dict:
        now = time.monotonic()
        self._maybe_evaluate(now, force=True)
        slos = []
        with self._lock:
            requests_total = self._requests_total
            outcomes = dict(self._outcomes)
            rows = [(slo,
                     self._window_tally(slo, FAST_WINDOW_S, now),
                     self._window_tally(slo, SLOW_WINDOW_S, now),
                     self._burn(slo, FAST_WINDOW_S, now),
                     self._burn(slo, SLOW_WINDOW_S, now))
                    for slo in self._slos]
        for slo, (ft, fb), (st, sb), fast, slow in rows:
            doc = {
                "slo": slo.name,
                "kind": slo.kind,
                "target": slo.target,
                "error_budget": round(slo.budget, 6),
                "windows": {
                    "5m": {"requests": ft, "bad": fb,
                           "burn_rate": round(fast, 4)
                           if fast is not None else None},
                    "1h": {"requests": st, "bad": sb,
                           "burn_rate": round(slow, 4)
                           if slow is not None else None},
                },
                "budget_remaining_1h": self._budget_remaining(slo, now),
                "alerting": slo.alerting,
                "alerts_fired": slo.fired,
            }
            if slo.kind == "latency":
                doc["latency_target_ms"] = slo.latency_ms
            if slo.tenant is not None:
                doc["tenant"] = slo.tenant
            slos.append(doc)
        return {
            "requests_total": requests_total,
            "outcomes": outcomes,
            "fast_burn_threshold": self.fast_burn_threshold,
            "slow_burn_threshold": self.slow_burn_threshold,
            "min_events": self.min_events,
            "slos": slos,
        }

    def clear(self) -> None:
        """Reset windows and alert latches (bench measurement slices);
        lifetime counters survive."""
        with self._lock:
            for slo in self._slos:
                slo.buckets.clear()
                slo.alerting = False
                slo.alert_window = ""
            self._alert_last_log.clear()


# -- the flight recorder ------------------------------------------------------

# bundle-name sequence, process-wide: with the pid in the filename, a
# (pid, seq) pair is unique even when several recorders (CI runs many
# Apps per process) share one INCIDENT_DIR within the same second
_bundle_seq = 0
_seq_lock = threading.Lock()


class FlightRecorder:
    """Captures correlated diagnostic bundles to ``INCIDENT_DIR``.

    ``trigger`` is the serving-path entry: a rate-limit check per
    incident class and a drop-not-queue enqueue; the capture (plane
    summaries + file IO) runs on a lazily-started worker thread.
    ``dump_now`` captures synchronously for the paths where the process
    is about to die (SIGTERM/atexit teardown, bench rc=3)."""

    def __init__(self, incident_dir: str, max_bytes: int = 64 * 1024 * 1024,
                 rate_limit_s: float = 300.0, journal: Optional[OpsJournal]
                 = None, engine: Optional[SloEngine] = None, metrics=None):
        self.incident_dir = incident_dir
        self.max_bytes = max(int(max_bytes), 0)
        self.rate_limit_s = float(rate_limit_s)
        self.journal = journal
        self.engine = engine
        self.metrics = metrics
        self._lock = sanitizers.register_lock(
            threading.Lock(), "monitoring.incidents.recorder")
        self._last_dump: dict[str, float] = {}  # folded class -> monotonic
        self._dumped = 0
        self._rate_limited = 0
        self._queue: queue.Queue = queue.Queue(maxsize=4)
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # the App's live serving stats (coalescer, tenant gate): pull
        # callables registered at wiring time, each exception-guarded
        self._stats_providers: dict[str, Callable[[], dict]] = {}
        self._config_fingerprint: Optional[dict] = None

    # -- wiring ---------------------------------------------------------------

    def add_stats_provider(self, name: str, fn: Callable[[], dict]) -> None:
        self._stats_providers[name] = fn

    def set_config_fingerprint(self, doc: dict) -> None:
        self._config_fingerprint = doc

    # -- triggers -------------------------------------------------------------

    @staticmethod
    def _fold_class(cls: str) -> str:
        return cls if cls in INCIDENT_CLASSES else OTHER

    def _rate_limited_now(self, cls: str, force: bool) -> bool:
        """Check-only: True when ``cls`` is inside its rate-limit window.
        The window stamp is written only once a capture is actually
        admitted (enqueued) or written — a dropped or failed capture must
        not silence its incident class for the whole window."""
        if force:
            return False
        with self._lock:
            last = self._last_dump.get(cls)
            if last is not None and \
                    time.monotonic() - last < self.rate_limit_s:
                self._rate_limited += 1
                return True
        return False

    def _stamp(self, cls: str) -> None:
        with self._lock:
            self._last_dump[cls] = time.monotonic()

    def _unstamp(self, cls: str) -> None:
        with self._lock:
            self._last_dump.pop(cls, None)

    def trigger(self, cls: str, reason: str = "",
                detail: Optional[dict] = None) -> bool:
        """Request an asynchronous bundle capture. -> True when a capture
        was admitted (not rate-limited, queue not full)."""
        cls = self._fold_class(cls)
        self._ensure_worker()
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(cls)
            if last is not None and now - last < self.rate_limit_s:
                self._rate_limited += 1
                return False
            try:
                self._queue.put_nowait((cls, reason, detail))
            except queue.Full:
                # the worker is saturated with captures — the in-flight
                # ones already preserve the incident window; drop (and
                # leave the class un-stamped so the next trigger retries)
                return False
            self._last_dump[cls] = now
        return True

    def dump_now(self, cls: str, reason: str = "",
                 detail: Optional[dict] = None,
                 force: bool = False) -> Optional[str]:
        """Capture + write synchronously (teardown/bench paths). -> the
        bundle path, or None when rate-limited or the write failed."""
        cls = self._fold_class(cls)
        if self._rate_limited_now(cls, force=force):
            return None
        try:
            path = self._write(self.capture(cls, reason, detail))
        except Exception:  # noqa: BLE001 — a dump must never take down a caller
            _LOG.warning("incident dump failed", exc_info=True)
            return None
        self._stamp(cls)
        return path

    # -- worker (exception-guarded run loop: a dead recorder thread would
    # -- silently drop every later incident — graftlint JGL011) --------------

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            t = threading.Thread(target=self._run, daemon=True,
                                 name="incident-recorder")
            # start() under the lock: a created-but-unstarted thread reads
            # is_alive() False, and a concurrent caller would spawn a
            # duplicate run loop
            t.start()
            self._worker = t

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.25)
            except queue.Empty:
                continue
            if item is None:
                continue  # shutdown wake-up sentinel
            try:
                self._write(self.capture(*item))
            except Exception:  # noqa: BLE001 — the recorder loop must survive
                _LOG.warning("incident capture failed", exc_info=True)
                # re-arm the class: the admission stamp must not silence
                # an incident whose capture produced no bundle
                self._unstamp(item[0])

    # -- capture --------------------------------------------------------------

    def capture(self, cls: str, reason: str = "",
                detail: Optional[dict] = None) -> dict:
        """Build one correlated bundle. Every plane section is captured
        under its own guard — one broken plane must not cost the bundle —
        and stamps its own ``captured_unix`` so sections are provably
        time-consistent."""
        bundle: dict = {
            "incident": {
                "class": cls,
                "reason": reason,
                "detail": detail or {},
                "ts_unix": round(time.time(), 3),
                "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "pid": os.getpid(),
            },
        }
        if self._config_fingerprint is not None:
            bundle["config"] = self._config_fingerprint

        def section(name: str, fn: Callable[[], Optional[dict]]) -> None:
            try:
                doc = fn()
            except Exception as e:  # noqa: BLE001 — capture what survives
                bundle[name] = {"error": f"{type(e).__name__}: {e}"}
                return
            if doc is not None:
                if isinstance(doc, dict):
                    doc = {"captured_unix": round(time.time(), 3), **doc}
                bundle[name] = doc

        journal = self.journal if self.journal is not None else _journal
        if journal is not None:
            section("journal", journal.summary)
        engine = self.engine if self.engine is not None else _engine
        if engine is not None:
            section("slo", engine.summary)

        def _perf():
            from weaviate_tpu.monitoring import perf

            w = perf.get_window()
            return w.summary() if w is not None else None

        def _quality():
            from weaviate_tpu.monitoring import quality

            a = quality.get_auditor()
            return a.summary() if a is not None else None

        def _memory():
            from weaviate_tpu.monitoring import memory

            led = memory.get_ledger()
            return led.summary() if led is not None else None

        def _traces():
            from weaviate_tpu.monitoring import tracing

            t = tracing.get_tracer()
            if t is None:
                return None
            return {"tail": t.snapshot()[-32:]}

        def _breaker():
            from weaviate_tpu.serving import robustness

            br = robustness.get_breaker()
            if br is None:
                return None
            state = br.state()
            return {
                "state": state,
                "state_name": {0: "closed", 1: "open",
                               2: "half_open"}.get(state, "?"),
                "failure_threshold": br.failure_threshold,
                "reset_timeout_s": br.reset_timeout_s,
            }

        section("perf", _perf)
        section("quality", _quality)
        section("memory", _memory)
        section("traces", _traces)
        section("breaker", _breaker)
        for name, fn in list(self._stats_providers.items()):
            section(name, fn)
        return bundle

    # -- persistence ----------------------------------------------------------

    def _write(self, bundle: dict) -> Optional[str]:
        """Atomic single-file write (tmp + rename) followed by the disk-
        budget prune: oldest bundles go first, the one just written is
        never pruned (a cap smaller than one bundle keeps the newest)."""
        os.makedirs(self.incident_dir, exist_ok=True)
        cls = bundle.get("incident", {}).get("class", OTHER)
        with _seq_lock:
            global _bundle_seq
            _bundle_seq += 1
            seq = _bundle_seq
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        # pid in the name: recorders in different processes (CI shares one
        # INCIDENT_DIR across Apps) must never compute the same path and
        # silently overwrite each other's evidence; class stays the LAST
        # dash-segment (index() parses it from there)
        name = f"incident-{stamp}-{os.getpid()}-{seq:04d}-{cls}.json"
        path = os.path.join(self.incident_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
        os.replace(tmp, path)
        with self._lock:
            self._dumped += 1
        m = self.metrics
        if m is not None:
            try:
                m.incident_bundles.labels(cls).inc()
            except Exception:  # noqa: BLE001
                pass
        journal = self.journal if self.journal is not None else _journal
        if journal is not None:
            try:
                journal.emit("incident_dump", scope=cls, file=name)
            except Exception:  # noqa: BLE001
                pass
        self._prune(keep=name)
        _LOG.warning("incident bundle written: %s (class=%s)", path, cls)
        return path

    def _bundles(self) -> list:
        """(mtime, name, bytes) for every bundle on disk, oldest first."""
        try:
            names = os.listdir(self.incident_dir)
        except OSError:
            return []
        out = []
        for n in names:
            if not (n.startswith("incident-") and n.endswith(".json")):
                continue
            p = os.path.join(self.incident_dir, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((st.st_mtime, n, st.st_size))
        out.sort()
        return out

    def _prune(self, keep: Optional[str] = None) -> None:
        if self.max_bytes <= 0:
            return
        bundles = self._bundles()
        total = sum(b for _, _, b in bundles)
        for _, n, b in bundles:
            if total <= self.max_bytes:
                break
            if n == keep:
                continue
            try:
                os.unlink(os.path.join(self.incident_dir, n))
                total -= b
            except OSError:
                pass

    def dir_bytes(self) -> int:
        """Bundle bytes on disk — the memory ledger's disk-scope
        ``incident_bundles`` component."""
        return sum(b for _, _, b in self._bundles())

    def index(self) -> list:
        """Bundle listing for /debug/incidents, newest first."""
        return [{"file": n, "bytes": b,
                 "mtime_unix": round(t, 1),
                 "class": n[:-5].rsplit("-", 1)[-1]}
                for t, n, b in reversed(self._bundles())]

    def stats(self) -> dict:
        with self._lock:
            return {
                "incident_dir": self.incident_dir,
                "dir_max_bytes": self.max_bytes,
                "rate_limit_s": self.rate_limit_s,
                "dumped": self._dumped,
                "rate_limited": self._rate_limited,
            }

    def shutdown(self) -> None:
        self._stop.set()
        w = self._worker
        if w is not None:
            try:
                self._queue.put_nowait(None)  # wake a blocked worker
            except queue.Full:
                pass
            w.join(timeout=2)


# -- module state + zero-hop accessors ----------------------------------------

_journal: Optional[OpsJournal] = None
_engine: Optional[SloEngine] = None
_recorder: Optional[FlightRecorder] = None

# final journal summaries of recently-unconfigured Apps (CI failure
# artifact: tests/conftest.py dumps these beside the perf/quality/memory
# stashes). Guarded by its own lock — concurrent App teardowns share it.
_final_summaries: deque = deque(maxlen=8)
_summaries_lock = threading.Lock()


def configure(journal: Optional[OpsJournal] = None,
              engine: Optional[SloEngine] = None,
              recorder: Optional[FlightRecorder] = None) -> None:
    """Install the process-wide incident plane (any subset)."""
    global _journal, _engine, _recorder
    if journal is not None:
        _journal = journal
    if engine is not None:
        _engine = engine
    if recorder is not None:
        _recorder = recorder


def unconfigure(journal: Optional[OpsJournal] = None,
                engine: Optional[SloEngine] = None,
                recorder: Optional[FlightRecorder] = None) -> None:
    """Clear each global only if still ours (App shutdown must not tear
    down a newer App's plane); stash the journal's final summary for the
    CI artifact dump when it recorded anything; stop the recorder."""
    global _journal, _engine, _recorder
    if journal is not None:
        try:
            doc = journal.summary()
            if doc.get("events_total"):
                with _summaries_lock:
                    _final_summaries.append(doc)
        except Exception:  # noqa: BLE001 — teardown must never fail shutdown
            pass
        if _journal is journal:
            _journal = None
    if engine is not None and _engine is engine:
        _engine = None
    if recorder is not None:
        if _recorder is recorder:
            _recorder = None
        recorder.shutdown()


def get_journal() -> Optional[OpsJournal]:
    return _journal


def get_engine() -> Optional[SloEngine]:
    return _engine


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def emit(kind: str, scope: str = "", tenant: Optional[str] = None,
         **detail) -> None:
    """The serving-path journal entry. Disabled => one comparison.
    Exception-guarded HERE, once, so the planes' emission call sites can
    never take down a serving path."""
    j = _journal
    if j is None:
        return
    try:
        j.emit(kind, scope=scope, tenant=tenant, **detail)
    except Exception:  # noqa: BLE001 — the journal must never break serving
        pass


def note_request(outcome: str, dur_ms: float = 0.0,
                 tenant: Optional[str] = None) -> None:
    """The per-request SLO feed (REST/gRPC frontends). Disabled => one
    comparison; exception-guarded like emit()."""
    e = _engine
    if e is None:
        return
    try:
        e.note(outcome, dur_ms, tenant)
    except Exception:  # noqa: BLE001 — SLO accounting must never break serving
        pass


def trigger(cls: str, reason: str = "",
            detail: Optional[dict] = None) -> bool:
    """Fire an incident (asynchronous capture). Disabled => one
    comparison; exception-guarded like emit()."""
    r = _recorder
    if r is None:
        return False
    try:
        return r.trigger(cls, reason=reason, detail=detail)
    except Exception:  # noqa: BLE001 — triggers must never break serving
        return False


def teardown_dump() -> Optional[str]:
    """The SIGTERM/atexit hook (chained by profiling.install_trace_
    teardown): dump a forced ``teardown`` bundle IF a recorder is still
    live — a cleanly shut-down App has already unconfigured, so normal
    exits write nothing; a process dying with a live server preserves its
    evidence."""
    r = _recorder
    if r is None:
        return None
    try:
        return r.dump_now("teardown",
                          reason="process teardown with a live server "
                                 "(SIGTERM/atexit)", force=True)
    except Exception:  # noqa: BLE001 — teardown must never raise
        return None


def emergency_dump(reason: str, directory: Optional[str] = None,
                   detail: Optional[dict] = None) -> Optional[str]:
    """Best-effort bundle for processes without a wired recorder (the
    bench's rc=3 unreachable-device exit, the storm modes): uses the
    configured recorder when one is live (forced), else writes a one-shot
    bundle of whatever plane state this process still holds — including
    the perf/quality/memory ``recent_summaries()`` stashes, which survive
    App teardowns — to ``directory`` (default: $INCIDENT_DIR, else
    ./incidents)."""
    try:
        r = _recorder
        if r is not None:
            return r.dump_now("bench", reason=reason, detail=detail,
                              force=True)
        directory = directory or os.environ.get("INCIDENT_DIR") \
            or "./incidents"
        one_shot = FlightRecorder(directory, journal=_journal,
                                  engine=_engine)
        bundle = one_shot.capture("bench", reason=reason, detail=detail)
        # the module-level stashes outlive any torn-down App: a dying
        # bench session still preserves its duty-cycle/ledger evidence
        for name, mod in (("perf_history", "perf"),
                          ("quality_history", "quality"),
                          ("memory_history", "memory")):
            try:
                import importlib

                m = importlib.import_module(
                    f"weaviate_tpu.monitoring.{mod}")
                hist = m.recent_summaries()
                if hist:
                    bundle[name] = hist
            except Exception:  # noqa: BLE001 — capture what survives
                pass
        return one_shot._write(bundle)
    except Exception:  # noqa: BLE001 — an emergency dump must never raise
        _LOG.warning("emergency incident dump failed", exc_info=True)
        return None


def recent_summaries() -> list:
    """Final journal summaries of Apps torn down this process (newest
    last), plus the live journal's current summary when one is
    installed."""
    with _summaries_lock:
        out = list(_final_summaries)
    j = _journal
    if j is not None:
        try:
            out.append(j.summary())
        except Exception:  # noqa: BLE001
            pass
    return out
