"""Prometheus metric registry.

Reference: usecases/monitoring/prometheus.go:22-58 — a process-wide singleton
(`GetMetrics`, prometheus.go:70) holding ~40 metric vecs covering batch
durations, object counts, LSM activity, vector-index operations/durations/
tombstones, query durations, the filtered-vector-search phase breakdown
(shard_read.go:236-287), startup and backup timings.

TPU-first delta: device-side timings come from whole batched dispatches, so
the per-phase breakdown is {filter, device_search (one metric — upload +
scan + topk are one XLA program), rescore, hydrate} rather than the
reference's per-edge accounting. Exposition uses prometheus_client; the REST
layer mounts it on PROMETHEUS_MONITORING_PORT like configure_api.go:116-121.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

_MS_BUCKETS = (0.1, 0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 10000)

# occupancy buckets (requests/rows per coalesced dispatch): powers of two to
# mirror the index's query-padding buckets
_COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class TenantLabeler:
    """Bounded-cardinality mapper from tenant ids to metric label values:
    the top-K tenants by observed traffic get their own label, everyone
    else aggregates under ``other`` — 10k distinct tenants must not mint
    10k prometheus series (graftlint JGL010 is the static twin of this
    runtime bound: label values may never be dynamically-built strings).

    Prometheus series are forever once emitted, so the promotion policy is
    conservative: a tenant is labeled while fewer than ``top_k`` are, and
    afterwards only DISPLACES the weakest labeled tenant when its traffic
    exceeds twice the weakest's — and the total number of tenants ever
    labeled in one process is hard-capped at ``3 * top_k`` (after that the
    set freezes; latecomers stay in ``other``). Traffic counts live in a
    dict pruned to its heaviest half at ``max_tracked``, so memory is
    bounded no matter how many tenant ids a storm invents."""

    OTHER = "other"

    # observations between halvings of every traffic count: ages out a
    # tenant that was heavy long ago, so a CURRENTLY-abusive tenant can
    # displace it within ~one decay window instead of having to out-count
    # its whole lifetime history
    DECAY_EVERY = 50_000

    def __init__(self, top_k: int = 10, max_tracked: int = 4096):
        self.top_k = max(int(top_k), 1)
        self.max_tracked = max(int(max_tracked), 16)
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._labeled: set[str] = set()
        self._ever_labeled = 0
        self._since_decay = 0

    def observe(self, tenant: str) -> str:
        """Count one unit of traffic for `tenant` -> its label value."""
        with self._lock:
            self._since_decay += 1
            if self._since_decay >= self.DECAY_EVERY:
                self._since_decay = 0
                self._counts = {t: c // 2 for t, c in self._counts.items()
                                if c // 2 > 0 or t in self._labeled}
            c = self._counts.get(tenant, 0) + 1
            self._counts[tenant] = c
            if tenant in self._labeled:
                return tenant
            if len(self._labeled) < self.top_k \
                    and self._ever_labeled < 3 * self.top_k:
                self._labeled.add(tenant)
                self._ever_labeled += 1
                return tenant
            if self._ever_labeled < 3 * self.top_k and self._labeled:
                weakest = min(self._labeled,
                              key=lambda t: self._counts.get(t, 0))
                if c > 2 * self._counts.get(weakest, 0):
                    self._labeled.discard(weakest)
                    self._labeled.add(tenant)
                    self._ever_labeled += 1
                    return tenant
            if len(self._counts) > self.max_tracked:
                # keep the heaviest half (labeled tenants always survive)
                keep = sorted(self._counts, key=self._counts.get,
                              reverse=True)[: self.max_tracked // 2]
                self._counts = {t: self._counts[t]
                                for t in set(keep) | self._labeled
                                if t in self._counts}
            return self.OTHER

    def label_for(self, tenant: str) -> str:
        """The label value for `tenant` WITHOUT counting traffic."""
        with self._lock:
            return tenant if tenant in self._labeled else self.OTHER


class Metrics:
    """All metric vecs; label names mirror the reference's (class_name,
    shard_name, operation ...)."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        r = self.registry

        def h(name, doc, labels=()):
            return Histogram(name, doc, labels, registry=r, buckets=_MS_BUCKETS)

        def g(name, doc, labels=()):
            return Gauge(name, doc, labels, registry=r)

        def c(name, doc, labels=()):
            return Counter(name, doc, labels, registry=r)

        # batch / object write path (prometheus.go batch metrics)
        self.batch_durations = h(
            "weaviate_batch_durations_ms", "Batch import phase durations",
            ("operation", "class_name", "shard_name"))
        self.batch_delete_durations = h(
            "weaviate_batch_delete_durations_ms", "Batch delete durations",
            ("class_name", "shard_name"))
        self.objects_durations = h(
            "weaviate_objects_durations_ms", "Single-object op durations",
            ("operation", "step", "class_name", "shard_name"))
        self.object_count = g(
            "weaviate_object_count", "Objects per shard", ("class_name", "shard_name"))

        # queries
        self.queries_count = g(
            "weaviate_concurrent_queries_count", "In-flight queries",
            ("class_name", "query_type"))
        self.query_durations = h(
            "weaviate_queries_durations_ms", "Query durations",
            ("class_name", "query_type"))
        self.query_dimensions = c(
            "weaviate_query_dimensions_total", "Vector dimensions searched",
            ("query_type", "operation", "class_name"))
        # filtered vector search phase breakdown (shard_read.go:236-287)
        self.filtered_vector_filter = h(
            "weaviate_filtered_vector_filter_durations_ms", "allowList build",
            ("class_name", "shard_name"))
        self.filtered_vector_search = h(
            "weaviate_filtered_vector_search_durations_ms",
            "device search dispatch (upload+scan+topk)", ("class_name", "shard_name"))
        self.filtered_vector_rescore = h(
            "weaviate_filtered_vector_rescore_durations_ms", "PQ rescoring pass",
            ("class_name", "shard_name"))
        self.filtered_vector_objects = h(
            "weaviate_filtered_vector_objects_durations_ms", "result hydration",
            ("class_name", "shard_name"))

        # vector index lifecycle (hnsw metrics.go / insert_metrics.go analogs)
        self.vector_index_ops = c(
            "weaviate_vector_index_operations_total", "add/delete/search ops",
            ("operation", "class_name", "shard_name"))
        self.vector_index_durations = h(
            "weaviate_vector_index_durations_ms", "index op durations",
            ("operation", "step", "class_name", "shard_name"))
        self.vector_index_tombstones = g(
            "weaviate_vector_index_tombstones", "live tombstones",
            ("class_name", "shard_name"))
        self.vector_index_tombstone_cleanups = c(
            "weaviate_vector_index_tombstone_cleanup_threads_total",
            "tombstone cleanup runs", ("class_name", "shard_name"))
        self.vector_index_size = g(
            "weaviate_vector_index_size", "index capacity (slots)",
            ("class_name", "shard_name"))
        # per-shard labels so multi-shard classes sum() correctly in prom
        # (a class-only gauge would be overwritten by whichever shard
        # flushed last)
        self.vector_dimensions = g(
            "weaviate_vector_dimensions_sum", "tracked vector dimensions",
            ("class_name", "shard_name"))
        self.vector_segments = g(
            "weaviate_vector_segments_sum", "tracked PQ segments",
            ("class_name", "shard_name"))

        # LSM (prometheus.go lsm metrics)
        self.lsm_active_segments = g(
            "weaviate_lsm_active_segments", "segments per bucket",
            ("strategy", "class_name", "shard_name", "path"))
        self.lsm_segment_objects = g(
            "weaviate_lsm_segment_objects", "entries per segment level",
            ("strategy", "class_name", "shard_name", "path", "level"))
        self.lsm_compactions = c(
            "weaviate_lsm_compactions_total", "compactions run",
            ("strategy", "path"))
        self.lsm_memtable_durations = h(
            "weaviate_lsm_memtable_durations_ms", "memtable op durations",
            ("strategy", "operation"))

        # startup (prometheus.go startup metrics)
        self.startup_durations = h(
            "weaviate_startup_durations_ms", "startup phase durations", ("operation",))
        self.startup_progress = g(
            "weaviate_startup_progress", "0..1 progress", ("operation",))

        # backup
        self.backup_store_durations = h(
            "weaviate_backup_store_ms", "backup store durations",
            ("backend", "class_name"))
        self.backup_restore_durations = h(
            "weaviate_backup_restore_ms", "restore durations",
            ("backend", "class_name"))

        # schema / cluster
        self.schema_tx = c(
            "weaviate_schema_tx_total", "schema transactions", ("type", "status"))
        self.replication_ops = c(
            "weaviate_replication_operations_total", "replication coordinator ops",
            ("operation", "status"))

        # cross-request query coalescer (serving/coalescer.py). Registered
        # here, once, at Metrics construction — the same pattern as
        # weaviate_device_fallback_total: the serving path only ever touches
        # already-registered vecs (inside try/except in the coalescer), so a
        # broken/missing metrics stack can never take down query serving.
        self.coalescer_queue_depth = g(
            "weaviate_coalescer_queue_depth",
            "query rows admission-queued awaiting a coalesced device dispatch")
        self.coalescer_batch_requests = Histogram(
            "weaviate_coalescer_batch_requests",
            "requests per coalesced device dispatch (occupancy)",
            registry=r, buckets=_COUNT_BUCKETS)
        self.coalescer_batch_rows = Histogram(
            "weaviate_coalescer_batch_rows",
            "query rows per coalesced device dispatch (occupancy)",
            registry=r, buckets=_COUNT_BUCKETS)
        self.coalescer_wait = h(
            "weaviate_coalescer_wait_ms",
            "time a request spent in the admission queue before its "
            "dispatch started")
        self.coalescer_bypass = c(
            "weaviate_coalescer_bypass_total",
            "requests that bypassed the coalescer queue to the direct path",
            ("reason",))

        # request tracing (monitoring/tracing.py): exemplar counters so a
        # dashboard sees trace volume/outcomes and the attributed phase
        # shape without scraping /debug/traces. Same registration-once
        # pattern as the coalescer vecs: the tracer only touches
        # already-registered metrics, inside try/except.
        self.traces = c(
            "weaviate_traces_total", "completed request traces",
            ("kind", "outcome"))
        self.trace_phase = h(
            "weaviate_trace_phase_ms",
            "per-request attributed dispatch-phase durations "
            "(device time split across coalesced riders by rows)",
            ("phase",))
        self.trace_dispatch_rows = c(
            "weaviate_trace_dispatch_rows_total",
            "rows in traced device dispatches (actual vs padded — the "
            "fleet-wide padding-waste ratio)", ("kind",))

        # snapshot-isolated read plane (index/tpu.py IndexSnapshot):
        # contention observability for the lock-free search path.
        # Registered once here; the index sets them unguarded, in the same
        # style as its existing gauge updates (_update_index_gauges) —
        # metrics is either None or this working registry.
        self.index_snapshot_gen = g(
            "weaviate_index_snapshot_generation",
            "published device-state snapshot generation (one bump per "
            "writer publication; readers dispatch on it lock-free)",
            ("class_name", "shard_name"))
        self.index_lock_wait = h(
            "weaviate_index_lock_wait_ms",
            "time a snapshot read waited on the index write lock (0 on "
            "the lock-free fast path; nonzero = read-your-writes flush)",
            ("class_name", "shard_name"))
        self.index_inflight_dispatches = g(
            "weaviate_index_inflight_dispatches",
            "search dispatches enqueued on a snapshot but not yet "
            "finalized (the read pipeline's depth)",
            ("class_name", "shard_name"))

        # request-lifecycle robustness (serving/robustness.py): breaker
        # state + shed/deadline counters. Registered once here (the same
        # pattern as the coalescer vecs); the serving path only touches
        # them through exception-guarded helpers.
        self.breaker_state = g(
            "weaviate_breaker_state",
            "device circuit breaker state (0=closed 1=open 2=half-open)")
        self.breaker_transitions = c(
            "weaviate_breaker_transitions_total",
            "device circuit breaker state transitions", ("state",))
        self.requests_shed = c(
            "weaviate_requests_shed_total",
            "requests shed by admission control (429/RESOURCE_EXHAUSTED "
            "with a Retry-After hint)", ("reason",))
        self.deadline_expired = c(
            "weaviate_deadline_expired_total",
            "requests that failed fast on an expired deadline, by the "
            "stage that detected it", ("where",))

        # multi-tenant fairness (serving/coalescer.py weighted-fair
        # admission): per-tenant shed/deadline/queue-depth accounting.
        # EVERY tenant label value is routed through `tenant_labels`
        # (top-K by traffic + "other"), so cardinality stays bounded no
        # matter how many tenant ids traffic invents — the runtime twin
        # of the JGL010 static rule.
        self.tenant_labels = TenantLabeler()
        self.tenant_requests = c(
            "weaviate_tenant_requests_total",
            "requests admitted to the serving path, by (bounded) tenant",
            ("tenant",))
        self.tenant_shed = c(
            "weaviate_tenant_requests_shed_total",
            "requests shed by admission control, by (bounded) tenant — an "
            "abusive tenant's sheds land on ITS label, not the fleet's",
            ("tenant", "reason"))
        self.tenant_deadline = c(
            "weaviate_tenant_deadline_expired_total",
            "requests that failed fast on an expired deadline in the "
            "serving queue, by (bounded) tenant", ("tenant",))
        self.tenant_queued_rows = g(
            "weaviate_tenant_queued_rows",
            "query rows in the serving pipeline per (bounded) tenant, "
            "admission until lane settle — the occupancy the "
            "tenant_budget cap bounds (queue-only depth is "
            "weaviate_coalescer_queue_depth)",
            ("tenant",))

        # continuous device-performance attribution (monitoring/perf.py):
        # rolling-window roofline gauges + the host-overhead ledger's
        # per-dispatch phase shares. Registered once here (the coalescer
        # pattern); the perf window only touches them inside try/except.
        self.device_mfu = g(
            "weaviate_device_mfu_pct",
            "achieved model FLOPs utilization over the rolling perf "
            "window, percent of platform peak (wall-clock form — the "
            "serving-level number; the device-busy form is in "
            "/debug/perf)")
        self.device_hbm_bw = g(
            "weaviate_device_hbm_bw_pct",
            "achieved HBM bandwidth over the rolling perf window, "
            "percent of platform peak")
        self.device_duty_cycle = g(
            "weaviate_device_duty_cycle",
            "fraction of wall-clock with an in-flight device dispatch "
            "(enqueue->fetch intervals, overlap-merged) — low duty at "
            "high kernel MFU = the orchestration gap")
        self.perf_phase_share = Histogram(
            "weaviate_perf_phase_share",
            "per-dispatch share of the host-overhead ledger "
            "(filter/enqueue/device/gather_hop/hydrate) each stage took",
            ("phase",), registry=r,
            buckets=(0.01, 0.02, 0.05, 0.1, 0.2, 0.3, 0.5, 0.7, 0.9, 1.0))

        # front-door tenant concurrency gate (serving/robustness.py
        # TenantConcurrencyGate): aggregate occupancy + refusals. Per-shed
        # tenant attribution already rides weaviate_tenant_requests_shed_
        # total{reason="concurrency"}; these are the label-free gate-level
        # twins an operator alerts on (ROADMAP item 4 follow-up).
        self.tenant_gate_inflight = g(
            "weaviate_tenant_gate_inflight",
            "requests currently holding a tenant-gate concurrency slot, "
            "summed over tenants")
        self.tenant_gate_shed = c(
            "weaviate_tenant_gate_shed_total",
            "requests refused at the front-door tenant concurrency gate "
            "(also counted per tenant/reason in the shed vecs)")

        # online quality observability (monitoring/quality.py): the shadow
        # recall auditor's rolling estimates + audit accounting. Tier label
        # values come from the costmodel TIER_* enum (bounded; JGL010-
        # clean); the auditor only touches these inside try/except.
        self.recall_at_k = g(
            "weaviate_recall_at_k",
            "EWMA recall@k of shadow-audited live searches vs the exact "
            "host plane, per dispatch tier (1.0 = every audited answer "
            "was exact)", ("tier",))
        self.distance_relerr = g(
            "weaviate_distance_relerr",
            "mean rank-aligned relative distance error of shadow-audited "
            "live searches vs the exact host plane, per dispatch tier",
            ("tier",))
        self.quality_audits = c(
            "weaviate_quality_audits_total",
            "shadow recall audits by outcome (ok / shed = dropped under "
            "the drop-not-queue budget / deadline = host scan over its "
            "audit budget / error)", ("outcome",))
        self.quality_audit_lag = h(
            "weaviate_quality_audit_lag_ms",
            "time between a sampled dispatch's finalize and its audit "
            "completing (how stale the recall estimate runs)")
        self.quality_degraded = c(
            "weaviate_quality_degraded_total",
            "degradation alerts: a tier's EWMA recall crossed below "
            "RECALL_ALERT_THRESHOLD (one increment per transition; the "
            "log line is rate-limited separately)", ("tier",))

        # cheap always-on index health (stamped on the write path by
        # index/tpu.py _update_index_gauges — independent of tracing and
        # auditing, so /debug/index and /metrics report health even with
        # both planes disabled)
        self.vector_index_live = g(
            "weaviate_vector_index_live_count", "live (non-tombstoned) "
            "vectors per shard", ("class_name", "shard_name"))
        self.index_tombstone_fraction = g(
            "weaviate_index_tombstone_fraction",
            "tombstoned fraction of the shard's occupied slots — creeping "
            "growth after deletes is the compaction-debt signal",
            ("class_name", "shard_name"))

        # memory & capacity observability (monitoring/memory.py): the
        # device/host/disk byte ledger's bounded component gauges + the
        # write-path lifecycle + exhaustion alerting. Component label
        # values come from the memory.DEVICE_COMPONENTS/HOST_COMPONENTS/
        # DISK_COMPONENTS taxonomies (bounded; foreign names fold into
        # "other" — JGL010-clean); the ledger only touches these inside
        # try/except.
        self.device_bytes = g(
            "weaviate_device_bytes",
            "HBM bytes the ledger accounts per buffer component "
            "(analytic shape x dtype at snapshot publish — equals the "
            "buffers' nbytes exactly; zero device syncs)", ("component",))
        self.host_bytes = g(
            "weaviate_host_bytes",
            "host RAM bytes the ledger accounts per consumer component "
            "(slot/tombstone mirrors, PQ host rows, staged rows, breaker "
            "fallback rows, auditor rows, allowList cache)", ("component",))
        self.disk_bytes = g(
            "weaviate_disk_bytes",
            "data-volume bytes (used/free) so device/host/disk capacity "
            "read from one dashboard", ("component",))
        self.memory_headroom = g(
            "weaviate_memory_headroom_pct",
            "remaining capacity percentage per scope (device HBM vs the "
            "backend's bytes_limit, host vs MemTotal, disk vs the data "
            "volume) — the number the exhaustion alert thresholds",
            ("scope",))
        self.write_flush = h(
            "weaviate_write_flush_ms",
            "write-path flush/device-write durations (staged rows landing "
            "on device, COW copy included)")
        self.cow_copy_bytes = c(
            "weaviate_cow_copy_bytes_total",
            "host bytes duplicated by copy-on-write so a published "
            "snapshot's pinned arrays are never mutated under a reader")
        self.memory_alerts = c(
            "weaviate_memory_exhaustion_alerts_total",
            "memory-headroom degradation alerts per scope (one increment "
            "per below-threshold transition; the log line is rate-limited "
            "separately)", ("scope",))
        self.memory_drift = g(
            "weaviate_memory_ledger_drift_bytes",
            "allocator-reported bytes_in_use minus the ledger's analytic "
            "per-device total where the backend provides memory_stats() — "
            "a cross-check gauge, never the primary accounting", ("scope",))

        # incident flight recorder + SLO burn-rate engine (monitoring/
        # incidents.py): the ops-event journal's bounded kind counter, the
        # config-declared SLOs' multi-window burn gauges, and the bundle
        # counter. Label values are bounded taxonomies (incidents.EVENT_
        # KINDS / INCIDENT_CLASSES, with foreign values folded to "other";
        # SLO names are built once at engine init from config) — the
        # JGL010 discipline, with JGL013 as the journal's static twin;
        # the incident plane only touches these inside try/except.
        self.ops_events = c(
            "weaviate_ops_events_total",
            "structured ops-journal events by (bounded) kind — breaker "
            "transitions, shed bursts, quality/memory alerts, jit "
            "compiles, device fallbacks, SLO burns (monitoring/"
            "incidents.py)", ("kind",))
        self.slo_burn_rate = g(
            "weaviate_slo_burn_rate",
            "error-budget burn rate per SLO and window (5m fast / 1h "
            "slow): bad-request fraction over the window divided by the "
            "SLO's error budget — 1.0 spends budget exactly at the "
            "sustainable rate", ("slo", "window"))
        self.slo_budget_remaining = g(
            "weaviate_slo_error_budget_remaining",
            "error budget left over the trailing 1h window per SLO "
            "(1.0 = untouched, 0.0 = the hour's budget is gone)",
            ("slo",))
        self.incident_bundles = c(
            "weaviate_incident_bundles_total",
            "flight-recorder bundles written to INCIDENT_DIR, by "
            "(bounded) incident class", ("class",))

        # self-tuning control plane (serving/controller.py): knob names
        # and controller names are FIXED sets (controller.KNOB_NAMES /
        # the four controllers) — bounded by construction, the JGL010
        # discipline; all writes ride the tick thread inside try/except.
        self.controller_brownout_stage = g(
            "weaviate_controller_brownout_stage",
            "current brownout-ladder stage (0 = normal serving, 1 = "
            "tightened admission margins, 2 = shrunken tenant budgets + "
            "scaled Retry-After, 3 = optional work paused)")
        self.controller_knob = g(
            "weaviate_controller_knob",
            "current value of each controller-actuated serving knob "
            "(equals its configured default while unactuated)", ("knob",))
        self.controller_actuations = c(
            "weaviate_controller_actuations_total",
            "knob actuations applied, per controller (brownout / budget "
            "/ lanes / rate)", ("controller",))

        # device-dispatch degradation (graftlint JGL004): every path that
        # silently falls back from the TPU to a host engine counts here, so
        # a fleet serving at CPU speed is visible on a dashboard instead of
        # only in a benchmark regression
        self.device_fallbacks = c(
            "weaviate_device_fallback_total",
            "device dispatches that degraded to a host fallback",
            ("component", "reason"))

    def expose(self) -> bytes:
        """Text exposition (the /metrics handler body)."""
        return generate_latest(self.registry)


_lock = threading.Lock()
_instance: Optional[Metrics] = None


def get_metrics() -> Metrics:
    """Process-wide singleton (GetMetrics, prometheus.go:70)."""
    global _instance
    with _lock:
        if _instance is None:
            _instance = Metrics()
        return _instance


def noop_metrics() -> Metrics:
    """Fresh isolated registry (tests / embedded use)."""
    return Metrics(CollectorRegistry())


# -- device-fallback observability (graftlint JGL004) -------------------------

FALLBACK_LOG_INTERVAL_S = 60.0

_fallback_log_lock = threading.Lock()
_fallback_last_log: dict[tuple[str, str], float] = {}


def record_device_fallback(
    component: str,
    reason: str,
    exc: Optional[BaseException] = None,
    *,
    note: str = "",
    log: bool = True,
    interval: float = FALLBACK_LOG_INTERVAL_S,
) -> bool:
    """Make host degradation observable: ALWAYS increment the fallback
    counter, and log at most once per (component, reason) per `interval`
    seconds so a hot loop that falls back per request cannot flood the log.
    Callers that already emit a richer one-shot message pass log=False and
    still get counted. -> True when a log line was emitted."""
    get_metrics().device_fallbacks.labels(
        component=component, reason=reason).inc()
    if not log:
        return False
    now = time.monotonic()
    with _fallback_log_lock:
        last = _fallback_last_log.get((component, reason))
        if last is not None and now - last < interval:
            return False
        _fallback_last_log[(component, reason)] = now
    detail = f" ({type(exc).__name__}: {exc})" if exc is not None else ""
    logging.getLogger("weaviate_tpu.monitoring.fallback").warning(
        "device dispatch degraded to host fallback: component=%s reason=%s%s%s"
        " — further occurrences are counted in weaviate_device_fallback_total"
        " and logged at most every %.0fs",
        component, reason, detail, f" [{note}]" if note else "", interval)
    return True
